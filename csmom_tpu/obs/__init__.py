"""Run telemetry: structured spans, a metrics registry, a phase timeline.

The last two tunnel windows were diagnosed after the fact from scattered
prints, bench JSON fragments, and the chaos rehearsal log.  This package
is the single answer to "where did the time and the dispatches go in this
run?" — span-based tracing in the Dapper spirit, sized for a capture
pipeline instead of an RPC fleet:

- :mod:`~csmom_tpu.obs.spans` — nestable, thread-safe spans
  (``span("bench.row", shape=...)``) recording monotonic wall time (plus
  device time via the ``profiling.fetch`` device_get pattern), emitted as
  a JSON-lines event stream keyed by a run id.  Cross-process: children
  append to the same stream (CLOCK_MONOTONIC is system-wide on Linux, so
  their timestamps compose on one timeline).
- :mod:`~csmom_tpu.obs.metrics` — a process-wide registry of counters /
  gauges / histograms (rows landed, deadline margin, dispatch counts; the
  AOT cache hit/miss counters fold in from ``profiling.compile_stats``),
  snapshotted into every BENCH record.
- :mod:`~csmom_tpu.obs.timeline` — assembles the event stream into a
  per-run ``TELEMETRY_<run>.json`` sidecar (phases: warmup, probe,
  compile, row, land, other) that ``chaos.invariants`` schema-validates
  like every other committed artifact, and renders it as a text flame
  summary (``csmom timeline <run>``).
- :mod:`~csmom_tpu.obs.trace` — PER-REQUEST tracing across the serving
  fabric: a trace context minted at admission and threaded through the
  queue, batcher, engine dispatch, and across the router→worker process
  boundary (stitchable span halves over ``serve/proto.py``); telescoping
  stage clocks whose sum reconciles with the request wall by schema;
  closed trace books landing as ``TRACE_<run>.json`` (``csmom trace``
  renders the decomposition).
- :mod:`~csmom_tpu.obs.memstats` — the device-memory axis: per-shape
  ``compiled.memory_analysis()`` bytes captured during the AOT pass,
  folded into metrics snapshots (hence the sidecar) and the warmup
  report.
- :mod:`~csmom_tpu.obs.ledger` / :mod:`~csmom_tpu.obs.regress` — the
  CROSS-run half: ingest every committed artifact into a normalized,
  provenance-aware per-metric trajectory, and turn raw repeat samples
  into block-bootstrap CI regression verdicts (``csmom ledger
  show/diff/gate``).  Single-run telemetry says where this run's time
  went; the ledger says whether this run moved the trajectory.

Like the chaos harness, the whole layer is ZERO-COST when disarmed: with
no collector armed, ``span()`` returns a shared no-op singleton and
``metric.inc()`` is one global load + compare — no allocation, no I/O
(tested in tests/test_obs.py, mirroring the chaos unarmed contract).
Arming is explicit (:func:`~csmom_tpu.obs.spans.arm`) or env-driven
(``CSMOM_TELEMETRY`` = an event-stream path, ``1`` for in-memory, ``0``
to force off), so the measurement path never pays for observability it
did not ask for.

Nothing in these modules imports jax (or numpy) — but reaching them runs
the ``csmom_tpu`` package ``__init__`` (which does).  The bench
supervisor therefore imports this package LAZILY and only when armed: a
disarmed supervisor (``CSMOM_TELEMETRY=0``) stays package-import-free,
and an armed one pays the ~1 s package import once, before its first
probe — never inside a measured interval.
"""

from csmom_tpu.obs import (
    ledger,
    memstats,
    metrics,
    regress,
    spans,
    timeline,
    trace,
)
from csmom_tpu.obs.spans import (
    arm,
    arm_from_env,
    arm_policy,
    armed,
    disarm,
    point,
    span,
)

__all__ = [
    "arm",
    "arm_from_env",
    "arm_policy",
    "armed",
    "disarm",
    "ledger",
    "memstats",
    "metrics",
    "point",
    "regress",
    "span",
    "spans",
    "timeline",
    "trace",
]
