"""Fleet observatory: continuous cross-process metrics time series,
kill-window capacity accounting, and demand telemetry for the fabric.

``SERVE_FABRIC_r19.json`` says "p99 through the kill window was 593 ms"
and the ROADMAP *attributes* that to capacity loss while a SIGKILLed
worker's replacement re-warms — but the attribution was a narrative,
because nothing recorded fleet capacity, queue depth, or per-class
demand OVER TIME.  This module is the measurement layer: every fabric
process — worker, router replica, and the loadgen host that carries the
supervisors — samples its own ``obs/metrics.py`` registry on a fixed
monotonic cadence into **snapshot deltas** (sequence-numbered,
process-identity-stamped, counters monotone by construction, see
``metrics.snapshot_delta``) and streams them to a per-run aggregator
over the r19 channel layer via the lifecycle ``stats_stream`` op —
persistent channel, never the request hot path, and chaos-free by
construction (``serve.transport`` faults fire only for ``score``).

The aggregator assembles bounded ring-buffer time series keyed
``(process, metric)`` plus per-process stream books, and the run lands a
closed-world ``FLEET_<run>.json`` artifact carrying:

- per-second per-class offered/admitted/served demand (the
  :class:`DemandBook`), reconciling with the serve request books BY
  SCHEMA (``chaos/invariants.py`` kind ``fleet``);
- queue depth and in-flight occupancy per worker (gauge series);
- worker lifecycle walls — spawn→bind→warm→ready, one sample per
  (re)spawn, the ``worker-ready-wall`` ledger row ROADMAP item 2 names;
- a **kill-window capacity account**: effective worker-seconds available
  vs nominal across the run, split into the kill windows (SIGKILL →
  replacement ready) and steady state, so the r19 residual-tail claim
  becomes the gate-able ``fleet_kill_window_capacity_loss_frac``.

**Closed stream books**: every process that ever streamed a delta is
closed with a reason.  A clean emitter sends a ``fin`` frame at
shutdown; a SIGKILLed emitter cannot, so its connection's EOF closes the
series as a reason-closed gap ("stream severed ...") — never a silent
truncation.  Frame sequence numbers make dropped frames visible the
same way (``seq_gaps``), because a counter series assembled from deltas
must say when deltas went missing.

Zero-cost disarmed (the spans/trace discipline, pinned by tests): with
no emitter armed this module's hooks are one global load + compare, the
serve hot path is untouched, and nothing samples, dials, or locks.
Armed, the sampling runs on its own daemon thread and every send
failure degrades to a counted drop — observation must never cost the
run it observes.

Env contract (how fabric processes join one run's observatory):

- ``CSMOM_FLEET``            aggregator address (``unix:`` path or
  ``tcp:host:port``); unset/empty/``0`` = disarmed.
- ``CSMOM_FLEET_RUN``        run id stamped on every frame.
- ``CSMOM_FLEET_CADENCE_S``  sampling cadence (default 0.25 s).

Stdlib-only and ``mono_now_s``-only (clock-discipline pins this module
into the serve timing tier): the series timestamps, the demand buckets,
and the capacity account live on the SAME clock the queue expires on
and the loadgen measures on — on Linux CLOCK_MONOTONIC is system-wide,
so per-process stamps compose onto one timeline.
"""

from __future__ import annotations

import math
import os
import threading
from collections import deque

from csmom_tpu.obs import metrics as _metrics
from csmom_tpu.obs import spans as _spans
from csmom_tpu.utils.deadline import mono_now_s

__all__ = [
    "DEFAULT_CADENCE_S",
    "ENV_ADDR",
    "ENV_CADENCE",
    "ENV_RUN",
    "SCHEMA_VERSION",
    "SERIES_CAP",
    "FleetAggregator",
    "FleetEmitter",
    "absolute_events",
    "arm",
    "arm_emitter_from_env",
    "armed",
    "build_artifact",
    "capacity_account",
    "current_aggregator",
    "demand",
    "disarm",
    "disarm_emitter",
    "lifecycle_walls",
    "open_demand_window",
]

SCHEMA_VERSION = 1

ENV_ADDR = "CSMOM_FLEET"
ENV_RUN = "CSMOM_FLEET_RUN"
ENV_CADENCE = "CSMOM_FLEET_CADENCE_S"

DEFAULT_CADENCE_S = 0.25

# ring bound per (process, metric) series: at the default cadence this
# holds 150 s of samples — beyond it the OLDEST points roll off (the
# books keep the totals), so a long soak costs constant memory
SERIES_CAP = 600

# bound on DISTINCT series: a runaway metric-name generator must fill a
# counter ("series_dropped"), never the aggregator's memory
MAX_SERIES = 4096

# one stats_stream round trip's budget — an aggregator that cannot ack
# within this is treated as gone and the frame is counted dropped
FRAME_TIMEOUT_S = 2.0

# the armed aggregator / emitter, or None.  Module-global on purpose
# (the spans discipline): every disarmed hook is one load + compare.
_AGGREGATOR = None
_EMITTER = None


def _proc_name(role: str, slot=None) -> str:
    # pid-qualified so a SIGKILLed worker's REPLACEMENT (same role, same
    # slot, new process) opens its own stream book instead of writing
    # its fin over the victim's severed close reason — each incarnation
    # is its own reason-closed series
    base = f"{role}:{slot}" if slot is not None else str(role)
    return f"{base}@{os.getpid()}"


# ---------------------------------------------------------------- emitter ---

class FleetEmitter:
    """This process's registry sampler: one daemon thread, one
    persistent channel to the aggregator, one frame per cadence tick.

    Every frame carries the delta since the previous tick plus this
    emitter's own frame sequence number — a send that fails consumes
    its sequence number anyway, so the aggregator's ``seq_gaps`` book
    records exactly how many deltas never arrived.  A dead aggregator
    costs one counted drop per tick (bounded by ``FRAME_TIMEOUT_S``),
    never a stalled serving thread: sampling runs entirely off the
    request path.
    """

    def __init__(self, address: str, run_id: str, role: str, slot=None,
                 cadence_s: float = DEFAULT_CADENCE_S):
        self.address = address
        self.run_id = run_id
        self.role = str(role)
        self.slot = slot
        self.proc = _proc_name(role, slot)
        self.cadence_s = float(cadence_s)
        self.dropped = 0
        self._seq = 0
        self._prev = None
        self._channel = None
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> "FleetEmitter":
        _metrics.set_identity(self.role, self.slot)
        # the registry only accumulates while a spans collector is armed
        # (the zero-cost-disarmed contract); a fleet-armed process that
        # is otherwise telemetry-dark arms an in-memory collector so its
        # counters exist to sample
        if _spans._COLLECTOR is None:
            _spans.arm(None, proc=self.role)
        self._prev = _metrics.snapshot(include_compile=False)
        # hello frame, synchronously, before the cadence loop exists:
        # the stream book opens the moment the process arms, so a
        # SIGKILL at ANY later instant severs an OPEN stream — a victim
        # that dies inside the first cadence interval must not read as
        # "never joined"
        self._tick()
        self._thread = threading.Thread(
            target=self._loop, name=f"fleet-emitter-{self.proc}",
            daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        next_t = mono_now_s() + self.cadence_s
        while not self._stop.wait(max(0.0, next_t - mono_now_s())):
            next_t += self.cadence_s
            self._tick()

    def _tick(self, fin: str | None = None) -> bool:
        cur = _metrics.snapshot(include_compile=False)
        try:
            delta = _metrics.snapshot_delta(self._prev, cur)
        except ValueError:
            # a reset registry mid-run (tests): restart the delta chain
            # from here rather than emit a splice
            self._prev = cur
            return False
        self._prev = cur
        self._seq += 1
        frame = {
            "op": "stats_stream",
            "run": self.run_id,
            "proc": self.proc,
            "role": self.role,
            "slot": self.slot,
            "pid": os.getpid(),
            "seq": self._seq,
            "t_s": round(mono_now_s(), 6),
            "counters": delta["counters"],
            "gauges": delta["gauges"],
            "histograms": delta["histograms"],
            "dropped": self.dropped,
        }
        if fin is not None:
            frame["fin"] = fin
        return self._send(frame)

    def _send(self, frame: dict) -> bool:
        from csmom_tpu.serve import proto

        for _ in (0, 1):  # one transparent redial, then count the drop
            ch = self._channel
            if ch is None or not ch.alive:
                try:
                    sock = proto.connect(self.address, FRAME_TIMEOUT_S)
                    ch = self._channel = proto.Channel(
                        self.address, sock,
                        frame_deadline_s=FRAME_TIMEOUT_S)
                except (OSError, ValueError):
                    self._channel = None
                    break
            try:
                ch.request(frame, None, timeout_s=FRAME_TIMEOUT_S)
                return True
            except Exception:
                try:
                    ch.close("fleet emitter redial")
                except Exception:
                    pass
                self._channel = None
        self.dropped += 1
        return False

    def stop(self, reason: str = "emitter stopped") -> None:
        """Final delta + ``fin`` frame, then close the channel.  A
        process that never reaches here (SIGKILL) is exactly the
        severed-stream case the aggregator reason-closes on EOF."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.cadence_s + 1.0)
        self._tick(fin=reason)
        ch, self._channel = self._channel, None
        if ch is not None:
            try:
                ch.close("fleet emitter stopped")
            except Exception:
                pass


# ------------------------------------------------------------- aggregator ---

class FleetAggregator:
    """The per-run sink: listener, stream books, ring-buffer series,
    and the demand book.

    One leaf lock guards all mutable state and never calls out while
    held (the lock-order audit's acyclic contract).  Connections are
    served by the channel layer's own loop; a connection that ends
    without a ``fin`` reason-closes every process that streamed on it.
    """

    def __init__(self, run_id: str, transport: str = "unix",
                 cadence_s: float = DEFAULT_CADENCE_S,
                 scratch_dir: str | None = None,
                 series_cap: int = SERIES_CAP):
        self.run_id = run_id
        self.transport = transport
        self.cadence_s = float(cadence_s)
        self.series_cap = int(series_cap)
        self.address: str | None = None
        self.t0_s = mono_now_s()
        self._scratch_dir = scratch_dir
        self._srv = None
        self._accept_thread = None
        self._conn_threads: list = []
        self._stopping = False
        self._lock = threading.Lock()
        self._series: dict = {}     # (proc, metric) -> series state
        self._procs: dict = {}      # proc -> stream book
        self.frames = 0
        self.frames_malformed = 0
        self.series_dropped = 0
        # demand book: armed only inside the measurement window, so
        # pre-run self-probes never pollute the reconciliation
        self._demand_open = False
        self._demand_t0 = None
        self._demand_per_s: dict = {}   # int bucket -> cls -> event -> n
        self._demand_totals: dict = {}  # cls -> event -> n

    # ----------------------------------------------------------- serving --

    def start(self) -> "FleetAggregator":
        import tempfile

        from csmom_tpu.serve import proto

        if self.transport == "tcp":
            self.address = f"tcp:127.0.0.1:{proto.free_tcp_port()}"
        else:
            d = self._scratch_dir or tempfile.mkdtemp(prefix="csmom-fleet-")
            self.address = os.path.join(d, "aggregator.sock")
        self._srv = proto.listen(self.address)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-aggregator-accept",
            daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # listener closed: shutdown
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="fleet-aggregator-conn", daemon=True)
            t.start()
            self._conn_threads.append(t)

    def _serve_conn(self, conn) -> None:
        from csmom_tpu.serve import proto

        procs_on_conn: set = set()
        fin_on_conn: set = set()

        def handler(obj, arrays):
            if obj.get("op") != "stats_stream":
                return {"ok": False,
                        "error": f"unknown op {obj.get('op')!r}"}, None
            ok, proc, fin = self._ingest(obj)
            if proc is not None:
                procs_on_conn.add(proc)
                if fin:
                    fin_on_conn.add(proc)
            return {"ok": ok, "seq": obj.get("seq")}, None

        try:
            proto.serve_connection(conn, handler,
                                   idle_timeout_s=proto.SERVE_IDLE_S)
        finally:
            # EOF/error without a fin is the SIGKILL signature: close
            # the stream as a reason-closed gap, never silently
            for p in procs_on_conn - fin_on_conn:
                self.close_proc(
                    p, "stream severed: connection lost without fin "
                       "(peer killed or crashed)")

    # ----------------------------------------------------------- ingest ---

    def _ingest(self, frame: dict):
        proc = frame.get("proc")
        seq = frame.get("seq")
        t_s = frame.get("t_s")
        if (not isinstance(proc, str) or not isinstance(seq, int)
                or not isinstance(t_s, (int, float))):
            with self._lock:
                self.frames_malformed += 1
            return False, None, False
        fin = frame.get("fin")
        with self._lock:
            self.frames += 1
            book = self._procs.get(proc)
            if book is None:
                book = self._procs[proc] = {
                    "role": frame.get("role"),
                    "slot": frame.get("slot"),
                    "pid": frame.get("pid"),
                    "first_seq": seq,
                    "last_seq": seq - 1,
                    "samples": 0,
                    "seq_gaps": 0,
                    "dropped": 0,
                    "t_first_s": t_s,
                    "t_last_s": t_s,
                    "closed": False,
                    "close_reason": None,
                }
            gap = seq - book["last_seq"] - 1
            if gap > 0:
                book["seq_gaps"] += gap
            book["last_seq"] = max(book["last_seq"], seq)
            book["samples"] += 1
            book["dropped"] = max(book["dropped"],
                                  int(frame.get("dropped") or 0))
            book["t_last_s"] = t_s
            for name, d in (frame.get("counters") or {}).items():
                self._append(proc, name, "counter", t_s, d)
            for name, v in (frame.get("gauges") or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self._append(proc, name, "gauge", t_s, v)
            if fin:
                book["closed"] = True
                book["close_reason"] = f"fin: {fin}"[:160]
        return True, proc, bool(fin)

    def _append(self, proc: str, metric: str, kind: str, t_s: float,
                v) -> None:
        # caller holds self._lock
        key = (proc, metric)
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= MAX_SERIES:
                self.series_dropped += 1
                return
            s = self._series[key] = {
                "kind": kind, "cum": 0.0,
                "t": deque(maxlen=self.series_cap),
                "v": deque(maxlen=self.series_cap),
            }
        if kind == "counter":
            # sum of non-negative deltas: monotone BY CONSTRUCTION
            s["cum"] += max(0.0, float(v))
            v = s["cum"]
        s["t"].append(float(t_s))
        s["v"].append(float(v))

    # ----------------------------------------------------------- demand ---

    def open_demand_window(self) -> None:
        """Start counting demand (call AFTER self-probes, so the
        reconciliation against the serve request books is exact)."""
        with self._lock:
            self._demand_open = True
            self._demand_t0 = mono_now_s()

    def note_demand(self, event: str, slo_class: str) -> None:
        t = mono_now_s()
        with self._lock:
            if not self._demand_open:
                return
            bucket = int(t - self._demand_t0)
            cls = str(slo_class)
            per = self._demand_per_s.setdefault(bucket, {})
            cb = per.setdefault(cls, {})
            cb[event] = cb.get(event, 0) + 1
            tot = self._demand_totals.setdefault(cls, {})
            tot[event] = tot.get(event, 0) + 1

    def demand_offered_in(self, t0_abs: float, t1_abs: float) -> int:
        """Offered arrivals inside an absolute-mono window, counted at
        the demand book's one-second bucket granularity."""
        with self._lock:
            if self._demand_t0 is None:
                return 0
            b0 = int(t0_abs - self._demand_t0)
            b1 = int(t1_abs - self._demand_t0)
            n = 0
            for b, per in self._demand_per_s.items():
                if b0 <= b <= b1:
                    for cb in per.values():
                        n += cb.get("offered", 0)
            return n

    def demand_recent_rps(self, horizon_s: float = 3.0,
                          event: str = "offered",
                          slo_class: str | None = None) -> float:
        """Trailing arrival rate (events/s) over ``horizon_s``, read
        from the demand book's one-second buckets — the autoscaler's
        control input (``serve/fleet.py``).  ``slo_class`` narrows to a
        single class (for quota tuning); default sums all classes."""
        now = mono_now_s()
        with self._lock:
            if self._demand_t0 is None or not self._demand_open:
                return 0.0
            width = max(1, int(math.ceil(horizon_s)))
            now_b = int(now - self._demand_t0)
            b0 = now_b - width + 1
            n = 0
            for b, per in self._demand_per_s.items():
                if b0 <= b <= now_b:
                    for cls, cb in per.items():
                        if slo_class is not None and cls != slo_class:
                            continue
                        n += cb.get(event, 0)
            return n / float(width)

    # ---------------------------------------------------------- closing ---

    def close_proc(self, proc: str, reason: str) -> None:
        with self._lock:
            book = self._procs.get(proc)
            if book is not None and not book["closed"]:
                book["closed"] = True
                book["close_reason"] = str(reason)[:160]

    def close_all(self, reason: str = "run-end") -> None:
        with self._lock:
            for book in self._procs.values():
                if not book["closed"]:
                    book["closed"] = True
                    book["close_reason"] = str(reason)[:160]

    def stop(self) -> None:
        self._stopping = True
        srv, self._srv = self._srv, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass
        if self.address and not self.address.startswith("tcp:"):
            try:
                os.unlink(self.address)
            except OSError:
                pass

    # ---------------------------------------------------------- reading ---

    def snapshot(self, t0_s: float | None = None) -> dict:
        """Series + books as one JSON-ready dict, timestamps shifted to
        be relative to ``t0_s`` (default: aggregator start)."""
        base = self.t0_s if t0_s is None else float(t0_s)
        with self._lock:
            points = {}
            for (proc, metric), s in sorted(self._series.items()):
                points[f"{proc}|{metric}"] = {
                    "proc": proc,
                    "metric": metric,
                    "kind": s["kind"],
                    "t_s": [round(t - base, 3) for t in s["t"]],
                    "v": [round(v, 6) for v in s["v"]],
                }
            processes = {}
            for proc, book in sorted(self._procs.items()):
                processes[proc] = dict(
                    book,
                    t_first_s=round(book["t_first_s"] - base, 3),
                    t_last_s=round(book["t_last_s"] - base, 3),
                )
            per_second = []
            for b in sorted(self._demand_per_s):
                per_second.append({"t_s": b,
                                   **{cls: dict(ev) for cls, ev in
                                      sorted(self._demand_per_s[b].items())}})
            demand_t0 = (None if self._demand_t0 is None
                         else round(self._demand_t0 - base, 3))
            return {
                "books": {
                    "procs_opened": len(self._procs),
                    "procs_closed": sum(1 for b in self._procs.values()
                                        if b["closed"]),
                    "frames": self.frames,
                    "frames_malformed": self.frames_malformed,
                    "seq_gaps": sum(b["seq_gaps"]
                                    for b in self._procs.values()),
                    "frames_dropped_by_emitters": sum(
                        b["dropped"] for b in self._procs.values()),
                    "series_count": len(self._series),
                    "series_dropped": self.series_dropped,
                },
                "processes": processes,
                "points": points,
                "demand": {
                    "t0_s": demand_t0,
                    "classes": {cls: dict(ev) for cls, ev in
                                sorted(self._demand_totals.items())},
                    "per_second": per_second,
                },
            }


# ---------------------------------------------------------------- arming ----

def armed() -> bool:
    return _AGGREGATOR is not None


def current_aggregator() -> FleetAggregator | None:
    return _AGGREGATOR


def arm(run_id: str, transport: str = "unix",
        cadence_s: float | None = None,
        scratch_dir: str | None = None) -> FleetAggregator:
    """Arm fleet capture for this run: start the aggregator, export the
    env contract so processes spawned after this call join, and arm a
    local emitter for the loadgen/supervisor host process itself."""
    global _AGGREGATOR
    if cadence_s is None:
        raw = os.environ.get(ENV_CADENCE, "")
        cadence_s = float(raw) if raw else DEFAULT_CADENCE_S
    disarm(reason="re-armed")
    agg = FleetAggregator(run_id, transport=transport,
                          cadence_s=cadence_s,
                          scratch_dir=scratch_dir).start()
    _AGGREGATOR = agg
    os.environ[ENV_ADDR] = agg.address
    os.environ[ENV_RUN] = run_id
    os.environ[ENV_CADENCE] = str(cadence_s)
    _arm_local_emitter("loadgen")
    return agg


def _arm_local_emitter(role: str, slot=None) -> FleetEmitter:
    global _EMITTER
    em = FleetEmitter(os.environ[ENV_ADDR],
                      os.environ.get(ENV_RUN) or "unnamed",
                      role, slot,
                      cadence_s=float(os.environ.get(ENV_CADENCE)
                                      or DEFAULT_CADENCE_S)).start()
    _EMITTER = em
    return em


def arm_emitter_from_env(role: str, slot=None) -> FleetEmitter | None:
    """Child-process side of the env contract: join the run's aggregator
    or stay disarmed (``CSMOM_FLEET`` unset/empty/``0``).  Called from
    worker/router mains — a send-only hook, never the request path."""
    addr = os.environ.get(ENV_ADDR, "")
    if not addr or addr == "0":
        return None
    return _arm_local_emitter(role, slot)


def disarm_emitter(reason: str = "emitter stopped") -> None:
    """Fin-close and drop this process's emitter (clean shutdown; a
    SIGKILL never reaches here, which is the point of fin)."""
    global _EMITTER
    em, _EMITTER = _EMITTER, None
    if em is not None:
        em.stop(reason)


def disarm(reason: str = "run-end") -> None:
    """Stop the local emitter (fin), close every still-open stream book
    with ``reason``, stop the aggregator, and retract the env contract
    so later spawns do not dial a dead socket."""
    global _AGGREGATOR
    disarm_emitter(reason)
    agg, _AGGREGATOR = _AGGREGATOR, None
    if agg is not None:
        agg.close_all(reason)
        agg.stop()
    for k in (ENV_ADDR, ENV_RUN, ENV_CADENCE):
        os.environ.pop(k, None)


def open_demand_window() -> None:
    """Start demand counting on the armed aggregator (no-op disarmed)."""
    agg = _AGGREGATOR
    if agg is not None:
        agg.open_demand_window()


def demand(event: str, slo_class: str) -> None:
    """Note one demand event (``offered`` / ``admitted`` / ``served``)
    for an SLO class.  Disarmed: one global load + compare — the serve
    submit path pays nothing when fleet capture is off (pinned)."""
    agg = _AGGREGATOR
    if agg is None:
        return
    agg.note_demand(event, slo_class)


# ------------------------------------------------------- capacity account ---

def absolute_events(events: list, t0_mono_s: float) -> list:
    """Supervisor events (``t_s`` relative to the supervisor's start)
    shifted onto the absolute monotonic timeline the series live on."""
    return [dict(e, t_s=e["t_s"] + t0_mono_s) for e in events]


def lifecycle_walls(events: list) -> list:
    """One sample per (re)spawn: every ``ready`` event's spawn→ready
    wall plus the worker-reported bind/warm decomposition (see
    ``serve/supervisor.py``).  ``kind`` carries the spawn regime
    (cold / respawn / roll / spare-promotion) so fast-path samples gate
    against their own kind instead of averaging across regimes."""
    out = []
    for e in events:
        if e.get("event") != "ready":
            continue
        out.append({
            "worker_id": e.get("worker_id"),
            "generation": e.get("generation"),
            "kind": e.get("spawn_kind") or "cold",
            "wall_s": e.get("wall_s"),
            "walls": e.get("walls"),
        })
    return out


def _overlap(a0: float, a1: float, b0: float, b1: float) -> float:
    return max(0.0, min(a1, b1) - max(a0, b0))


def capacity_account(events: list, n_slots: int, window: tuple) -> dict:
    """Effective worker-seconds available vs nominal over ``window``
    (absolute-mono ``(t0, t1)``), from supervisor lifecycle events on
    the same timeline (:func:`absolute_events`).

    A slot is AVAILABLE from each ``ready`` until its next
    ``chaos_kill``/``death`` (whichever stamps first).  Each such down
    transition opens a **kill window** [kill, victim's next ready] —
    the re-warm interval the r19 tail rode (a monitor-detected death
    digs the same hole as an explicit chaos kill, and the death notice
    trailing a booked kill never double-opens).  The account is computed
    purely from measured lifecycle stamps: no model, no imputation —
    steady-state loss ≈ 0 is a *result*, not an assumption.

    Hot spares (``serve/fleet.py``) enter the account as WARM-CAPACITY
    reserve intervals: ``spare_ready`` opens one, and any of
    ``spare_promoted``/``spare_death``/``spare_stopped`` closes it.
    Spare events never open kill windows (a parked spare dying costs no
    serving capacity — it was never routed), and a reserve covering a
    kill window offsets the victim's hole: the account measures warm
    capacity the fleet *possesses*.  The routable gap a client could
    feel is gated separately, by the promotion-kind ready wall and the
    in-window demand/latency criteria.
    """
    t0, t1 = float(window[0]), float(window[1])
    per_slot: dict = {}
    spare_marks: dict = {}
    for e in events:
        wid = e.get("worker_id")
        ev = e.get("event")
        if wid is None:
            continue
        if ev in ("ready", "chaos_kill", "death"):
            per_slot.setdefault(wid, []).append((float(e["t_s"]), ev))
        elif ev in ("spare_ready", "spare_promoted", "spare_death",
                    "spare_stopped"):
            spare_marks.setdefault(wid, []).append((float(e["t_s"]), ev))
    intervals = []       # (start, end) of availability, per slot merged
    spare_intervals = []
    for wid, marks in spare_marks.items():
        marks.sort()
        up_since = None
        for t, ev in marks:
            if ev == "spare_ready":
                if up_since is None:
                    up_since = t
            elif up_since is not None:
                spare_intervals.append((up_since, t))
                up_since = None
        if up_since is not None:
            spare_intervals.append((up_since, t1))
    intervals.extend(spare_intervals)
    kill_windows = []
    for wid, marks in per_slot.items():
        marks.sort()
        up_since = None
        for t, ev in marks:
            if ev == "ready":
                if up_since is None:
                    up_since = t
                for kw in kill_windows:
                    if kw["worker_id"] == wid and kw["t_ready_s"] is None \
                            and t > kw["t_kill_s"]:
                        kw["t_ready_s"] = t
                        break
            else:
                if up_since is not None:
                    intervals.append((up_since, t))
                    up_since = None
                # chaos_kill opens the window, and so does a
                # monitor-detected `death` (an organic crash — or a
                # fault-plan self-kill inside the worker — digs the same
                # capacity hole); the monitor's death notice for an
                # already-booked victim must not double-open it
                if not any(kw["worker_id"] == wid
                           and kw["t_ready_s"] is None
                           for kw in kill_windows):
                    kill_windows.append({"worker_id": wid, "t_kill_s": t,
                                         "t_ready_s": None})
        if up_since is not None:
            intervals.append((up_since, t1))
    # an unreplaced victim's window runs to the end of the run — honest:
    # the capacity never came back inside the measured window
    for kw in kill_windows:
        kw["open_ended"] = kw["t_ready_s"] is None
        if kw["t_ready_s"] is None:
            kw["t_ready_s"] = t1
    nominal = max(0.0, (t1 - t0)) * n_slots
    available = sum(_overlap(a, b, t0, t1) for a, b in intervals)
    # merge kill windows into a disjoint union before accounting, so two
    # overlapping victims do not double-count the same wall
    spans = sorted((max(kw["t_kill_s"], t0), min(kw["t_ready_s"], t1))
                   for kw in kill_windows)
    merged = []
    for a, b in spans:
        if b <= a:
            continue
        if merged and a <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], b))
        else:
            merged.append((a, b))
    kw_nominal = sum((b - a) for a, b in merged) * n_slots
    kw_available = sum(_overlap(ia, ib, a, b)
                       for ia, ib in intervals for a, b in merged)
    ss_nominal = nominal - kw_nominal
    ss_available = available - kw_available
    for kw in kill_windows:
        a = max(kw["t_kill_s"], t0)
        b = min(kw["t_ready_s"], t1)
        width = max(0.0, b - a)
        avail = sum(_overlap(ia, ib, a, b) for ia, ib in intervals)
        kw.update(
            t_kill_s=round(kw["t_kill_s"] - t0, 3),
            t_ready_s=round(kw["t_ready_s"] - t0, 3),
            width_s=round(width, 3),
            # spare reserve can push in-window available past nominal;
            # loss never reads negative (warm capacity ≥ nominal means
            # the hole was covered, not that capacity was conjured)
            loss_frac=(round(max(0.0, 1.0 - avail / (width * n_slots)), 4)
                       if width > 0 and n_slots else 0.0),
        )
    spare_reserve = sum(_overlap(a, b, t0, t1) for a, b in spare_intervals)
    return {
        "n_slots": n_slots,
        "window_s": round(t1 - t0, 3),
        "nominal_worker_s": round(nominal, 3),
        "available_worker_s": round(min(available, nominal), 3),
        "spare_reserve_worker_s": round(spare_reserve, 3),
        "kill_windows": kill_windows,
        "kill_window_loss_frac": (
            round(max(0.0, 1.0 - kw_available / kw_nominal), 4)
            if kw_nominal > 0 else 0.0),
        "steady_state_loss_frac": (
            round(max(0.0, 1.0 - ss_available / ss_nominal), 4)
            if ss_nominal > 0 else 0.0),
    }


# --------------------------------------------------------------- artifact ---

def _series_quantiles(values: list) -> dict:
    if not values:
        return {"p50": None, "p95": None, "max": None}
    s = sorted(values)

    def pick(q):
        return s[max(0, math.ceil(q * len(s)) - 1)]

    return {"p50": pick(0.50), "p95": pick(0.95), "max": s[-1]}


def build_artifact(agg: FleetAggregator, run_id: str, *,
                   requests: dict | None = None,
                   worker_events: list | None = None,
                   router_events: list | None = None,
                   n_workers: int | None = None,
                   n_routers: int | None = None,
                   window: tuple | None = None,
                   channels: dict | None = None,
                   fresh_compiles=None,
                   platform: str | None = None,
                   workload: str | None = None,
                   elastic: dict | None = None,
                   extra: dict | None = None) -> dict:
    """The FLEET artifact (kind ``fleet``, schema v1): closed stream
    books + ring-buffer series + demand book + lifecycle walls + the
    kill-window capacity account, plus the matching serve run's request
    book so demand reconciles BY SCHEMA (offered == admitted ==
    ``requests.admitted``; served == ``requests.served``).

    ``window`` is the measured load window in absolute mono seconds;
    ``worker_events``/``router_events`` are supervisor events already on
    that timeline (:func:`absolute_events`).
    """
    t0 = agg.t0_s if window is None else float(window[0])
    t1 = mono_now_s() if window is None else float(window[1])
    snap = agg.snapshot(t0_s=t0)
    worker_events = worker_events or []
    router_events = router_events or []
    walls = lifecycle_walls(worker_events)
    wall_samples = [w["wall_s"] for w in walls
                    if isinstance(w.get("wall_s"), (int, float))]
    capacity = capacity_account(worker_events, n_workers or 0, (t0, t1))
    router_capacity = (capacity_account(router_events, n_routers or 0,
                                        (t0, t1))
                       if router_events else None)
    for kw in capacity["kill_windows"]:
        kw["demand_offered_in_window"] = agg.demand_offered_in(
            t0 + kw["t_kill_s"], t0 + kw["t_ready_s"])
    occupancy: dict = {}
    for key, s in snap["points"].items():
        if s["metric"] in ("serve.queue_depth", "serve.in_flight"):
            occ = occupancy.setdefault(s["proc"], {})
            occ[s["metric"].split(".", 1)[1]] = _series_quantiles(s["v"])
    loss = capacity["kill_window_loss_frac"]
    # split the ready walls by spawn regime: a spare promotion gating
    # against the cold-spawn distribution (or vice versa) is a lie
    walls_by_kind: dict = {}
    for w in walls:
        if isinstance(w.get("wall_s"), (int, float)):
            kind = str(w.get("kind") or "cold")
            walls_by_kind.setdefault(kind, []).append(round(w["wall_s"], 4))
    kind_samples = {
        "fleet_worker_ready_wall_%s_s"
        % kind.replace("spare-promotion", "promotion").replace("-", "_"):
        samples
        for kind, samples in sorted(walls_by_kind.items())
    }
    ex = {
        "platform": platform,
        "workload": workload,
        "samples": {
            "fleet_worker_ready_wall_s": [
                round(w, 4) for w in wall_samples],
            **kind_samples,
            "fleet_kill_window_capacity_loss_frac": [
                kw["loss_frac"] for kw in capacity["kill_windows"]],
        },
        **(extra or {}),
    }
    return {
        "kind": "fleet",
        "schema_version": SCHEMA_VERSION,
        "run_id": run_id,
        "metric": "fleet_kill_window_capacity_loss_frac",
        "value": loss,
        "unit": "frac",
        "vs_baseline": 1.0,
        "cadence_s": agg.cadence_s,
        "window_s": round(t1 - t0, 3),
        "series": {
            "books": snap["books"],
            "processes": snap["processes"],
            "points": snap["points"],
        },
        "demand": snap["demand"],
        "occupancy": occupancy,
        "lifecycle": {
            "ready_walls_s": [round(w, 4) for w in wall_samples],
            "events": walls,
        },
        "capacity": capacity,
        "router_capacity": router_capacity,
        "elastic": dict(elastic) if elastic else None,
        "requests": dict(requests) if requests else None,
        "channels": dict(channels) if channels else None,
        "compile": {
            "in_window_fresh_compiles": fresh_compiles,
            "note": "copied from the driven serve run: the capture "
                    "window IS the serving window, so 0 here means no "
                    "fresh compile hid inside any kill window",
        },
        "extra": ex,
    }
