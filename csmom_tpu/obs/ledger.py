"""Cross-run perf ledger: the committed artifacts as ONE trajectory.

Every round lands evidence at the repo root — ``BENCH_rNN.json`` driver
captures, ``BENCH_FULL_rNN.json`` full records, ``MULTICHIP_*`` /
``MULTIHOST_*`` / ``HISTRANK_*`` / ``PHASES_*`` captures,
``TELEMETRY_rNN.json`` sidecars, ``SERVE_rNN.json`` signal-service
load records — and until now the *trajectory* across
them lived only as hand-written ROADMAP prose.  This module ingests the
whole heterogeneous family (schema contract:
:mod:`csmom_tpu.chaos.invariants` — the same ``detect_kind``/``validate``
the rehearsal and the tier-1 sweep use) into normalized per-metric
:class:`Row`\\ s that a regression gate can diff mechanically.

Provenance discipline is the point.  Every row carries its platform,
device kind, and workload fingerprint, and two rows are only comparable
when all three match (:meth:`Row.key`): a CPU-fallback wall never
silently compares against a TPU wall, a reduced-grid number never
against the north-star grid.  Provenance and flags ride separately and
control PAIRING, not the key: rows flagged ``partial`` / ``smoke`` / a
named variant (watcher re-runs, session captures) stay VISIBLE in the
trajectory but are excluded from gating (:meth:`Row.gate_eligible`),
and diff refuses to pair rows of differing flag provenance — the
ledger shows everything and only compares like-for-like.

Raw repeat samples (``extra.samples`` in new FULL records, recorded
per-rep by ``bench.py``) ride along on their rows so
:mod:`csmom_tpu.obs.regress` can put a bootstrap CI behind every
verdict instead of a bare delta.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import os
import re

from csmom_tpu.chaos import invariants as inv

__all__ = [
    "DEFAULT_PATTERNS",
    "Ledger",
    "Row",
    "load",
    "run_of",
]

DEFAULT_PATTERNS = (
    "BENCH_*.json",
    "MULTICHIP_*.json",
    "MULTIHOST_*.json",
    "HISTRANK_*.json",
    "PHASES_*.json",
    "TELEMETRY_*.json",
    "SERVE_*.json",
    "REPLAY_*.json",
    "TRACE_*.json",
    "FLEET_*.json",
)

_RUN_RE = re.compile(r"_r(\d+)")


def _scratch_note(basename: str) -> str | None:
    """A precise skip-reason for known scratch/per-machine files, or
    None to ingest.  Single-sourced on the same rules the hygiene tests
    enforce: ``BENCH_TPU_LAST.json`` is the per-machine session cache,
    and a TELEMETRY name that is both uncommittable
    (:func:`invariants.committable_sidecar`) and un-attributable (no
    round id) is a rehearse/scratch sidecar.  An uncommittable-but-
    attributable name (``TELEMETRY_rNN-<pid>.json`` operator reruns)
    still ingests — flagged as a variant, never gate-eligible."""
    if basename == "BENCH_TPU_LAST.json":
        return "per-machine TPU session cache, not round evidence: skipped"
    if (basename.startswith(("TELEMETRY_", "SERVE_", "REPLAY_", "TRACE_",
                             "FLEET_"))
            and not inv.committable_sidecar(basename)
            and run_of(basename)[0] is None):
        return ("scratch sidecar (uncommittable name, no round id), not "
                "round evidence: skipped")
    return None

# bench-extra wall metrics: field name -> the extra field holding that
# leg's workload fingerprint.  All are walls (lower is better, seconds).
_WALL_METRICS = {
    "event_backtest_wall_s": "workload",
    "event_batched_per_run_s": "workload",
    "grid16_rank_s": "grid_workload",
    "grid16_qcut_s": "grid_workload",
    "grid16_rank_matmul_s": "grid_workload",
    "grid16_rank_pallas_s": "grid_workload",
    "grid16_rank_matmul_bf16_s": "grid_workload",
    "pack_ingest_s": "grid_workload",
    "grid16_rank_full_s": "grid_full_workload",
    "grid16_rank_matmul_full_s": "grid_full_workload",
    # the device-mesh leg (ISSUE 10): its workload fingerprint CARRIES
    # the mesh layout + device count, so a 1-device and an N-device run
    # are different keys and never gate against each other
    "grid16_rank_full_sharded_s": "grid_full_sharded_workload",
}


@dataclasses.dataclass(frozen=True)
class Row:
    """One (run, metric) observation with full provenance."""

    run: str                 # normalized round id, e.g. "r05"
    run_num: int
    metric: str
    value: float
    unit: str
    direction: str           # "lower" | "higher" (which way is better)
    platform: str | None     # "cpu" / "tpu" / None (unrecorded)
    device_kind: str | None
    workload: str | None     # fingerprint two runs must share to compare
    source: str              # artifact file the row came from
    samples: tuple = ()      # raw per-rep measurements, () when absent
    flags: tuple = ()        # "partial", "smoke", "info", "variant:<v>"
    notes: tuple = ()        # footnotes: documented costs the reader of
    #                          a verdict must see — unlike flags they do
    #                          NOT exclude the row from gating and are
    #                          not part of the comparability key

    def key(self):
        """Comparability key: rows only diff/gate within the same key."""
        return (self.metric, self.platform, self.device_kind, self.workload)

    def gate_eligible(self) -> bool:
        # flags ARE the provenance mechanism: any flag (partial, smoke,
        # info, variant) marks evidence the gate must not regress against
        return not self.flags


@dataclasses.dataclass
class Ledger:
    rows: list
    problems: list           # [{"source": ..., "note": ...}, ...]
    root: str

    def runs(self) -> list:
        return sorted({r.run for r in self.rows},
                      key=lambda s: int(s.lstrip("r")))

    def by_key(self) -> dict:
        out: dict = {}
        for r in self.rows:
            out.setdefault(r.key(), []).append(r)
        for rows in out.values():
            rows.sort(key=lambda r: (r.run_num, r.source))
        return out

    def rows_for_run(self, run: str) -> list:
        want = _norm_run(run)
        return [r for r in self.rows if r.run == want]


def _norm_run(run: str) -> str:
    m = re.fullmatch(r"r?(\d+)", str(run).strip())
    if not m:
        return str(run)
    return f"r{int(m.group(1)):02d}"


def run_of(basename: str):
    """(run_id, run_num, variant) parsed from an artifact file name;
    ``(None, None, None)`` when the name carries no round id.

    ANY residue between the round id and ``.json`` names a variant —
    including the ``-<pid>`` suffix ``timeline.write_sidecar``'s
    no-clobber path gives operator reruns (``TELEMETRY_r05-1234.json``):
    only the bare canonical name is the round's evidence; everything
    else stays visible but flagged, hence never gate-eligible."""
    m = _RUN_RE.search(basename)
    if not m:
        return None, None, None
    num = int(m.group(1))
    stem = basename[m.end():]
    if stem.endswith(".json"):
        stem = stem[:-len(".json")]
    variant = stem.lstrip("_-") or None
    return f"r{num:02d}", num, variant


def _num(v):
    """A measured number, or None — reason strings ('skipped: ...') and
    booleans are not measurements."""
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def _flags(obj: dict, variant: str | None, info: bool = False) -> tuple:
    """Provenance flags of one record ``obj`` (the FULL artifact object,
    so the top-level ``partial`` marker is honored via the same
    :func:`invariants.is_partial` rule the schema family defines)."""
    flags = []
    if inv.is_partial(obj):
        flags.append("partial")
    if "smoke" in (obj.get("extra") or {}):
        flags.append("smoke")
    if info:
        flags.append("info")
    if variant:
        flags.append(f"variant:{variant}")
    return tuple(flags)


def _bench_rows(obj: dict, run: str, num: int, variant, source: str) -> list:
    """Rows from one bench-style record (FULL record, parsed headline, or
    a session capture)."""
    extra = obj.get("extra") or {}
    platform = extra.get("platform")
    # old records (r02/r03) predate device_kind; within one platform the
    # platform string is the honest default, not a fabricated kind
    device_kind = extra.get("device_kind") or platform
    flags = _flags(obj, variant)
    samples = _sample_map(extra)
    rows = []

    def add(metric, value, unit, direction, workload_field):
        v = _num(value)
        if v is None:
            return  # unmeasured legs carry reason strings, not numbers
        rows.append(Row(
            run=run, run_num=num, metric=metric, value=v, unit=unit,
            direction=direction, platform=platform,
            device_kind=device_kind,
            workload=extra.get(workload_field), source=source,
            samples=samples.get(metric, ()),
            flags=flags,
        ))

    add(obj.get("metric", "headline"), obj.get("value"),
        obj.get("unit", "?"), "higher", "workload")
    for metric, workload_field in _WALL_METRICS.items():
        add(metric, extra.get(metric), "s", "lower", workload_field)
    ct = extra.get("compile_totals")
    if isinstance(ct, dict):
        add("in_window_fresh_compiles", ct.get("in_window_fresh_compiles"),
            "compiles", "lower", "workload")
    return rows


def _telemetry_rows(obj: dict, run: str, num: int, variant,
                    source: str) -> list:
    """Rows from a TELEMETRY sidecar: phase walls (informational — phase
    mix shifts with the tunnel's mood, not with code quality) and the
    per-shape memory bytes from the metrics snapshot (gate-relevant:
    compiled memory is deterministic per shape+backend)."""
    rows = []
    base = dict(run=run, run_num=num, source=source)
    for ph in obj.get("phases") or []:
        if not isinstance(ph, dict):
            continue
        v = _num(ph.get("dur_s"))
        if v is None or not isinstance(ph.get("name"), str):
            continue
        rows.append(Row(
            metric=f"phase.{ph['name']}_s", value=v, unit="s",
            direction="lower", platform=None, device_kind=None,
            workload=obj.get("root") if isinstance(obj.get("root"), str)
            else None,
            flags=_flags({}, variant, info=True), **base,
        ))
    metrics = obj.get("metrics")
    mem = metrics.get("memory") if isinstance(metrics, dict) else None
    if isinstance(mem, dict):
        for shape_name, stats in sorted(mem.items()):
            if not isinstance(stats, dict):
                continue  # capture-failure reason string: nothing to diff
            peak = stats.get("peak_bytes")
            if not isinstance(peak, int) or isinstance(peak, bool):
                continue
            platform = stats.get("platform")
            if not isinstance(platform, str):
                # compiled bytes are per-backend; an unstamped entry
                # could pair a CPU model against a TPU measurement under
                # key (None, None) — refuse rather than mis-attribute
                continue
            # the measurement basis is part of the comparability key: a
            # backend-reported peak covers intermediates a modeled
            # argument+output+temp sum cannot, so a jax upgrade that
            # starts reporting real peaks must open a NEW trajectory,
            # not diff measured-vs-modeled on the old one
            src = stats.get("peak_source", "")
            basis = ("modeled" if isinstance(src, str)
                     and src.startswith("model") else "measured")
            rows.append(Row(
                metric="mem_peak_bytes", value=float(peak), unit="bytes",
                direction="lower", platform=platform,
                device_kind=platform, workload=f"{shape_name} [{basis}]",
                flags=_flags({}, variant), **base,
            ))
    return rows


def _sample_map(extra: dict) -> dict:
    """``extra.samples`` as {key: tuple-of-floats}, numeric entries only
    (the same defense as bench's sample ingestion: a damaged list
    degrades to fewer samples, never a raise)."""
    raw = extra.get("samples")
    if not isinstance(raw, dict):
        return {}
    out = {}
    for key, vals in raw.items():
        if isinstance(vals, list):
            out[key] = tuple(
                float(v) for v in vals
                if isinstance(v, (int, float)) and not isinstance(v, bool))
    return out


def _serve_rows(obj: dict, run: str, num: int, variant,
                source: str) -> list:
    """Rows from a SERVE artifact: the online workload's trajectory.

    Throughput (higher is better) and the total-latency percentiles
    (lower) are the gate-relevant axes; the in-window fresh-compile
    count rides along because the zero-compile property is the serve
    layer's structural claim and a regression there is a padding/warmup
    bug, not noise.  Smoke-bucket runs arrive flagged (``extra.smoke``)
    and therefore never gate — same provenance discipline as bench.

    v2 artifacts (ISSUE 8) add: ``serve_offered_rps`` (info — what the
    schedule asked for), an ``offered-limited`` flag on the THROUGHPUT
    row when the service fully kept up (achieved == offered measures
    the load, not the ceiling — such a row must never gate against a
    saturation-limited one, the r11 footnote made mechanical; latency
    rows still gate), ``serve_cache_hit_rate`` (higher), per-class p99
    rows (``serve_<class>_p99_ms``, lower — each class's budget
    promise), and ``serve_p99_under_burst_ms`` for bursty-schedule runs
    (lower — the tail-under-burst gate row, so a tail regression fails
    the PR, not the postmortem)."""
    extra = obj.get("extra") or {}
    platform = extra.get("platform")
    device_kind = extra.get("device_kind") or platform
    workload = extra.get("workload")
    flags = _flags(obj, variant)
    base = dict(run=run, run_num=num, source=source, platform=platform,
                device_kind=device_kind, workload=workload)
    samples = _sample_map(extra)
    total_samples = samples.get("serve_total_ms", ())
    rows = []
    v = _num(obj.get("value"))
    if v is not None:
        thr_flags = flags
        if obj.get("offered_limited") is True:
            thr_flags = flags + ("offered-limited",)
        rows.append(Row(metric="serve_throughput_rps", value=v,
                        unit=str(obj.get("unit", "req/s")),
                        direction="higher", flags=thr_flags, **base))
    orps = _num((obj.get("offered") or {}).get("offered_rps"))
    if orps is not None:
        rows.append(Row(metric="serve_offered_rps", value=orps,
                        unit="req/s", direction="higher",
                        flags=_flags(obj, variant, info=True), **base))
    total = (obj.get("latency_ms") or {}).get("total")
    if isinstance(total, dict):
        for q in ("p50", "p95", "p99"):
            pv = _num(total.get(q))
            if pv is not None:
                rows.append(Row(metric=f"serve_{q}_ms", value=pv, unit="ms",
                                direction="lower", flags=flags,
                                samples=total_samples, **base))
        if (obj.get("offered") or {}).get("schedule_kind") == "bursty":
            pv = _num(total.get("p99"))
            if pv is not None:
                # the tail-under-burst gate row: same measurement as
                # serve_p99_ms, named so the gate's verdict reads as
                # what it is — tail latency under bursty load
                rows.append(Row(metric="serve_p99_under_burst_ms",
                                value=pv, unit="ms", direction="lower",
                                flags=flags, samples=total_samples,
                                **base))
    cache = obj.get("cache")
    if isinstance(cache, dict) and cache.get("enabled", True):
        hr = _num(cache.get("hit_rate"))
        if hr is not None:
            rows.append(Row(metric="serve_cache_hit_rate", value=hr,
                            unit="frac", direction="higher", flags=flags,
                            **base))
    classes = obj.get("classes")
    if isinstance(classes, dict):
        for name, book in sorted(classes.items()):
            if not isinstance(book, dict):
                continue
            pv = _num((book.get("latency_ms") or {}).get("p99"))
            if pv is not None:
                rows.append(Row(metric=f"serve_{name}_p99_ms", value=pv,
                                unit="ms", direction="lower", flags=flags,
                                samples=samples.get(f"class:{name}", ()),
                                **base))
    # v3 (ISSUE 9): per-ENDPOINT rows.  Metric keys derive from the
    # artifact's endpoint names — which the schema validator pins to the
    # engine registry — so a newly registered endpoint lands its own
    # ledger trajectory (serve_ep_<name>_p99_ms gates; served rides as
    # info) with no edit here.
    endpoints = obj.get("endpoints")
    if isinstance(endpoints, dict):
        for name, book in sorted(endpoints.items()):
            if not isinstance(book, dict):
                continue
            pv = _num((book.get("latency_ms") or {}).get("p99"))
            if pv is not None:
                rows.append(Row(metric=f"serve_ep_{name}_p99_ms", value=pv,
                                unit="ms", direction="lower", flags=flags,
                                samples=samples.get(f"ep:{name}", ()),
                                **base))
            sv = _num(book.get("served"))
            if sv is not None:
                rows.append(Row(metric=f"serve_ep_{name}_served", value=sv,
                                unit="req", direction="higher",
                                flags=_flags(obj, variant, info=True),
                                **base))
    fc = _num((obj.get("compile") or {}).get("in_window_fresh_compiles"))
    if fc is not None:
        rows.append(Row(metric="serve_in_window_fresh_compiles", value=fc,
                        unit="compiles", direction="lower", flags=flags,
                        **base))
    # mesh runs (ISSUE 10): the scaling-probe efficiency rides as an
    # info row — speedup/devices at the largest warmed bucket.  Info,
    # never gated: CPU host-platform devices share cores, so the number
    # documents THIS topology's delivery, not a regression axis.
    mesh = extra.get("mesh")
    if isinstance(mesh, dict):
        eff = _num((mesh.get("scaling") or {}).get("scaling_efficiency"))
        if eff is not None:
            rows.append(Row(metric="mesh_scaling_efficiency", value=eff,
                            unit="frac", direction="higher",
                            flags=_flags(obj, variant, info=True), **base))
    return rows


def _serve_pool_rows(obj: dict, run: str, num: int, variant,
                     source: str) -> list:
    """Rows from a SERVE_POOL artifact: the multi-process tier's
    trajectory.  Throughput (higher), total-latency percentiles (lower),
    availability (higher — the robustness headline: the fraction of
    admitted requests the pool answered honestly), hedge rate (lower —
    hedges are paid straggler insurance; a rising rate means the fleet
    is straggling more), and the summed in-window fresh-compile count
    (lower; zero is the warm-before-ready contract across restarts)."""
    extra = obj.get("extra") or {}
    platform = extra.get("platform")
    device_kind = extra.get("device_kind") or platform
    workload = extra.get("workload")
    flags = _flags(obj, variant)
    base = dict(run=run, run_num=num, source=source, platform=platform,
                device_kind=device_kind, workload=workload, flags=flags)
    rows = []
    v = _num(obj.get("value"))
    if v is not None:
        # same honesty rule as single-process serve: a pool that fully
        # kept up measured the offered load, not a saturation ceiling —
        # flagged so it never gates against a saturated run
        if obj.get("offered_limited") is True:
            thr_base = dict(base, flags=flags + ("offered-limited",))
        else:
            thr_base = base
        rows.append(Row(metric="serve_pool_throughput_rps", value=v,
                        unit=str(obj.get("unit", "req/s")),
                        direction="higher", **thr_base))
    orps = _num((obj.get("offered") or {}).get("offered_rps"))
    if orps is not None:
        rows.append(Row(metric="serve_pool_offered_rps", value=orps,
                        unit="req/s", direction="higher",
                        **dict(base, flags=_flags(obj, variant,
                                                  info=True))))
    pool_samples = _sample_map(extra).get("serve_pool_total_ms", ())
    total = (obj.get("latency_ms") or {}).get("total")
    if isinstance(total, dict):
        for q in ("p50", "p95", "p99"):
            pv = _num(total.get(q))
            if pv is not None:
                rows.append(Row(metric=f"serve_pool_{q}_ms", value=pv,
                                unit="ms", direction="lower",
                                **dict(base, samples=pool_samples)))
    av = _num(obj.get("availability"))
    if av is not None:
        rows.append(Row(metric="serve_pool_availability", value=av,
                        unit="frac", direction="higher", **base))
    hr = _num((obj.get("hedge") or {}).get("rate"))
    if hr is not None:
        rows.append(Row(metric="serve_pool_hedge_rate", value=hr,
                        unit="frac", direction="lower", **base))
    fc = _num((obj.get("compile") or {}).get("in_window_fresh_compiles"))
    if fc is not None:
        rows.append(Row(metric="serve_pool_in_window_fresh_compiles",
                        value=fc, unit="compiles", direction="lower",
                        **base))
    return rows


def _serve_fabric_rows(obj: dict, run: str, num: int, variant,
                       source: str) -> list:
    """Rows from a SERVE_FABRIC artifact: the three-tier horizontal
    fabric's trajectory (ISSUE 14).  Throughput (higher), total-latency
    percentiles (lower, CI-backed by the bounded sample list),
    availability at the CLIENT tier (higher — the outermost ledger's
    robustness headline), the POOL-LEVEL cache hit rate (higher — the
    number consistent-hash routing exists to lift past the per-worker
    baseline), the client-observed hedge rate (lower — paid straggler
    insurance), failovers as info (they track the chaos plan, not code
    quality), and the fleet-summed fresh-compile count (lower).

    Latency rows from a capture taken with the fleet observatory ARMED
    (``extra.observatory_armed``, recorded since ISSUE 20) carry an
    ``observatory-armed`` NOTE — a footnote, not a flag: the rows still
    gate (armed captures are the steady state from r20 on, so armed
    gates against armed and a real latency regression still fails the
    PR), but every verdict that prints them says why the p50 stepped.
    The pinned cost, A/B-measured at r20 on the committed bursty
    schedule (0.3 s bursts at 240-300 rps): ~+0.3-0.4 ms p50 at steady
    25 rps, +5-13 ms p50 under burst (trial pairs 28.2->33.5 and
    35.3->42.5 ms), distributed across client span recording, the
    router demand hook, and in-router emitters — no single hot line to
    delete, accepted as the price of a closed-books observatory
    (the r11 "offered-limited" footnote idiom, minus gate exclusion)."""
    extra = obj.get("extra") or {}
    platform = extra.get("platform")
    device_kind = extra.get("device_kind") or platform
    workload = extra.get("workload")
    flags = _flags(obj, variant)
    base = dict(run=run, run_num=num, source=source, platform=platform,
                device_kind=device_kind, workload=workload, flags=flags)
    rows = []
    v = _num(obj.get("value"))
    if v is not None:
        if obj.get("offered_limited") is True:
            thr_base = dict(base, flags=flags + ("offered-limited",))
        else:
            thr_base = base
        rows.append(Row(metric="serve_fabric_throughput_rps", value=v,
                        unit=str(obj.get("unit", "req/s")),
                        direction="higher", **thr_base))
    orps = _num((obj.get("offered") or {}).get("offered_rps"))
    if orps is not None:
        rows.append(Row(metric="serve_fabric_offered_rps", value=orps,
                        unit="req/s", direction="higher",
                        **dict(base, flags=_flags(obj, variant,
                                                  info=True))))
    fabric_samples = _sample_map(extra).get("serve_fabric_total_ms", ())
    lat_notes = (("observatory-armed",)
                 if extra.get("observatory_armed") is True else ())
    total = (obj.get("latency_ms") or {}).get("total")
    if isinstance(total, dict):
        for q in ("p50", "p95", "p99"):
            pv = _num(total.get(q))
            if pv is not None:
                rows.append(Row(metric=f"serve_fabric_{q}_ms", value=pv,
                                unit="ms", direction="lower",
                                **dict(base, samples=fabric_samples,
                                       notes=lat_notes)))
    av = _num(obj.get("availability"))
    if av is not None:
        rows.append(Row(metric="serve_fabric_availability", value=av,
                        unit="frac", direction="higher", **base))
    chr_ = _num((obj.get("cache") or {}).get("pool_hit_rate"))
    if chr_ is not None:
        rows.append(Row(metric="serve_fabric_cache_hit_rate", value=chr_,
                        unit="frac", direction="higher", **base))
    hr = _num((obj.get("hedge") or {}).get("rate"))
    if hr is not None:
        rows.append(Row(metric="serve_fabric_hedge_rate", value=hr,
                        unit="frac", direction="lower", **base))
    fo = _num((obj.get("requests") or {}).get("failovers"))
    if fo is not None:
        rows.append(Row(metric="serve_fabric_failovers", value=fo,
                        unit="req", direction="lower",
                        **dict(base, flags=_flags(obj, variant,
                                                  info=True))))
    fc = _num((obj.get("compile") or {}).get("in_window_fresh_compiles"))
    if fc is not None:
        rows.append(Row(metric="serve_fabric_in_window_fresh_compiles",
                        value=fc, unit="compiles", direction="lower",
                        **base))
    return rows


def _fleet_rows(obj: dict, run: str, num: int, variant,
                source: str) -> list:
    """Rows from a FLEET artifact: the observatory's trajectory
    (ISSUE 19).  The kill-window capacity-loss fraction (lower — how
    much of the fleet's nominal worker-seconds a kill actually cost,
    CI-backed by the per-window sample list) and the worst spawn→ready
    wall (lower — the re-warm interval that IS the kill window's width,
    sampled once per (re)spawn) gate; per-class demand rates ride as
    info because offered load tracks the loadgen plan, not code
    quality."""
    extra = obj.get("extra") or {}
    platform = extra.get("platform")
    device_kind = extra.get("device_kind") or platform
    workload = extra.get("workload")
    flags = _flags(obj, variant)
    samples = _sample_map(extra)
    base = dict(run=run, run_num=num, source=source, platform=platform,
                device_kind=device_kind, workload=workload)
    rows = []
    v = _num(obj.get("value"))
    if v is not None:
        rows.append(Row(
            metric="fleet_kill_window_capacity_loss_frac", value=v,
            unit=str(obj.get("unit", "frac")), direction="lower",
            flags=flags,
            samples=samples.get("fleet_kill_window_capacity_loss_frac",
                                ()), **base))
    walls = (obj.get("lifecycle") or {}).get("ready_walls_s")
    if isinstance(walls, list):
        nums = [w for w in (_num(x) for x in walls) if w is not None]
        if nums:
            rows.append(Row(
                metric="fleet_worker_ready_wall_s", value=max(nums),
                unit="s", direction="lower", flags=flags,
                samples=samples.get("fleet_worker_ready_wall_s", ()),
                **base))
    # per-spawn-kind walls (ISSUE 20): a spare promotion gates against
    # the promotion distribution, a cold spawn against cold — averaging
    # across regimes would hide a fast-path regression behind cold noise
    for key, kind_samples in sorted(samples.items()):
        if not key.startswith("fleet_worker_ready_wall_") \
                or key == "fleet_worker_ready_wall_s":
            continue
        nums = [w for w in (_num(x) for x in kind_samples)
                if w is not None]
        if nums:
            rows.append(Row(
                metric=key, value=max(nums), unit="s",
                direction="lower", flags=flags, samples=kind_samples,
                **base))
    classes = (obj.get("demand") or {}).get("classes")
    window_s = _num(obj.get("window_s"))
    if isinstance(classes, dict) and window_s:
        for cls, tot in sorted(classes.items()):
            off = _num((tot or {}).get("offered")) if isinstance(
                tot, dict) else None
            if off is not None:
                rows.append(Row(
                    metric=f"fleet_demand_{cls}_rps",
                    value=round(off / window_s, 3), unit="req/s",
                    direction="higher",
                    flags=_flags(obj, variant, info=True), **base))
    return rows


def _trace_rows(obj: dict, run: str, num: int, variant,
                source: str) -> list:
    """Rows from a TRACE artifact: the request-path decomposition's
    trajectory.

    Per-stage p99s (``trace_stage_<stage>_p99_ms``, lower) are the gate
    axes — a regression in ONE stage names its layer (queue_wait = the
    admission tier, dispatch = the engine, transport = the wire) instead
    of smearing across an end-to-end p99.  Per-class SLO error-budget
    burn rates (``serve_<class>_budget_burn``, lower — obs.metrics.
    budget_burn) gate too: a class burning its error budget faster fails
    the PR, not the postmortem.  Books/orphan totals ride as info (their
    counts track the workload, not code quality)."""
    extra = obj.get("extra") or {}
    platform = extra.get("platform")
    device_kind = extra.get("device_kind") or platform
    workload = extra.get("workload")
    flags = _flags(obj, variant)
    samples = _sample_map(extra)
    base = dict(run=run, run_num=num, source=source, platform=platform,
                device_kind=device_kind, workload=workload)
    rows = []
    stages = obj.get("stages")
    if isinstance(stages, dict):
        for stage, s in sorted(stages.items()):
            if not isinstance(s, dict):
                continue
            pv = _num(s.get("p99"))
            if pv is not None:
                metric = f"trace_stage_{stage}_p99_ms"
                rows.append(Row(metric=metric, value=pv, unit="ms",
                                direction="lower", flags=flags,
                                samples=samples.get(metric, ()), **base))
    classes = obj.get("classes")
    if isinstance(classes, dict):
        for name, book in sorted(classes.items()):
            if not isinstance(book, dict):
                continue
            burn = _num(book.get("budget_burn"))
            if burn is not None:
                rows.append(Row(metric=f"serve_{name}_budget_burn",
                                value=burn, unit="burn",
                                direction="lower", flags=flags, **base))
    books = obj.get("books")
    if isinstance(books, dict):
        cv = _num(books.get("complete"))
        if cv is not None:
            rows.append(Row(metric="trace_complete_traces", value=cv,
                            unit="traces", direction="higher",
                            flags=_flags(obj, variant, info=True), **base))
    oc = _num((obj.get("orphans") or {}).get("count"))
    if oc is not None:
        rows.append(Row(metric="trace_orphan_halves", value=oc,
                        unit="halves", direction="lower",
                        flags=_flags(obj, variant, info=True), **base))
    fc = _num((obj.get("compile") or {}).get("in_window_fresh_compiles"))
    if fc is not None:
        rows.append(Row(metric="trace_in_window_fresh_compiles", value=fc,
                        unit="compiles", direction="lower", flags=flags,
                        **base))
    return rows


def _replay_rows(obj: dict, run: str, num: int, variant,
                 source: str) -> list:
    """Rows from a REPLAY artifact: the streaming workload's trajectory.

    Tick throughput (higher) and the serve-side staleness-lag
    percentile (lower — how far behind the ingest frontier responses
    were computed) are the gate axes the live tier answers for; the
    in-window fresh-compile count rides along because the zero-compile
    replay window is a structural claim (warmed serve buckets + warmed
    stream reconcile entries), same as serve.  Smoke-bucket replays
    arrive flagged and never gate."""
    extra = obj.get("extra") or {}
    platform = extra.get("platform")
    device_kind = extra.get("device_kind") or platform
    workload = extra.get("workload")
    flags = _flags(obj, variant)
    base = dict(run=run, run_num=num, source=source, platform=platform,
                device_kind=device_kind, workload=workload, flags=flags)
    rows = []
    v = _num(obj.get("value"))
    if v is not None:
        rows.append(Row(metric="replay_ticks_per_s", value=v,
                        unit=str(obj.get("unit", "ticks/s")),
                        direction="higher", **base))
    stale = obj.get("staleness_ms")
    if isinstance(stale, dict):
        pv = _num(stale.get("p99"))
        if pv is not None:
            rows.append(Row(metric="replay_staleness_p99_ms", value=pv,
                            unit="ms", direction="lower", **base))
    total = ((obj.get("serve") or {}).get("latency_ms") or {}).get("total")
    if isinstance(total, dict):
        pv = _num(total.get("p99"))
        if pv is not None:
            rows.append(Row(metric="replay_serve_p99_ms", value=pv,
                            unit="ms", direction="lower", **base))
    fc = _num((obj.get("compile") or {}).get("in_window_fresh_compiles"))
    if fc is not None:
        rows.append(Row(metric="replay_in_window_fresh_compiles", value=fc,
                        unit="compiles", direction="lower", **base))
    return rows


def _generic_rows(obj: dict, kind: str, run: str, num: int, variant,
                  source: str) -> list:
    """Info rows for the remaining artifact kinds (multichip equality,
    phases profiles, histrank/multihost records reached without a bench
    wrapper): shown in the trajectory, never gated — their value axes
    are equality/topology claims, not regression-testable walls."""
    extra = obj.get("extra") or {}
    if kind == "multichip":
        return [Row(
            run=run, run_num=num, metric="multichip_ok",
            value=1.0 if obj.get("ok") else 0.0, unit="bool",
            direction="higher", platform=None, device_kind=None,
            workload=f"n_devices={obj.get('n_devices')}",
            source=source, flags=_flags(obj, variant, info=True),
        )]
    v = _num(obj.get("value"))
    if v is None:
        return []
    unit = str(obj.get("unit", "?"))
    return [Row(
        run=run, run_num=num, metric=str(obj.get("metric", "?")), value=v,
        unit=unit,
        # best-effort direction for a foreign value axis: walls read as
        # lower-is-better, anything else as higher.  Info rows never
        # gate, so a mislabel costs a display hint, not a verdict
        direction="lower" if unit.rstrip("s").endswith("_") or unit == "s"
        else "higher",
        platform=extra.get("platform"),
        device_kind=extra.get("device_kind") or extra.get("platform"),
        workload=extra.get("workload"), source=source,
        flags=_flags(obj, variant, info=True),
    )]


def ingest_file(path: str, have_full_runs=frozenset()) -> tuple:
    """``(rows, problems)`` for one artifact file.  Never raises on a
    damaged file: the damage IS the finding, reported as a problem."""
    source = os.path.basename(path)
    run, num, variant = run_of(source)
    if run is None:
        return [], [{"source": source,
                     "note": "no round id (rNN) in the file name: not "
                             "attributable to a run, skipped"}]
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except OSError as e:
        return [], [{"source": source, "note": f"unreadable: {e}"}]
    except json.JSONDecodeError as e:
        return [], [{"source": source, "note": f"not valid JSON: {e}"}]
    kind = inv.detect_kind(obj)
    if kind == "driver_capture":
        parsed = obj.get("parsed")
        if not isinstance(parsed, dict):
            return [], [{"source": source,
                         "note": "driver capture with parsed: null — the "
                                 "run's headline was lost (the r4 failure "
                                 "mode); no trajectory rows"}]
        if run in have_full_runs and variant is None:
            # the canonical FULL record is a superset of the CANONICAL
            # headline only — a variant capture (watcher rerun) is
            # distinct evidence and stays visible, flagged
            return [], []
        return _bench_rows(parsed, run, num, variant, source), []
    if kind == "record":
        # only BENCH-family records carry the gate-relevant wall metrics
        # with known directions; HISTRANK/MULTIHOST captures are record-
        # SHAPED but their value axes (comm ratios, equality claims) are
        # trajectory information, never regression-gated
        if source.startswith("BENCH"):
            return _bench_rows(obj, run, num, variant, source), []
        rows = _generic_rows(obj, kind, run, num, variant, source)
        if rows:
            return rows, []
        return [], [{"source": source,
                     "note": "record artifact with no numeric value axis: "
                             "present but contributes no trajectory rows"}]
    if kind == "trace":
        ver = obj.get("schema_version")
        if ver not in inv.KNOWN_TRACE_SCHEMA_VERSIONS:
            return [], [{"source": source,
                         "note": f"unknown trace schema_version {ver!r} "
                                 f"(reader understands "
                                 f"{list(inv.KNOWN_TRACE_SCHEMA_VERSIONS)}"
                                 "): not half-parsed into rows"}]
        return _trace_rows(obj, run, num, variant, source), []
    if kind == "replay":
        ver = obj.get("schema_version")
        if ver not in inv.KNOWN_REPLAY_SCHEMA_VERSIONS:
            return [], [{"source": source,
                         "note": f"unknown replay schema_version {ver!r} "
                                 f"(reader understands "
                                 f"{list(inv.KNOWN_REPLAY_SCHEMA_VERSIONS)}"
                                 "): not half-parsed into rows"}]
        return _replay_rows(obj, run, num, variant, source), []
    if kind == "fleet":
        ver = obj.get("schema_version")
        if ver not in inv.KNOWN_FLEET_SCHEMA_VERSIONS:
            return [], [{"source": source,
                         "note": f"unknown fleet schema_version {ver!r} "
                                 f"(reader understands "
                                 f"{list(inv.KNOWN_FLEET_SCHEMA_VERSIONS)}"
                                 "): not half-parsed into rows"}]
        return _fleet_rows(obj, run, num, variant, source), []
    if kind == "serve_fabric":
        ver = obj.get("schema_version")
        if ver not in inv.KNOWN_SERVE_FABRIC_SCHEMA_VERSIONS:
            return [], [{"source": source,
                         "note": f"unknown serve_fabric schema_version "
                                 f"{ver!r} (reader understands "
                                 f"{list(inv.KNOWN_SERVE_FABRIC_SCHEMA_VERSIONS)}"
                                 "): not half-parsed into rows"}]
        return _serve_fabric_rows(obj, run, num, variant, source), []
    if kind == "serve_pool":
        ver = obj.get("schema_version")
        if ver not in inv.KNOWN_SERVE_POOL_SCHEMA_VERSIONS:
            return [], [{"source": source,
                         "note": f"unknown serve_pool schema_version "
                                 f"{ver!r} (reader understands "
                                 f"{list(inv.KNOWN_SERVE_POOL_SCHEMA_VERSIONS)}"
                                 "): not half-parsed into rows"}]
        return _serve_pool_rows(obj, run, num, variant, source), []
    if kind == "serve":
        # closed-world schema, same rule as telemetry: a serve artifact
        # from a different era must not half-parse into gate rows
        ver = obj.get("schema_version")
        if ver not in inv.KNOWN_SERVE_SCHEMA_VERSIONS:
            return [], [{"source": source,
                         "note": f"unknown serve schema_version {ver!r} "
                                 f"(reader understands "
                                 f"{list(inv.KNOWN_SERVE_SCHEMA_VERSIONS)})"
                                 ": not half-parsed into rows"}]
        return _serve_rows(obj, run, num, variant, source), []
    if kind == "telemetry":
        # closed-world schema: a sidecar from a different era of the
        # code must not be half-parsed into gate-eligible rows (its
        # byte semantics may have changed) — same rule `csmom timeline`
        # enforces, via the same invariants constant
        ver = obj.get("schema_version")
        if ver not in inv.KNOWN_TELEMETRY_SCHEMA_VERSIONS:
            return [], [{"source": source,
                         "note": f"unknown telemetry schema_version "
                                 f"{ver!r} (reader understands "
                                 f"{list(inv.KNOWN_TELEMETRY_SCHEMA_VERSIONS)}"
                                 "): not half-parsed into rows"}]
        return _telemetry_rows(obj, run, num, variant, source), []
    if kind in ("multichip", "phases"):
        rows = _generic_rows(obj, kind, run, num, variant, source)
        if rows:
            return rows, []
        return [], [{"source": source,
                     "note": f"{kind} artifact with no numeric value axis: "
                             "present but contributes no trajectory rows"}]
    if kind == "tpu_cache":
        return [], [{"source": source,
                     "note": "session cache file: provenance belongs to the "
                             "run that captured it, skipped"}]
    return [], [{"source": source,
                 "note": "unrecognized artifact shape: no known key "
                         "signature matches"}]


def load(root: str, patterns=DEFAULT_PATTERNS) -> Ledger:
    """Ingest every committed artifact under ``root`` (non-recursive:
    round artifacts land at the repo root by contract)."""
    paths = []
    for pat in patterns:
        paths += _glob.glob(os.path.join(root, pat))
    paths = sorted(set(paths))
    # FULL records ingest first: a run's driver capture only defers to
    # its FULL record when that record ACTUALLY yielded rows — a
    # truncated/damaged FULL file (the ENOSPC case) must not suppress a
    # healthy headline that did land
    def _is_canonical_full(base: str) -> bool:
        if not base.startswith("BENCH_FULL_"):
            return False
        run, _, variant = run_of(base)
        return run is not None and variant is None

    rows, problems, have_full = [], [], set()
    deferred = []
    for p in paths:
        base = os.path.basename(p)
        note = _scratch_note(base)
        if note is not None:
            problems.append({"source": base, "note": note})
            continue
        if not _is_canonical_full(base):
            deferred.append(p)
            continue
        r, pr = ingest_file(p)
        rows += r
        problems += pr
        if r:
            have_full.add(run_of(base)[0])
    for p in deferred:
        r, pr = ingest_file(p, have_full_runs=have_full)
        rows += r
        problems += pr
    rows.sort(key=lambda r: (r.metric, r.run_num, r.source))
    return Ledger(rows=rows, problems=problems, root=root)
