"""Per-shape device-memory accounting from XLA's compiled executables.

The telemetry layer answers "where did the time go"; this module answers
the companion question the r6-r8 records never could: **how much device
memory does each compiled shape claim?**  On the north-star workload the
binding resource is HBM, not FLOPs — a shape that compiles fine on the
CPU fallback can OOM a v5e core — so memory has to be an observable axis
of the perf ledger, with per-shape evidence a regression gate can diff
across rounds, not a vibe ("it fit last time").

The capture site is the AOT pass (:mod:`csmom_tpu.compile.aot`): the one
place the repo holds a ``Compiled`` handle for every hot shape, so
``compiled.memory_analysis()`` (XLA's ``CompiledMemoryStats``: argument /
output / temp / generated-code bytes, peak where the backend reports it)
is free to read there — no extra compile, no extra dispatch.  The same
code runs on CPU and TPU; the byte numbers are per-backend, which is why
every ledger row carries its platform and the gate never diffs a cpu row
against a tpu row.

Captured stats land three ways (the ledger reads the third):

- the per-entry AOT record (``aot_compile``) and the warmup report;
- the process-wide registry here, folded into every
  :func:`csmom_tpu.obs.metrics.snapshot` under ``"memory"``;
- through the snapshot, the ``TELEMETRY_<run>.json`` sidecar —
  schema-validated by :mod:`csmom_tpu.chaos.invariants` like the rest of
  the artifact family.

jax-free at import (the chaos/obs contract): the module only touches a
``Compiled`` object the caller already holds.
"""

from __future__ import annotations

import threading

__all__ = [
    "BYTE_FIELDS",
    "capture",
    "memory_analysis_bytes",
    "peak_bytes",
    "record",
    "reset",
    "snapshot",
]

# CompiledMemoryStats fields we persist, in report order.  All ints
# (bytes); absent attributes are simply not reported rather than zeroed,
# so a backend that cannot account a field never fakes a 0 measurement.
BYTE_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)

# backends that report a true HBM peak expose it under one of these
_PEAK_ATTRS = ("peak_memory_in_bytes", "peak_memory_usage_in_bytes")

_LOCK = threading.Lock()
_REGISTRY: dict = {}  # entry name -> bytes dict (or capture-failure reason)


def memory_analysis_bytes(compiled) -> dict | str:
    """``compiled.memory_analysis()`` as a JSON-ready bytes dict.

    Returns a reason string instead of raising when the backend has no
    memory analysis (some plugins stub it out) — memory observability
    must never cost the compile that produced the handle.
    """
    try:
        stats = compiled.memory_analysis()
    except Exception as e:  # plugin-dependent surface: record why
        return f"not available: {type(e).__name__}: {e}"[:160]
    if stats is None:
        return "not available: backend returned no memory analysis"
    out: dict = {}
    for field in BYTE_FIELDS:
        v = getattr(stats, field, None)
        if isinstance(v, int):
            out[field] = v
    for attr in _PEAK_ATTRS:
        v = getattr(stats, attr, None)
        if isinstance(v, int) and v > 0:
            out["peak_bytes"] = v
            out["peak_source"] = attr
            break
    if "peak_bytes" not in out:
        # CPU (and some plugin) stats carry no peak; the live-buffer sum
        # over the MEASURED components is the defensible lower bound —
        # labeled as a model naming exactly what was summed, so a TPU
        # row never silently compares against a modeled CPU row as if
        # both were measured peaks.  Components that were not reported
        # contribute nothing and are not named: a backend reporting
        # neither a peak nor any component gets a reason string, never
        # a fabricated 0 that a later real measurement would read as
        # infinite memory growth.
        comps = [f for f in ("argument_size_in_bytes",
                             "output_size_in_bytes",
                             "temp_size_in_bytes") if f in out]
        if not comps:
            return ("not available: backend reports neither a peak nor "
                    "argument/output/temp byte components")
        out["peak_bytes"] = sum(out[f] for f in comps)
        out["peak_source"] = ("model: "
                              + "+".join(c.split("_")[0] for c in comps)
                              + " (backend reports no peak)")
    return out


def record(name: str, stats: dict | str) -> None:
    """Register one shape's stats in the process-wide table (last write
    wins: recompiling a shape re-measures it)."""
    with _LOCK:
        _REGISTRY[name] = stats


def capture(name: str, compiled, platform: str | None = None) -> dict | str:
    """Measure + register in one step; returns what was recorded.

    ``platform`` stamps the backend the bytes belong to — compiled
    memory is per-backend, and the ledger refuses to diff rows whose
    platforms differ, so an unstamped row can never masquerade as a
    TPU measurement."""
    stats = memory_analysis_bytes(compiled)
    if isinstance(stats, dict) and platform:
        stats["platform"] = platform
    record(name, stats)
    return stats


def peak_bytes(stats) -> int | None:
    """The comparable scalar of one entry (None for failure reasons)."""
    if isinstance(stats, dict) and isinstance(stats.get("peak_bytes"), int):
        return stats["peak_bytes"]
    return None


def snapshot() -> dict:
    """All captured shapes: ``{entry_name: bytes_dict_or_reason}``."""
    with _LOCK:
        return dict(_REGISTRY)


def reset() -> None:
    with _LOCK:
        _REGISTRY.clear()
