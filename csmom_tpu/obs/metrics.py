"""Process-wide metrics registry: counters, gauges, histograms.

One flat registry per process, keyed by dotted metric name.  Handles are
cheap and cached (``counter("bench.rows_landed")`` twice returns the
same object), and every mutator checks the telemetry arming flag FIRST:
disarmed, ``inc()``/``set()``/``observe()`` are one global load + one
compare — no lock, no allocation — the same zero-cost-unarmed contract
as ``chaos.checkpoint`` and ``obs.span`` (pinned by tests).  The
registry therefore only accumulates while a collector is armed, which is
exactly when a snapshot can land anywhere.

``snapshot()`` is what bench embeds in every BENCH record (and emits
into the event stream): all registered values, plus the AOT
compile-cache accounting folded in from ``profiling.compile_stats`` —
cache hits/misses, trace vs backend-compile counts — and the
jax.monitoring listener state, both read lazily so a jax-free process
(the bench supervisor) can snapshot without importing jax.

Every snapshot is sequence-numbered and process-identity-stamped (pid +
role + slot, see :func:`set_identity`), and :func:`snapshot_delta` turns
two consecutive snapshots into the wire-ready delta the fleet emitters
stream: counter deltas are non-negative BY CONSTRUCTION (a counter that
reads lower than it did one sequence number ago is a corrupted registry,
and the delta refuses to exist rather than emit a lie).
"""

from __future__ import annotations

import math
import os
import sys
import threading

from csmom_tpu.obs import spans as _spans

__all__ = ["budget_burn", "counter", "gauge", "histogram", "set_identity",
           "snapshot", "snapshot_delta", "reset"]

_LOCK = threading.Lock()
_REGISTRY: dict = {}  # name -> metric handle
_SEQ = 0  # monotonic per-process snapshot sequence number
_IDENTITY = {"role": "main", "slot": None}  # stamped into every snapshot


class Counter:
    """Monotone event count.  ``inc(n)`` is a no-op while disarmed."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if _spans._COLLECTOR is None:
            return
        with _LOCK:
            self.value += n


class Gauge:
    """Last-written value (deadline margin, queue depth, a flag)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, v) -> None:
        if _spans._COLLECTOR is None:
            return
        with _LOCK:
            self.value = v


class Histogram:
    """Streaming summary of observations with bounded log-bucket
    quantile estimation — p50/p95/p99 with NO per-sample storage.

    Buckets are geometric with ratio ``2**0.25`` (four per doubling)
    spanning [2^-20, 2^20) ≈ [1 µs, 1 M] in whatever unit the caller
    observes, with one underflow and one overflow bucket — 162 ints,
    allocated ONCE at registration.  A quantile answer is the geometric
    midpoint of the bucket holding that rank, so the relative error is
    bounded by the bucket ratio (≈ ±9%) — tight enough for a live tail
    snapshot; the artifact pipeline keeps exact reservoirs where a gate
    needs them.  The disarmed fast path is unchanged: one global load,
    one compare, return.
    """

    # four buckets per doubling across 2^[-20, 20): index 0 = underflow
    # (v < 2^-20, incl. zero/negative), index -1 = overflow
    _LOG_MIN = -20
    _LOG_MAX = 20
    _PER_DOUBLING = 4
    _N_BUCKETS = (_LOG_MAX - _LOG_MIN) * _PER_DOUBLING + 2

    __slots__ = ("name", "count", "total", "min", "max", "buckets")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.buckets = [0] * self._N_BUCKETS

    def _index(self, v: float) -> int:
        if v < 2.0 ** self._LOG_MIN:
            return 0
        i = int((math.log2(v) - self._LOG_MIN) * self._PER_DOUBLING) + 1
        return min(i, self._N_BUCKETS - 1)

    def _bucket_value(self, i: int) -> float:
        """The geometric midpoint of bucket ``i`` (edges for the under/
        overflow buckets — an out-of-range estimate must not extrapolate
        past what was observable)."""
        if i <= 0:
            return 2.0 ** self._LOG_MIN
        if i >= self._N_BUCKETS - 1:
            return 2.0 ** self._LOG_MAX
        lo = self._LOG_MIN + (i - 1) / self._PER_DOUBLING
        return 2.0 ** (lo + 0.5 / self._PER_DOUBLING)

    def observe(self, v: float) -> None:
        if _spans._COLLECTOR is None:
            return
        with _LOCK:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.buckets[self._index(v)] += 1

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile estimate from the log buckets (None
        until something was observed).  Clamped into [min, max] so a
        one-sample histogram answers that sample, not a bucket edge.

        Lock-free read, like ``summary()`` always was: ``snapshot()``
        calls this while holding the registry lock (which is NOT
        reentrant), and a torn read costs one snapshot a stale count,
        never a wrong bucket."""
        if not self.count:
            return None
        rank = max(1, math.ceil(q * self.count))
        acc = 0
        for i, n in enumerate(self.buckets):
            acc += n
            if acc >= rank:
                est = self._bucket_value(i)
                return max(self.min, min(self.max, est))
        return self.max

    def summary(self) -> dict:
        out = {
            "count": self.count,
            "sum": round(self.total, 6),
            "min": self.min,
            "max": self.max,
            "mean": round(self.total / self.count, 6) if self.count else None,
        }
        for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            v = self.quantile(q)
            out[name] = None if v is None else round(v, 6)
        return out


def _get(name: str, cls):
    with _LOCK:
        m = _REGISTRY.get(name)
        if m is None:
            m = _REGISTRY[name] = cls(name)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}"
            )
        return m


def counter(name: str) -> Counter:
    return _get(name, Counter)


def gauge(name: str) -> Gauge:
    return _get(name, Gauge)


def histogram(name: str) -> Histogram:
    return _get(name, Histogram)


def budget_burn(n_served: int, n_violations: int,
                slo_target: float = 0.99) -> float | None:
    """Per-class SLO error-budget burn rate.

    The class's budget promise is an SLO: ``slo_target`` of served
    requests finish inside the class deadline budget.  The error budget
    is the allowed violation fraction (``1 - slo_target``), and the burn
    rate is observed violations over allowance::

        burn = (n_violations / n_served) / (1 - slo_target)

    1.0 means the run consumed its error budget exactly; under 1.0 is
    headroom; over 1.0 is an SLO breach scaled by how hard (burn 2.0 =
    violating at twice the allowed rate).  The ledger ingests these as
    ``serve_<class>_budget_burn`` rows (lower is better), so a class
    that starts burning its budget fails the PR gate, not the
    postmortem.  None when nothing was served — "no traffic" must never
    be spelled "no burn".
    """
    if n_served <= 0:
        return None
    allowed = 1.0 - float(slo_target)
    if allowed <= 0:
        raise ValueError(f"slo_target must be < 1, got {slo_target}")
    return round((n_violations / n_served) / allowed, 4)


def set_identity(role: str, slot=None) -> None:
    """Declare who this process is in the fleet (``worker``/``router``/
    ``loadgen``/...).  Stamped into every subsequent snapshot so a delta
    landing at the aggregator names its emitter without side-channel
    bookkeeping.  The pid is read at snapshot time, not here — a fork
    after ``set_identity`` must not inherit a stale pid."""
    with _LOCK:
        _IDENTITY["role"] = str(role)
        _IDENTITY["slot"] = slot


def reset() -> None:
    """Drop every registered metric (tests re-register per case).  The
    sequence number is NOT reset — it is a per-process lifetime counter,
    and rewinding it would let a post-reset snapshot alias a pre-reset
    one in a delta stream."""
    with _LOCK:
        _REGISTRY.clear()


def snapshot(include_compile: bool = True) -> dict:
    """All registered metrics as one JSON-ready dict.

    ``compile`` folds in the process-global AOT cache / dispatch counters
    from :func:`csmom_tpu.utils.profiling.compile_stats`, read lazily and
    only when jax is already imported — a jax-free supervisor snapshots
    its own registry and records WHY the compile block is absent instead
    of importing a backend to fill it.
    """
    global _SEQ
    with _LOCK:
        _SEQ += 1
        out: dict = {
            "seq": _SEQ,
            "identity": {"pid": os.getpid(), "role": _IDENTITY["role"],
                         "slot": _IDENTITY["slot"]},
            "counters": {m.name: m.value for m in _REGISTRY.values()
                         if isinstance(m, Counter)},
            "gauges": {m.name: m.value for m in _REGISTRY.values()
                       if isinstance(m, Gauge)},
            "histograms": {m.name: m.summary() for m in _REGISTRY.values()
                           if isinstance(m, Histogram)},
        }
    # device-memory axis: per-shape memory_analysis bytes captured by the
    # AOT pass (obs.memstats).  Included only when something was captured
    # — a process that never held a Compiled handle has nothing to claim,
    # and an empty block would read as "measured: zero shapes use memory"
    from csmom_tpu.obs import memstats as _memstats

    mem = _memstats.snapshot()
    if mem:
        out["memory"] = mem
    if include_compile:
        if "jax" in sys.modules:
            from csmom_tpu.utils.profiling import (
                compile_stats,
                listeners_installed,
            )

            out["compile"] = compile_stats().as_dict()
            out["profiling_listeners_installed"] = listeners_installed()
        else:
            out["compile"] = ("not applicable: jax not imported in this "
                              "process (supervisor-side snapshot)")
    return out


def snapshot_delta(prev: dict, cur: dict) -> dict:
    """The change between two snapshots of the SAME process, wire-ready.

    This is the primitive every exporter shares: counters become
    non-negative deltas (a counter first seen in ``cur`` deltas from
    zero), gauges carry their current value (a gauge is a last-write,
    not an accumulation), histograms carry count/sum deltas.  Three
    things are refused loudly instead of smoothed over:

    - a pid or role mismatch (a delta across two different processes is
      not a delta, it is a splice);
    - a non-advancing sequence number (``cur`` must be strictly newer);
    - a counter or histogram count that went DOWN — counters are monotone
      by construction, so a regression means registry corruption, and
      emitting it would poison every downstream cumulative series.
    """
    pid_prev = prev.get("identity", {}).get("pid")
    pid_cur = cur.get("identity", {}).get("pid")
    if pid_prev != pid_cur:
        raise ValueError(
            f"snapshot_delta across processes: prev pid {pid_prev}, "
            f"cur pid {pid_cur}"
        )
    seq_prev, seq_cur = prev.get("seq"), cur.get("seq")
    if seq_prev is None or seq_cur is None or seq_cur <= seq_prev:
        raise ValueError(
            f"snapshot_delta needs advancing seq: prev {seq_prev}, "
            f"cur {seq_cur}"
        )
    counters = {}
    prev_c = prev.get("counters", {})
    for name, v in cur.get("counters", {}).items():
        d = v - prev_c.get(name, 0)
        if d < 0:
            raise ValueError(
                f"counter {name!r} went backwards ({prev_c.get(name)} -> "
                f"{v}): counters are monotone by construction"
            )
        counters[name] = d
    hists = {}
    prev_h = prev.get("histograms", {})
    for name, s in cur.get("histograms", {}).items():
        p = prev_h.get(name, {})
        dc = s.get("count", 0) - p.get("count", 0)
        if dc < 0:
            raise ValueError(
                f"histogram {name!r} count went backwards "
                f"({p.get('count')} -> {s.get('count')})"
            )
        hists[name] = {
            "count": dc,
            "sum": round(s.get("sum", 0.0) - p.get("sum", 0.0), 6),
        }
    return {
        "seq": seq_cur,
        "identity": dict(cur.get("identity", {})),
        "counters": counters,
        "gauges": dict(cur.get("gauges", {})),
        "histograms": hists,
    }
