"""Process-wide metrics registry: counters, gauges, histograms.

One flat registry per process, keyed by dotted metric name.  Handles are
cheap and cached (``counter("bench.rows_landed")`` twice returns the
same object), and every mutator checks the telemetry arming flag FIRST:
disarmed, ``inc()``/``set()``/``observe()`` are one global load + one
compare — no lock, no allocation — the same zero-cost-unarmed contract
as ``chaos.checkpoint`` and ``obs.span`` (pinned by tests).  The
registry therefore only accumulates while a collector is armed, which is
exactly when a snapshot can land anywhere.

``snapshot()`` is what bench embeds in every BENCH record (and emits
into the event stream): all registered values, plus the AOT
compile-cache accounting folded in from ``profiling.compile_stats`` —
cache hits/misses, trace vs backend-compile counts — and the
jax.monitoring listener state, both read lazily so a jax-free process
(the bench supervisor) can snapshot without importing jax.
"""

from __future__ import annotations

import sys
import threading

from csmom_tpu.obs import spans as _spans

__all__ = ["counter", "gauge", "histogram", "snapshot", "reset"]

_LOCK = threading.Lock()
_REGISTRY: dict = {}  # name -> metric handle


class Counter:
    """Monotone event count.  ``inc(n)`` is a no-op while disarmed."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if _spans._COLLECTOR is None:
            return
        with _LOCK:
            self.value += n


class Gauge:
    """Last-written value (deadline margin, queue depth, a flag)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, v) -> None:
        if _spans._COLLECTOR is None:
            return
        with _LOCK:
            self.value = v


class Histogram:
    """Streaming summary of observations: count / sum / min / max."""

    __slots__ = ("name", "count", "total", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None

    def observe(self, v: float) -> None:
        if _spans._COLLECTOR is None:
            return
        with _LOCK:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def summary(self) -> dict:
        return {
            "count": self.count,
            "sum": round(self.total, 6),
            "min": self.min,
            "max": self.max,
            "mean": round(self.total / self.count, 6) if self.count else None,
        }


def _get(name: str, cls):
    with _LOCK:
        m = _REGISTRY.get(name)
        if m is None:
            m = _REGISTRY[name] = cls(name)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}"
            )
        return m


def counter(name: str) -> Counter:
    return _get(name, Counter)


def gauge(name: str) -> Gauge:
    return _get(name, Gauge)


def histogram(name: str) -> Histogram:
    return _get(name, Histogram)


def reset() -> None:
    """Drop every registered metric (tests re-register per case)."""
    with _LOCK:
        _REGISTRY.clear()


def snapshot(include_compile: bool = True) -> dict:
    """All registered metrics as one JSON-ready dict.

    ``compile`` folds in the process-global AOT cache / dispatch counters
    from :func:`csmom_tpu.utils.profiling.compile_stats`, read lazily and
    only when jax is already imported — a jax-free supervisor snapshots
    its own registry and records WHY the compile block is absent instead
    of importing a backend to fill it.
    """
    with _LOCK:
        out: dict = {
            "counters": {m.name: m.value for m in _REGISTRY.values()
                         if isinstance(m, Counter)},
            "gauges": {m.name: m.value for m in _REGISTRY.values()
                       if isinstance(m, Gauge)},
            "histograms": {m.name: m.summary() for m in _REGISTRY.values()
                           if isinstance(m, Histogram)},
        }
    # device-memory axis: per-shape memory_analysis bytes captured by the
    # AOT pass (obs.memstats).  Included only when something was captured
    # — a process that never held a Compiled handle has nothing to claim,
    # and an empty block would read as "measured: zero shapes use memory"
    from csmom_tpu.obs import memstats as _memstats

    mem = _memstats.snapshot()
    if mem:
        out["memory"] = mem
    if include_compile:
        if "jax" in sys.modules:
            from csmom_tpu.utils.profiling import (
                compile_stats,
                listeners_installed,
            )

            out["compile"] = compile_stats().as_dict()
            out["profiling_listeners_installed"] = listeners_installed()
        else:
            out["compile"] = ("not applicable: jax not imported in this "
                              "process (supervisor-side snapshot)")
    return out
