"""CI-backed regression verdicts over raw repeat samples.

The Lee-Swaminathan replication standard the paper sets — report point
estimates *with* statistical significance — applies to our own
performance claims too: "r6 is slower than r5" from one wall-clock
sample each is exactly the kind of unquantified claim the paper's
Newey-West t-stats exist to prevent.  This module gives a bench leg's
repeat samples the same treatment the monthly spreads get, by REUSING
the repo's own inference machinery
(:func:`csmom_tpu.analytics.bootstrap.block_bootstrap`): a circular
block bootstrap of the mean (block resampling because consecutive
timing reps share thermal/cache state the way consecutive months share
autocorrelation), percentile CIs, and an interval-overlap test between
the candidate and reference runs.

Verdict vocabulary (what :mod:`csmom_tpu.cli.ledger` prints and gates
on):

``regression``
    CONFIRMED: both runs carry enough raw samples, the bootstrap CIs are
    disjoint in the bad direction, and the point change exceeds
    ``min_rel``.  The only sample-based verdict that fails the gate.
``improvement``
    The mirror image: disjoint CIs in the good direction.
``no-change``
    Overlapping CIs, or a change smaller than ``min_rel`` — the honest
    null.  Noise never fails a gate.
``suspect``
    Point values moved past ``suspect_rel`` but at least one side has no
    (or too few) raw samples, so no CI can back the claim.  Reported,
    never gate-failing: scarce tunnel windows must not be burned
    re-measuring a phantom.
``insufficient-samples`` / ``point-delta``
    Below the change threshold without CI backing.
``memory-growth`` / ``memory-shrink``
    The deterministic axis: compiled memory bytes are exact per
    (shape, backend), so a tolerance band replaces the bootstrap.

Only :func:`bootstrap_mean_ci` touches jax (lazily, CPU-sized arrays);
everything else is plain Python so the ledger CLI stays importable
without a backend.
"""

from __future__ import annotations

__all__ = [
    "bootstrap_mean_ci",
    "compare",
    "compare_memory",
    "compare_points",
    "compare_samples",
    "default_block_len",
    "GATE_FAILING",
    "MIN_SAMPLES",
]

# sample-count floor for a CI to mean anything: below this the bootstrap
# quantiles are dominated by the handful of distinct resample means
MIN_SAMPLES = 5

# verdicts that fail `csmom ledger gate`
GATE_FAILING = ("regression", "memory-growth")

# the single source for the verdict thresholds: function defaults AND
# the CLI's --min-rel/--suspect-rel/--mem-tol defaults read these, so
# policy changes land in one place
DEFAULT_MIN_REL = 0.05      # practical-significance floor (CI verdicts)
DEFAULT_SUSPECT_REL = 0.10  # point-delta drift worth flagging
DEFAULT_MEM_TOL = 0.10      # tolerated relative memory growth


def default_block_len(n: int) -> int:
    """n^(1/3) block rule (the stationary-bootstrap rate), floored at 1
    — short enough that a 5-rep leg still mixes, long enough that
    back-to-back reps sharing cache state stay together."""
    return max(1, int(round(n ** (1.0 / 3.0))))


def bootstrap_mean_ci(samples, n_resamples: int = 1000,
                      block_len: int | None = None,
                      ci_level: float = 0.95, seed: int = 0) -> dict:
    """Percentile CI of the mean of ``samples`` via the repo's circular
    block bootstrap (one fused jit call, vmapped over resamples).

    Returns ``{"n", "point", "lo", "hi", "block_len", "n_resamples",
    "ci_level"}`` with plain floats.
    """
    import jax
    import numpy as np

    from csmom_tpu.analytics.bootstrap import block_bootstrap

    xs = np.asarray([float(s) for s in samples], dtype=np.float64)
    if xs.size == 0:
        raise ValueError("bootstrap_mean_ci needs at least one sample")
    bl = default_block_len(xs.size) if block_len is None else block_len
    res = block_bootstrap(
        xs, np.ones(xs.size, bool), jax.random.PRNGKey(seed),
        n_samples=n_resamples, block_len=bl, ci_level=ci_level,
    )
    lo, hi = (float(v) for v in np.asarray(res.mean_ci))
    return {
        "n": int(xs.size),
        "point": float(np.asarray(res.mean_point)),
        "lo": lo,
        "hi": hi,
        "block_len": bl,
        "n_resamples": int(n_resamples),
        "ci_level": float(ci_level),
    }


def _rel_change(cand: float, ref: float) -> float:
    if ref == 0:
        return float("inf") if cand != ref else 0.0
    return (cand - ref) / abs(ref)


def _is_worse(rel: float, direction: str) -> bool:
    return rel > 0 if direction == "lower" else rel < 0


def compare_samples(cand_samples, ref_samples, direction: str = "lower",
                    min_rel: float = DEFAULT_MIN_REL, n_resamples: int = 1000,
                    ci_level: float = 0.95, seed: int = 0) -> dict:
    """Sampled-vs-sampled verdict: bootstrap both means, test CI overlap.

    ``direction`` is which way is BETTER for this metric: ``"lower"``
    for walls/bytes, ``"higher"`` for throughput.  A regression is only
    confirmed when the intervals are disjoint in the bad direction AND
    the point change exceeds ``min_rel`` — both the statistical and the
    practical significance bar, mirroring how the paper reports spreads.
    """
    cand = bootstrap_mean_ci(cand_samples, n_resamples=n_resamples,
                             ci_level=ci_level, seed=seed)
    ref = bootstrap_mean_ci(ref_samples, n_resamples=n_resamples,
                            ci_level=ci_level, seed=seed + 1)
    rel = _rel_change(cand["point"], ref["point"])
    if direction == "lower":
        cand_worse_disjoint = cand["lo"] > ref["hi"]
        cand_better_disjoint = cand["hi"] < ref["lo"]
    else:
        cand_worse_disjoint = cand["hi"] < ref["lo"]
        cand_better_disjoint = cand["lo"] > ref["hi"]
    if cand_worse_disjoint and abs(rel) >= min_rel:
        verdict = "regression"
    elif cand_better_disjoint and abs(rel) >= min_rel:
        verdict = "improvement"
    else:
        verdict = "no-change"
    return {
        "verdict": verdict,
        "basis": "bootstrap-ci",
        "rel_change": rel,
        "worse": _is_worse(rel, direction),
        "direction": direction,
        "candidate": cand,
        "reference": ref,
    }


def compare_points(cand_value: float, ref_value: float,
                   direction: str = "lower",
                   suspect_rel: float = DEFAULT_SUSPECT_REL,
                   reason: str = "no raw samples",
                   n_cand: int = 1, n_ref: int = 1) -> dict:
    """Point-vs-point comparison: delta only, NEVER a confirmed verdict.

    Without enough repeat samples there is no interval, so the worst
    this can say is ``suspect`` — a pointed invitation to re-measure,
    not a gate failure (single-sample noise must not block a PR).
    ``n_cand``/``n_ref`` report each side's TRUE raw-sample count (a
    bare aggregate counts as 1) so the operator re-measures the run
    that is actually short."""
    rel = _rel_change(cand_value, ref_value)
    worse = _is_worse(rel, direction)
    verdict = "suspect" if worse and abs(rel) >= suspect_rel else "point-delta"
    return {
        "verdict": verdict,
        "basis": f"point-delta ({reason}: CI not computable)",
        "rel_change": rel,
        "worse": worse,
        "direction": direction,
        "candidate": {"point": float(cand_value), "n": max(n_cand, 1)},
        "reference": {"point": float(ref_value), "n": max(n_ref, 1)},
    }


def compare_memory(cand_bytes: int, ref_bytes: int,
                   tol_rel: float = DEFAULT_MEM_TOL) -> dict:
    """Deterministic memory verdict: compiled byte counts are exact per
    (shape, backend), so growth past the tolerance band is a confirmed
    ``memory-growth`` with no bootstrap needed.  A changed workload or
    platform changes the ledger key instead of tripping this — only an
    UNEXPLAINED growth (same shape, same backend, more bytes) fails."""
    rel = _rel_change(float(cand_bytes), float(ref_bytes))
    if rel > tol_rel:
        verdict = "memory-growth"
    elif rel < -tol_rel:
        verdict = "memory-shrink"
    else:
        verdict = "no-change"
    return {
        "verdict": verdict,
        "basis": f"exact-bytes (tolerance ±{tol_rel:.0%})",
        "rel_change": rel,
        "worse": rel > tol_rel,
        "direction": "lower",
        "candidate": {"point": float(cand_bytes), "n": 1},
        "reference": {"point": float(ref_bytes), "n": 1},
    }


def compare(cand_value, ref_value, cand_samples=None, ref_samples=None,
            direction: str = "lower", min_rel: float = DEFAULT_MIN_REL,
            suspect_rel: float = DEFAULT_SUSPECT_REL, min_samples: int = MIN_SAMPLES,
            n_resamples: int = 1000, seed: int = 0) -> dict:
    """Dispatch: CI comparison when both sides carry enough raw samples,
    honest point-delta otherwise (with the reason in ``basis``)."""
    n_c = len(cand_samples) if cand_samples else 0
    n_r = len(ref_samples) if ref_samples else 0
    if n_c >= min_samples and n_r >= min_samples:
        return compare_samples(cand_samples, ref_samples,
                               direction=direction, min_rel=min_rel,
                               n_resamples=n_resamples, seed=seed)
    if n_c or n_r:
        # name each side's count: the operator must re-measure the run
        # that is actually short, not the one that happens to be newer
        reason = (f"candidate has {n_c} raw sample(s), reference has "
                  f"{n_r} (< {min_samples} floor on at least one side)")
    else:
        reason = "no raw samples on either side"
    return compare_points(cand_value, ref_value, direction=direction,
                          suspect_rel=suspect_rel, reason=reason,
                          n_cand=n_c, n_ref=n_r)
