"""Nestable, thread-safe spans emitting a JSON-lines event stream.

A span is a timed block with a name, optional attributes, and a parent —
the innermost open span on the SAME thread (each thread keeps its own
stack, so concurrent threads nest independently instead of parenting
into each other's blocks).  Timestamps are ``time.monotonic()``: the
capture pipeline's clock discipline (utils.deadline) bans the wall clock
from timing paths, and on Linux CLOCK_MONOTONIC is system-wide, so
events appended by a child process compose with the supervisor's on one
timeline.

Usage::

    with span("bench.row", row="grid16.rank") as sp:
        dt = run_leg()
        sp.set(wall_s=dt)

    point("bench.probe", ok=True)          # a durationless event

Device time: ``sp.fetch(y)`` runs the ``profiling.fetch`` device_get
pattern (host-materialize a small result, the only sync that provably
includes execution on tunneled backends) and accumulates the blocking
wall into the span's ``device_s`` — so a span's record separates "time
this block waited on the device" from everything else.

Zero-cost disarmed (the chaos-checkpoint contract): with no collector
armed, ``span()`` returns one shared no-op singleton and ``point()`` is
a single global load — no allocation-visible work per call, pinned by
tests.  Armed, every event is serialized to JSON and appended to the
stream with one flushed write under a lock, so a SIGKILL mid-run loses
at most the event being written — the post-mortem property the chaos
faults exist to defend.

Env contract (how processes in one run share a stream):

- ``CSMOM_TELEMETRY``      ``0``/empty = disarmed; ``1`` = armed
  in-memory (no file); anything else = path of the JSONL event stream
  (opened append — children inherit and interleave whole lines).
- ``CSMOM_TELEMETRY_RUN``  run id stamped on every event (defaults to
  ``<proc>-<pid>``).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

__all__ = [
    "arm",
    "arm_from_env",
    "arm_policy",
    "armed",
    "disarm",
    "point",
    "span",
    "ENV_STREAM",
    "ENV_RUN",
]

ENV_STREAM = "CSMOM_TELEMETRY"
ENV_RUN = "CSMOM_TELEMETRY_RUN"

# the armed collector, or None.  Module-global on purpose: span()/point()
# disarmed must cost one global load + compare, nothing else.
_COLLECTOR = None

_TLS = threading.local()


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


class Collector:
    """Sink for one process's telemetry events (see :func:`arm`).

    Keeps every event in memory (same-process assembly) and, when given a
    path, appends each as one flushed JSON line (cross-process assembly).
    Thread-safe: one lock around sequence allocation and emission.
    """

    def __init__(self, path: str | None, run_id: str, proc: str):
        self.path = path
        self.run_id = run_id
        self.proc = proc
        self.pid = os.getpid()
        self.events: list = []
        self._lock = threading.Lock()
        self._seq = 0
        self._fh = None
        if path:
            try:
                self._fh = open(path, "a", encoding="utf-8")
            except OSError as e:
                # an unwritable stream must not cost the run (the layer's
                # own contract): degrade to in-memory, loudly
                self.path = None
                print(f"[obs] cannot open telemetry stream {path!r} "
                      f"({e}); continuing in-memory", file=sys.stderr)

    def next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def emit(self, event: dict) -> None:
        event.setdefault("run", self.run_id)
        event.setdefault("proc", self.proc)
        event.setdefault("pid", self.pid)
        with self._lock:
            if self._fh is None:
                # in-memory mode (and the fallback of a stream that died
                # mid-run): the list is what assembly reads
                self.events.append(event)
                return
            try:
                # one write + flush per event: a SIGKILL costs at most
                # the line in flight, never the stream.  The file is the
                # single store — assembly reads it back, so a long run
                # does not also accumulate every event dict in RAM.
                self._fh.write(json.dumps(event) + "\n")
                self._fh.flush()
            except (OSError, ValueError):
                self._fh = None  # a dead stream must not kill the run
                self.events.append(event)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class _NullSpan:
    """The disarmed span: one shared instance, every method a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def event(self, name, **attrs):
        return self

    def fetch(self, y):
        from csmom_tpu.utils.profiling import fetch as _fetch

        return _fetch(y)


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_col", "name", "attrs", "seq", "parent", "t0", "t1",
                 "device_s", "_thread")

    def __init__(self, col: Collector, name: str, attrs: dict):
        self._col = col
        self.name = name
        self.attrs = attrs
        self.device_s = 0.0
        self.seq = col.next_seq()
        self.parent = None
        self.t0 = self.t1 = 0.0
        self._thread = threading.get_ident()

    def __enter__(self):
        st = _stack()
        if st:
            self.parent = st[-1].seq
        st.append(self)
        self.t0 = time.monotonic()
        return self

    def __exit__(self, etype, evalue, tb):
        self.t1 = time.monotonic()
        st = _stack()
        if self in st:  # tolerate mis-nesting: drop self and anything above
            del st[st.index(self):]
        rec = {
            "kind": "span",
            "name": self.name,
            "seq": self.seq,
            "parent": self.parent,
            "thread": self._thread,
            "t0_s": round(self.t0, 6),
            "t1_s": round(self.t1, 6),
            "dur_s": round(self.t1 - self.t0, 6),
        }
        if self.device_s:
            rec["device_s"] = round(self.device_s, 6)
        if self.attrs:
            rec["attrs"] = _jsonable(self.attrs)
        if etype is not None:
            rec["error"] = f"{etype.__name__}: {evalue}"[:200]
        self._col.emit(rec)
        return False

    def set(self, **attrs):
        """Attach attributes to this span's record (last write wins)."""
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs):
        """A durationless event parented to this span."""
        _emit_point(self._col, name, attrs, parent=self.seq)
        return self

    def fetch(self, y):
        """``profiling.fetch(y)`` with the blocking wall accumulated into
        this span's ``device_s`` — the device_get timing discipline,
        attributed."""
        from csmom_tpu.utils.profiling import fetch as _fetch

        t0 = time.monotonic()
        out = _fetch(y)
        self.device_s += time.monotonic() - t0
        return out


def _jsonable(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            out[k] = repr(v)[:120]
    return out


def _emit_point(col: Collector, name: str, attrs: dict,
                parent: int | None = None) -> None:
    if parent is None:
        st = _stack()
        parent = st[-1].seq if st else None
    rec = {
        "kind": "point",
        "name": name,
        "seq": col.next_seq(),
        "parent": parent,
        "thread": threading.get_ident(),
        "t_s": round(time.monotonic(), 6),
    }
    if attrs:
        rec["attrs"] = _jsonable(attrs)
    col.emit(rec)


# ------------------------------------------------------------- frontend ----

def span(name: str, **attrs):
    """Open a span (context manager).  Disarmed: the shared no-op
    singleton, no allocation."""
    col = _COLLECTOR
    if col is None:
        return _NULL_SPAN
    return _Span(col, name, attrs)


def point(name: str, **attrs) -> None:
    """Record a durationless event.  Disarmed: a no-op."""
    col = _COLLECTOR
    if col is None:
        return
    _emit_point(col, name, attrs)


def armed() -> bool:
    return _COLLECTOR is not None


def arm(path: str | None = None, run_id: str | None = None,
        proc: str = "main") -> Collector:
    """Arm telemetry for this process; returns the collector.

    ``path``: the JSONL event stream to append to (None = in-memory
    only).  Re-arming replaces the previous collector (closing its
    stream).  Exports ``CSMOM_TELEMETRY``/``CSMOM_TELEMETRY_RUN`` so
    children spawned after this call join the same stream and run id.
    """
    global _COLLECTOR
    if run_id is None:
        run_id = os.environ.get(ENV_RUN) or f"{proc}-{os.getpid()}"
    old, _COLLECTOR = _COLLECTOR, Collector(path, run_id, proc)
    if old is not None:
        old.close()
    # export what the collector actually USES: if the stream open failed
    # and it degraded to in-memory, children must not append to a path
    # the assembler will never read
    os.environ[ENV_STREAM] = _COLLECTOR.path if _COLLECTOR.path else "1"
    os.environ[ENV_RUN] = run_id
    return _COLLECTOR


def disarm() -> None:
    """Close and drop the armed collector (span()/point() become no-ops)
    and retract the env contract :func:`arm` exported, so processes
    spawned later do not join a stream nobody is assembling."""
    global _COLLECTOR
    old, _COLLECTOR = _COLLECTOR, None
    if old is not None:
        old.close()
        os.environ.pop(ENV_STREAM, None)
        os.environ.pop(ENV_RUN, None)


def arm_from_env(proc: str) -> Collector | None:
    """Arm from the env contract, or return None (disarmed).

    The supervisor arms with an explicit path and exports it; children
    call this and join the stream.  ``CSMOM_TELEMETRY`` unset, empty, or
    ``0`` leaves the process disarmed.
    """
    val = os.environ.get(ENV_STREAM, "")
    if not val or val == "0":
        return None
    return arm(None if val == "1" else val,
               run_id=os.environ.get(ENV_RUN), proc=proc)


def arm_policy(proc: str, default_path: str | None = None,
               run_id: str | None = None) -> Collector | None:
    """The ONE arming decision every entry point shares (bench
    supervisor, ``csmom rehearse``, ``csmom warmup``), so the env
    contract cannot drift between copies:

    - ``CSMOM_TELEMETRY=0``: disarmed, full stop;
    - ``CSMOM_TELEMETRY`` set (a path, or ``1``): the operator's
      contract — join it verbatim, including their run id;
    - unset/empty: arm the caller's ``default_path`` when it provides
      one (the default-ON runs) and stay disarmed otherwise (env-armed
      -only entry points like ``csmom warmup``).
    """
    val = os.environ.get(ENV_STREAM, "")
    if val == "0":
        return None
    if val:
        return arm_from_env(proc)
    if default_path is None:
        return None
    return arm(default_path, run_id=run_id, proc=proc)


def current_collector() -> Collector | None:
    return _COLLECTOR
