"""Assemble a telemetry event stream into a per-run phase timeline.

The sidecar (``TELEMETRY_<run>.json``) is the artifact that answers
"where did the time go in this run?" without forensics: the run's wall,
partitioned into the capture pipeline's phases

    warmup -> probe -> compile -> row -> land   (+ other)

plus per-span aggregates and the final metrics snapshot.  It is
schema-validated by :mod:`csmom_tpu.chaos.invariants` (kind
``telemetry``) exactly like the committed BENCH_*/MULTICHIP_* records.

Phase accounting is a sweep, not a sum of span durations: spans nest and
processes overlap (a child's compile spans live inside the supervisor's
attempt span), so naively summing double-counts.  Instead every instant
of the run's wall is assigned to exactly ONE phase — the
highest-priority phase with a span covering that instant (land > row >
compile > probe > warmup), and ``other`` where none does.  Phase
durations therefore partition the wall by construction: their sum equals
``wall_s`` up to rounding, which is the invariant the schema validator
pins (within 5%).

Cross-process composition works because every event's timestamps are
``time.monotonic()`` and CLOCK_MONOTONIC is system-wide on Linux: a
child appending to the supervisor's stream lands its spans at the right
offsets on the same timeline.
"""

from __future__ import annotations

import datetime
import json
import os

__all__ = [
    "PHASES",
    "assemble",
    "finish_and_write",
    "load_sidecar",
    "phase_of",
    "read_events",
    "render",
    "scratch_dir",
    "sidecar_name",
    "write_sidecar",
]

# regenerated (uncommittable) sidecars land here instead of the cwd —
# three TELEMETRY_rehearse*.json once sat at the repo root because every
# rehearse run dropped its sidecar wherever it was launched from.  The
# directory is gitignored as a whole; `csmom timeline` searches it.
SCRATCH_DIRNAME = ".csmom_scratch"

SCHEMA_VERSION = 1

# priority order: when spans of two phases cover the same instant (a
# compile checkpoint inside a measured row, a child's rows inside the
# supervisor's probe loop) the more specific/later pipeline stage wins
PHASES = ("warmup", "probe", "compile", "row", "land")
_PRIORITY = {name: i for i, name in enumerate(PHASES)}


def phase_of(name: str, attrs: dict | None = None) -> str | None:
    """Map an event to its pipeline phase (an explicit ``phase`` attr
    wins; otherwise by name convention, matching the checkpoint
    inventory in chaos.inject)."""
    if attrs:
        p = attrs.get("phase")
        if p in _PRIORITY:
            return p
    n = name.lower()
    if "warmup" in n:
        return "warmup"
    if "probe" in n:
        return "probe"
    if "compile" in n or n.startswith("aot."):
        return "compile"
    if "land" in n or "finish" in n:
        return "land"
    if "row" in n:
        return "row"
    return None


def read_events(path: str) -> list:
    """Parse a JSONL event stream; damaged lines are skipped (the stream
    is append-flushed per event, so at most the killed writer's last line
    is torn)."""
    out = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(ev, dict):
                    out.append(ev)
    except OSError:
        pass
    return out


def _phase_partition(intervals: list, t0: float, t1: float) -> dict:
    """Assign every instant of [t0, t1] to the highest-priority covering
    phase; returns phase -> seconds (plus ``other`` for uncovered time).
    O(n^2) in span count — runs see tens of spans, not thousands."""
    durs = dict.fromkeys((*PHASES, "other"), 0.0)
    if t1 <= t0:
        return durs
    clipped = [(max(a, t0), min(b, t1), ph) for a, b, ph in intervals
               if min(b, t1) > max(a, t0)]
    cuts = sorted({t0, t1, *(a for a, _, _ in clipped),
                   *(b for _, b, _ in clipped)})
    for a, b in zip(cuts, cuts[1:]):
        best = None
        for x, y, ph in clipped:
            if x <= a and y >= b:
                if best is None or _PRIORITY[ph] > _PRIORITY[best]:
                    best = ph
        durs[best or "other"] += b - a
    return durs


def assemble(events: list, run_id: str | None = None,
             metrics: dict | None = None) -> dict:
    """Build the telemetry sidecar object from an event stream.

    ``metrics`` overrides the stream's last ``kind: metrics`` event (the
    assembling process usually snapshots fresher state than anything a
    child emitted).  With an explicit ``run_id``, events stamped with a
    DIFFERENT run are dropped first: an env-armed stream file is opened
    append, so a reused path can carry yesterday's run too, and a
    timeline mixing two runs corresponds to neither."""
    if run_id is not None:
        events = [e for e in events if e.get("run", run_id) == run_id]
    spans = [e for e in events if e.get("kind") == "span"
             and isinstance(e.get("t0_s"), (int, float))
             and isinstance(e.get("t1_s"), (int, float))]
    points = [e for e in events if e.get("kind") == "point"]

    # the run window: the longest root-flagged span (the supervisor's
    # root encloses every child), else the envelope of everything seen
    roots = [s for s in spans if (s.get("attrs") or {}).get("root")]
    if roots:
        root = max(roots, key=lambda s: s["t1_s"] - s["t0_s"])
        t0, t1, root_name = root["t0_s"], root["t1_s"], root["name"]
    elif spans or points:
        stamps = ([s["t0_s"] for s in spans] + [s["t1_s"] for s in spans]
                  + [p["t_s"] for p in points
                     if isinstance(p.get("t_s"), (int, float))])
        t0, t1 = min(stamps), max(stamps)
        root_name = f"envelope of {len(events)} events (no root span)"
    else:
        t0 = t1 = 0.0
        root_name = "empty event stream"

    intervals, phase_spans = [], dict.fromkeys((*PHASES, "other"), 0)
    for s in spans:
        ph = phase_of(s.get("name", ""), s.get("attrs"))
        phase_spans[ph or "other"] += 1
        if ph is not None and not (s.get("attrs") or {}).get("root"):
            intervals.append((s["t0_s"], s["t1_s"], ph))
    phase_points = dict.fromkeys((*PHASES, "other"), 0)
    for p in points:
        ph = phase_of(p.get("name", ""), p.get("attrs"))
        phase_points[ph or "other"] += 1

    wall = t1 - t0
    durs = _phase_partition(intervals, t0, t1)
    phases = [
        {
            "name": ph,
            "dur_s": round(durs[ph], 6),
            "frac": round(durs[ph] / wall, 4) if wall > 0 else 0.0,
            "n_spans": phase_spans[ph],
            "n_points": phase_points[ph],
        }
        for ph in (*PHASES, "other")
    ]

    # per-name aggregates: the flame summary's rows
    agg: dict = {}
    for s in spans:
        a = agg.setdefault(s.get("name", "?"), {
            "name": s.get("name", "?"),
            "phase": phase_of(s.get("name", ""), s.get("attrs")) or "other",
            "count": 0, "total_s": 0.0, "device_s": 0.0, "max_s": 0.0,
            "errors": 0,
        })
        d = s["t1_s"] - s["t0_s"]
        a["count"] += 1
        a["total_s"] += d
        a["device_s"] += s.get("device_s") or 0.0
        a["max_s"] = max(a["max_s"], d)
        a["errors"] += 1 if s.get("error") else 0
    span_rows = sorted(agg.values(), key=lambda a: -a["total_s"])
    for a in span_rows:
        for k in ("total_s", "device_s", "max_s"):
            a[k] = round(a[k], 6)

    if metrics is None:
        for e in reversed(events):
            if e.get("kind") == "metrics" and isinstance(e.get("data"), dict):
                metrics = e["data"]
                break
    run = run_id or next(
        (e["run"] for e in events if isinstance(e.get("run"), str)), "unknown"
    )
    return {
        "kind": "telemetry",
        "schema_version": SCHEMA_VERSION,
        "run_id": run,
        "generated_utc": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "root": root_name,
        "wall_s": round(wall, 6),
        "t0_s": round(t0, 6),
        "t1_s": round(t1, 6),
        "n_events": len(events),
        "n_spans": len(spans),
        "n_points": len(points),
        "n_processes": len({e.get("pid") for e in events}) if events else 0,
        "phases": phases,
        "spans": span_rows,
        "metrics": metrics if metrics is not None else
        "not captured: no metrics snapshot in this run's event stream",
    }


def sidecar_search_roots(explicit_root: str | None = None) -> list:
    """Sidecar resolution order shared by ``csmom timeline`` and
    ``csmom trace`` (one list, so the two commands can never drift): an
    explicit ``--root`` wins outright; otherwise the
    ``CSMOM_TELEMETRY_DIR`` override first, then the cwd and the repo
    checkout (committed round sidecars), each followed by its
    ``.csmom_scratch`` scratch directory (regenerated rehearse/smoke
    sidecars — see :func:`scratch_dir`)."""
    if explicit_root:
        return [explicit_root]
    roots: list = []
    env_dir = os.environ.get("CSMOM_TELEMETRY_DIR")
    if env_dir:
        roots.append(env_dir)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for base in (os.getcwd(), repo):
        roots += [base, os.path.join(base, SCRATCH_DIRNAME)]
    return roots


def scratch_dir(base: str | None = None) -> str:
    """The run-scoped scratch directory for regenerated sidecars
    (rehearse/smoke runs — anything ``invariants.committable_sidecar``
    refuses).  ``CSMOM_TELEMETRY_DIR`` overrides; the default is
    ``<base or cwd>/.csmom_scratch``, created on demand.  Committed
    round evidence (``*_rNN.json``) still lands at the repo root by
    contract — this directory is for everything that must NOT."""
    d = (os.environ.get("CSMOM_TELEMETRY_DIR")
         or os.path.join(base or os.getcwd(), SCRATCH_DIRNAME))
    os.makedirs(d, exist_ok=True)
    return d


def sidecar_name(run_id: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in run_id)
    return f"TELEMETRY_{safe}.json"


def write_sidecar(out_dir: str, run_id: str, events: list | None = None,
                  events_path: str | None = None,
                  metrics: dict | None = None,
                  overwrite: bool = True) -> str:
    """Assemble and atomically land ``TELEMETRY_<run>.json``; returns the
    file name, or a reason string on failure — a telemetry write must
    never take the run's real record down with it.

    ``overwrite=False`` is for runs whose id came from OUTSIDE
    (CSMOM_TELEMETRY_RUN): an operator re-using a round id like ``r05``
    from the repo root must not replace that round's committed sidecar,
    so an existing name is kept and the new run lands pid-suffixed."""
    if events is None:
        events = read_events(events_path) if events_path else []
    obj = assemble(events, run_id=run_id, metrics=metrics)
    name = sidecar_name(run_id)
    path = os.path.join(out_dir, name)
    if not overwrite and os.path.exists(path):
        name = sidecar_name(f"{run_id}-{os.getpid()}")
        path = os.path.join(out_dir, name)
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(obj, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        return name
    except OSError as e:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return f"unwritable ({type(e).__name__}: {e})"[:120]


def load_sidecar(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def finish_and_write(out_dir: str, fallback_metrics: dict | None = None,
                     overwrite: bool = True) -> str:
    """Land the armed collector's run as a sidecar and disarm.

    The one finish sequence every entry point (bench supervisor, `csmom
    rehearse`, `csmom warmup`) shares, so the contract cannot drift
    between copies: read the full stream FILE when there is one (children
    appended there; the in-memory list holds only this process's events),
    let a ``kind: metrics`` event in the stream outrank
    ``fallback_metrics`` (the measurement child's final snapshot beats
    the assembling process's registry), write ``TELEMETRY_<run>.json``
    into ``out_dir``, and disarm whatever happens.  Returns the sidecar
    name, or a reason string — never raises.
    """
    from csmom_tpu.obs import spans as _spans

    col = _spans.current_collector()
    if col is None:
        return "not captured: telemetry disarmed (CSMOM_TELEMETRY=0)"
    try:
        events = read_events(col.path) if col.path else list(col.events)
        # run-scoped, matching assemble()'s filter: a stale metrics event
        # from an older run in a reused stream must not suppress the live
        # fallback snapshot (it would then be dropped by the filter too)
        has_metrics = any(
            e.get("kind") == "metrics"
            and e.get("run", col.run_id) == col.run_id
            for e in events
        )
        return write_sidecar(out_dir, col.run_id, events=events,
                             metrics=None if has_metrics else fallback_metrics,
                             overwrite=overwrite)
    except Exception as e:  # never cost the caller's own record
        return f"telemetry assembly failed: {type(e).__name__}: {e}"[:160]
    finally:
        _spans.disarm()


def render(obj: dict, top: int = 12, width: int = 40) -> str:
    """The text flame summary ``csmom timeline`` prints."""
    wall_raw = obj.get("wall_s")
    wall = wall_raw if isinstance(wall_raw, (int, float)) else 0.0
    lines = [
        f"run {obj.get('run_id')}  wall {wall:.3f}s  "
        f"root {obj.get('root')}",
        f"events {obj.get('n_events')} ({obj.get('n_spans')} spans, "
        f"{obj.get('n_points')} points) across "
        f"{obj.get('n_processes')} process(es)   "
        f"generated {obj.get('generated_utc')}",
        "",
        "phase     dur_s      %   spans  points",
    ]
    # .get throughout: render stays best-effort on a damaged sidecar so
    # cmd_timeline can still print the schema violations after it
    for ph in obj.get("phases", []):
        if not isinstance(ph, dict):
            continue
        frac = ph.get("frac") or 0.0
        bar = "#" * max(1 if (ph.get("dur_s") or 0) > 0 else 0,
                        int(round(frac * width)))
        lines.append(
            f"{ph.get('name', '?'):<8} {ph.get('dur_s') or 0.0:>8.3f} "
            f"{frac:>6.1%}  {ph.get('n_spans', 0):>5}  "
            f"{ph.get('n_points', 0):>6}  {bar}"
        )
    rows = [a for a in obj.get("spans", []) if isinstance(a, dict)]
    if rows:
        lines += ["", f"top spans by total wall (of {len(rows)}):"]
        for a in rows[:top]:
            total = a.get("total_s") or 0.0
            dev = (f"  device {a['device_s']:.3f}s"
                   if a.get("device_s") else "")
            err = f"  errors {a['errors']}" if a.get("errors") else ""
            share = f" {total / wall:>6.1%}" if wall > 0 else ""
            lines.append(
                f"  {a.get('name', '?'):<34} {a.get('count', 0):>3}x "
                f"{total:>9.3f}s{share}  [{a.get('phase', '?')}]{dev}{err}"
            )
    m = obj.get("metrics")
    if isinstance(m, dict):
        bits = []
        for k, v in (m.get("counters") or {}).items():
            bits.append(f"{k}={v}")
        for k, v in (m.get("gauges") or {}).items():
            bits.append(f"{k}={v}")
        comp = m.get("compile")
        if isinstance(comp, dict):
            bits.append(f"cache_hits={comp.get('cache_hits')}")
            bits.append(f"cache_misses={comp.get('cache_misses')}")
            bits.append(f"backend_compiles={comp.get('backend_compiles')}")
        if bits:
            lines += ["", "metrics: " + "  ".join(str(b) for b in bits)]
    return "\n".join(lines)
