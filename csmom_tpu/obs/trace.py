"""Per-request tracing across the serving fabric: stage clocks, stitched
cross-process halves, closed trace books, and the TRACE artifact.

``SERVE_MESH_r15.json`` says "p99 was 13.6 ms" — one opaque number.  This
module makes the number decomposable per request: a :class:`TraceContext`
is minted at admission (trace id, endpoint, SLO class, panel version) and
threaded through the whole request path — admission queue, adaptive
batcher, engine dispatch, result fan-out — and ACROSS the process
boundary through ``serve/proto.py`` frames, so the router and the worker
emit stitchable span halves.  Tail at Scale (PAPERS [3]) argues a tail
must be *decomposed* before it can be engineered; this is the
decomposition, as gate-able evidence instead of prose.

Stage clocks are **telescoping monotonic marks**: every stage boundary is
one ``mono_now_s()`` stamp, and a stage's duration is the difference of
consecutive stamps — so the per-stage walls sum to the request wall
EXACTLY by construction (the artifact's ``reconcile`` block measures the
residual anyway; the schema pins it under epsilon).  The in-process
chain::

    admit -> queue_wait -> coalesce -> pad -> dispatch -> serialize

and the pool adds the router-side half::

    route -> transport -> <worker half, stitched> -> finalize

where ``transport`` is the winning attempt's wall minus the worker's own
reported wall (framing + socket both ways), so the stitched sum still
telescopes to the router-observed request wall.

**Closed trace books**: every request the book opened ends in exactly one
``complete`` (served, full stage chain) or one ``partial`` (rejected /
expired / crashed, closed WITH the reason).  A SIGKILLed worker's
in-flight dispatch produces no reply half — the router closes that
attempt as an **orphan half** with the connection failure as the reason
(counted per reason in the artifact), and the request's own trace closes
complete (failover won) or partial (every avenue exhausted).  The book's
``invariant_violations()`` is the mechanical check; the ``trace``
artifact schema (:mod:`csmom_tpu.chaos.invariants`) enforces it on
committed evidence, including reconciliation against the matching SERVE
artifact's request books (``complete == served``,
``partial == rejected + expired``).

Zero-cost disarmed (the ``obs/spans.py`` discipline, pinned by tests):
with no book armed, :func:`begin` returns one shared no-op singleton and
every mark/close is a method call on it — no allocation, no clock read.
The serve call sites additionally guard on ``req.trace is not None`` so
requests constructed outside a service cost nothing at all.

Stdlib-only and ``mono_now_s``-only (the clock-discipline lint pins this
module into the serve timing tier): one clock rules deadlines, recorded
latencies, AND the trace decomposition, so the stages are subtractable
from the same p99 the SLO gate reads.
"""

from __future__ import annotations

import heapq
import itertools
import math
import os
import random
import threading

from csmom_tpu.utils.deadline import mono_now_s

__all__ = [
    "EPSILON_MS",
    "SCHEMA_VERSION",
    "STAGES",
    "TRANSPORT_SUBSTAGES",
    "TraceBook",
    "TraceContext",
    "arm_tracing",
    "begin",
    "build_artifact",
    "current_book",
    "disarm_tracing",
    "note_batch",
    "tracing_armed",
]

SCHEMA_VERSION = 1

# the canonical stage vocabulary, in request-path order.  The router-side
# stages (route/connect/send/recv_wait/finalize) only appear on
# pool-stitched traces; mesh shard placement rides as trace ATTRS
# (devices/shards), because XLA executes a sharded dispatch as one
# program — the per-shard split is an attribute of the dispatch stage,
# not a separable wall.
#
# ``transport`` (ISSUE 15): since the persistent-channel round the wire
# wall is recorded as three TELESCOPING sub-stages — ``connect`` (channel
# acquisition: a pool hit is ~0, a dial pays the handshake), ``send``
# (frame fully written), ``recv_wait`` (reply wall minus the peer's own
# reported wall) — so the r18 connection-per-request bill is attributable
# to its component.  The book still aggregates their per-trace SUM under
# the DERIVED ``transport`` stage (it is not part of any trace's
# telescoping chain, so stage sums still reconcile with the request wall)
# to keep the ``trace_stage_transport_p99_ms`` trajectory comparable
# across r17/r18/r19.  Pre-r19 halves that recorded a flat ``transport``
# stage still stitch verbatim.
STAGES = ("admit", "queue_wait", "coalesce", "pad", "dispatch",
          "serialize", "route", "connect", "send", "recv_wait",
          "transport", "finalize")

# the wire sub-stages whose per-trace sum IS the derived transport wall
TRANSPORT_SUBSTAGES = ("connect", "send", "recv_wait")

# the auto-label for the residual a close() stamps: the stage that FOLLOWS
# the last recorded mark (a request rejected while queued closes its
# residual as queue_wait, a crash after pad closes it as dispatch, a
# served dispatch closes it as serialize)
_NEXT_STAGE = {
    None: "admit",
    "admit": "queue_wait",
    "queue_wait": "coalesce",
    "coalesce": "pad",
    "pad": "dispatch",
    "dispatch": "serialize",
    "serialize": "finalize",
}

# reconciliation tolerance: stage sums telescope exactly in float64, so
# the only residual is serialization rounding (6 decimals) — 2 ms is two
# orders of magnitude of headroom and still far under any stage wall
EPSILON_MS = 2.0

# bounded per-stage / per-class sample reservoirs (the artifact's CI
# backing); slowest-k critical paths kept for the decomposition CLI
_RESERVOIR_CAP = 256
_SLOWEST_K = 8

_TRACE_IDS = itertools.count(1)

# the armed book, or None.  Module-global on purpose (the spans
# discipline): begin() disarmed must cost one global load + compare.
_BOOK = None


class _NullTrace:
    """The disarmed trace: one shared instance, every method a no-op."""

    __slots__ = ()

    live = False      # call sites skip per-request trace work entirely

    def mark(self, stage):
        return self

    def set(self, **attrs):
        return self

    def note_orphan(self, worker_id, reason):
        return self

    def absorb_remote(self, half, t_start_s, t_end_s, worker_id=None,
                      t_acquired_s=None, t_sent_s=None):
        return self

    def close(self, outcome, reason=None, stage=None):
        return self

    def close_routed(self, outcome, t_done_s, reason=None):
        return self

    def to_wire(self):
        return None

    def half_record(self):
        return None


_NULL_TRACE = _NullTrace()


class TraceContext:
    """One request's trace: identity, stage marks, outcome.

    Not a general-purpose span tree — a straight-line stage chain sized
    for the serve request path, cheap enough to mint per request.  Marks
    are appended from the submit thread and then the dispatch thread; the
    queue's exactly-once terminal transition is the only closer, so no
    lock is needed on the chain itself.
    """

    __slots__ = ("trace_id", "endpoint", "slo_class", "panel_version",
                 "budget_ms", "t0_s", "marks", "attrs", "orphans",
                 "outcome", "reason", "stage_durs_s", "wall_s",
                 "_remote", "_book", "_olock")

    live = True

    def __init__(self, endpoint: str, slo_class: str,
                 panel_version: int | None = None,
                 budget_ms: float | None = None,
                 trace_id: str | None = None, book=None):
        if trace_id is None:
            trace_id = f"t{os.getpid()}-{next(_TRACE_IDS):06d}"
        self.trace_id = trace_id
        self.endpoint = endpoint
        self.slo_class = slo_class
        self.panel_version = panel_version
        self.budget_ms = budget_ms
        self.t0_s = mono_now_s()
        self.marks: list = []          # [(stage, t_s)], telescoping
        self.attrs: dict = {}
        self.orphans: list = []        # [(worker_id, reason)], pool halves
        self.outcome: str | None = None
        self.reason: str | None = None
        self.stage_durs_s: dict | None = None   # set at close
        self.wall_s: float | None = None
        self._remote = None            # (half, t_start, t_end, worker_id)
        self._book = book
        # guards the outcome transition vs note_orphan: a hedge loser's
        # connection failure races the winner's close on another thread
        self._olock = threading.Lock()

    # ------------------------------------------------------------- marks --

    def mark(self, stage: str):
        """Stamp one stage boundary (duration = delta to the previous
        mark, so stage walls telescope to the request wall)."""
        self.marks.append((stage, mono_now_s()))
        return self

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def note_orphan(self, worker_id: str | None, reason: str):
        """A dispatch half that will never be stitched: the peer died (or
        reset) before replying.  Recorded with the reason so the book can
        close the orphan ledger instead of losing the attempt.  A hedge
        loser that fails AFTER the request already closed still reaches
        the book directly — late orphans must not leak either.  The
        check-then-append runs under ``_olock`` (the same lock ``_finish``
        sets the outcome under): an orphan noted concurrently with the
        winning attempt's close must land either in ``orphans`` before
        the book snapshots it or in ``record_orphan`` — never nowhere."""
        wid = worker_id or "?"
        why = str(reason)[:160]
        with self._olock:
            if self.outcome is None:
                self.orphans.append((wid, why))
                return self
        if self._book is not None:
            self._book.record_orphan(wid, why)
        return self

    def absorb_remote(self, half: dict, t_start_s: float, t_end_s: float,
                      worker_id: str | None = None,
                      t_acquired_s: float | None = None,
                      t_sent_s: float | None = None):
        """Attach the worker's reply half (the server-side stage chain)
        plus the client-observed attempt window, for close-time
        stitching.  ``t_acquired_s`` / ``t_sent_s`` are the channel
        layer's marks (channel in hand; frame fully written) — when
        present, close-time stitching splits the wire wall into
        connect / send / recv_wait instead of one flat ``transport``.
        Last write wins — only the winning attempt's absorb survives to
        the terminal transition."""
        self._remote = (half, t_start_s, t_end_s, worker_id,
                        t_acquired_s, t_sent_s)
        return self

    # ------------------------------------------------------------- close --

    def close(self, outcome: str, reason: str | None = None,
              stage: str | None = None):
        """Terminal transition (exactly-once: a closed trace never moves).

        The residual since the last mark lands under ``stage`` (default:
        the stage that follows the last mark — see ``_NEXT_STAGE``).
        ``complete`` iff ``outcome == "served"``; anything else is a
        partial and MUST carry a reason (the closed-books contract).
        """
        if self.outcome is not None:
            return self
        last = self.marks[-1][0] if self.marks else None
        self.mark(stage or _NEXT_STAGE.get(last, "finalize"))
        self._finish(outcome, reason)
        return self

    def close_routed(self, outcome: str, t_done_s: float,
                     reason: str | None = None):
        """The router's stitched close: build the full chain from the
        client-observed window plus the absorbed worker half.

        ``route`` covers submit -> winning-attempt start; the wire wall
        (attempt wall minus the worker's own reported wall) lands as
        the channel marks allow — split into ``connect`` (channel
        acquired) / ``send`` (frame written) / ``recv_wait`` (the
        remainder) when the pooled transport reported its marks, or as
        one flat ``transport`` stage for a markless (pre-r19) attempt;
        the worker's stages ride verbatim in between, and ``finalize``
        covers the reply's fan-back — so the sum telescopes to the
        router-observed request wall exactly.  Without an absorbed half
        (every attempt failed, or the request never dispatched) the
        whole wall lands under ``route`` with the reason.
        """
        if self.outcome is not None:
            return self
        durs: dict = {}
        if self._remote is not None:
            half, t_start, t_end, worker_id, t_acq, t_sent = self._remote
            server = dict((half or {}).get("stages") or {})
            server_wall = sum(server.values())
            durs["route"] = max(0.0, t_start - self.t0_s)
            if t_acq is not None and t_sent is not None:
                # the channel marks split the wire wall (attempt window
                # minus the peer's own reported wall) into connect /
                # send / recv_wait.  The wire wall is authoritative;
                # the marks are stamps from ANOTHER thread's schedule
                # and can skew a few ms late under load, so connect and
                # send are clamped INTO the available wire wall (skew
                # lands in the stage whose stamp drifted, and the sum
                # still telescopes to the request wall exactly)
                wire_s = max(0.0, (t_end - t_start) - server_wall)
                connect_s = min(max(0.0, t_acq - t_start), wire_s)
                send_s = min(max(0.0, t_sent - t_acq),
                             wire_s - connect_s)
                durs["connect"] = connect_s
                durs["send"] = send_s
                for k, v in server.items():
                    durs[k] = durs.get(k, 0.0) + v
                durs["recv_wait"] = (durs.get("recv_wait", 0.0)
                                     + (wire_s - connect_s - send_s))
            else:
                durs["transport"] = max(0.0,
                                        (t_end - t_start) - server_wall)
                for k, v in server.items():
                    durs[k] = durs.get(k, 0.0) + v
            durs["finalize"] = durs.get("finalize", 0.0) + max(
                0.0, t_done_s - t_end)
            if worker_id is not None:
                self.attrs.setdefault("worker", worker_id)
            for k, v in ((half or {}).get("attrs") or {}).items():
                self.attrs.setdefault(k, v)
        else:
            durs["route"] = max(0.0, t_done_s - self.t0_s)
        self.stage_durs_s = durs
        self.wall_s = max(0.0, t_done_s - self.t0_s)
        self._finish(outcome, reason, prebuilt=True)
        return self

    def _finish(self, outcome: str, reason: str | None,
                prebuilt: bool = False) -> None:
        # the outcome flip is the linearization point note_orphan races
        # against: after the lock releases, late orphans go straight to
        # the book, and the record() below reads a stable orphans list
        with self._olock:
            self.outcome = outcome
        if reason is not None:
            self.reason = str(reason)[:200]
        if not prebuilt:
            durs: dict = {}
            prev = self.t0_s
            for stage, t in self.marks:
                durs[stage] = durs.get(stage, 0.0) + max(0.0, t - prev)
                prev = t
            self.stage_durs_s = durs
            self.wall_s = max(0.0, (self.marks[-1][1] if self.marks
                                    else self.t0_s) - self.t0_s)
        if self._book is not None:
            self._book.record(self)

    # -------------------------------------------------------------- wire --

    def to_wire(self) -> dict:
        """The context fields that cross the proto boundary (the frame
        header's ``trace`` entry) — identity only, never timing: each
        side's clocks stay local and stitching works on durations."""
        return {
            "trace_id": self.trace_id,
            "endpoint": self.endpoint,
            "slo_class": self.slo_class,
            "panel_version": self.panel_version,
            "budget_ms": self.budget_ms,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "TraceContext":
        """Rebuild the server-side half of a wire-carried context.  The
        request said "trace me", so the half exists even in a process
        with no armed book — its record rides back in the reply frame."""
        return cls(
            endpoint=str(d.get("endpoint")),
            slo_class=str(d.get("slo_class")),
            panel_version=d.get("panel_version"),
            budget_ms=d.get("budget_ms"),
            trace_id=str(d.get("trace_id")),
        )

    def half_record(self) -> dict | None:
        """This (closed) context as a reply-frame half: the server-side
        stage chain the router stitches.  None until closed — a torn half
        must not be mistaken for a measured one."""
        if self.outcome is None or self.stage_durs_s is None:
            return None
        return {
            "trace_id": self.trace_id,
            "outcome": self.outcome,
            "stages": {k: round(v, 6)
                       for k, v in self.stage_durs_s.items()},
            "wall_s": round(self.wall_s or 0.0, 6),
            "attrs": dict(self.attrs),
        }


class _Reservoir:
    """Bounded uniform sample reservoir (algorithm R), seeded for
    reproducible committed artifacts.  ``samples`` emits the surviving
    subset in ARRIVAL order — the same contract as loadgen's
    ``_bounded_samples`` (sorted index subsample): the ledger feeds
    these to the block bootstrap, which assumes consecutive samples
    share state, so overwriting random slots must not shuffle early
    observations after late ones."""

    __slots__ = ("cap", "n", "_pairs", "_rng")

    def __init__(self, cap: int = _RESERVOIR_CAP, seed: int = 0):
        self.cap = cap
        self.n = 0
        self._pairs: list = []          # [(arrival_seq, value)]
        self._rng = random.Random(seed)

    def add(self, v: float) -> None:
        self.n += 1
        if len(self._pairs) < self.cap:
            self._pairs.append((self.n, v))
        else:
            j = self._rng.randrange(self.n)
            if j < self.cap:
                self._pairs[j] = (self.n, v)

    @property
    def samples(self) -> list:
        return [v for _, v in sorted(self._pairs)]


def _percentiles_ms(samples: list) -> dict:
    """Nearest-rank p50/p95/p99 in ms (the loadgen rule, shared shape)."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None}
    s = sorted(samples)

    def pick(q):
        return round(1e3 * s[max(0, math.ceil(q * len(s)) - 1)], 3)

    return {"p50": pick(0.50), "p95": pick(0.95), "p99": pick(0.99)}


class TraceBook:
    """Aggregates every trace of one run into closed books.

    Thread-safe (one leaf lock; never calls out while holding it — the
    lock-order audit stays acyclic).  Holds bounded state only: stage
    reservoirs, per-class reservoirs, a slowest-k heap, counters — a
    million-request run costs the same memory as a thousand-request one.
    """

    def __init__(self, slo_target: float = 0.99, seed: int = 0):
        self.slo_target = float(slo_target)
        self._lock = threading.Lock()
        self._seed = seed
        self.opened = 0
        self.complete = 0
        self.partial = 0
        self.partial_reasons: dict = {}
        self.orphan_halves = 0
        self.orphan_reasons: dict = {}
        self._stage_res: dict = {}          # stage -> _Reservoir (seconds)
        self._stage_tot: dict = {}          # stage -> [count, total, max]
        self._class_res: dict = {}          # class -> _Reservoir (seconds)
        self._class_book: dict = {}         # class -> {count, served,
        #                                     violations, budget_ms}
        self._slowest: list = []            # min-heap of (wall, seq, entry)
        self._slow_seq = itertools.count()
        self._buckets: dict = {}            # (kind, B, A) -> pad book
        self.reconcile_checked = 0
        self.reconcile_violations = 0
        self.max_abs_residual_ms = 0.0

    # ------------------------------------------------------------ feeding --

    def open_trace(self, ctx: TraceContext) -> TraceContext:
        with self._lock:
            self.opened += 1
        ctx._book = self
        return ctx

    def record(self, ctx: TraceContext) -> None:
        """Fold one CLOSED trace into the books (called from the trace's
        terminal transition, exactly once by its guard)."""
        durs = ctx.stage_durs_s or {}
        wall = ctx.wall_s or 0.0
        residual_ms = abs(sum(durs.values()) - wall) * 1e3
        entry = None
        if ctx.outcome == "served":
            entry = {
                "trace_id": ctx.trace_id,
                "endpoint": ctx.endpoint,
                "class": ctx.slo_class,
                "wall_ms": round(wall * 1e3, 3),
                "stages": {k: round(v * 1e3, 3) for k, v in durs.items()},
                "attrs": dict(ctx.attrs),
            }
        # the DERIVED transport wall (ISSUE 15): the sub-stage sum of a
        # channel-stitched trace, aggregated under "transport" so the
        # r17/r18 trajectory row keeps its meaning — derived only, never
        # written into the trace's own telescoping chain (stage sums
        # must still reconcile with the request wall)
        fold = dict(durs)
        if "transport" not in fold:
            sub = [fold[k] for k in TRANSPORT_SUBSTAGES if k in fold]
            if sub:
                fold["transport"] = sum(sub)
        with self._lock:
            if ctx.outcome == "served":
                self.complete += 1
                for stage, d in fold.items():
                    res = self._stage_res.get(stage)
                    if res is None:
                        res = self._stage_res[stage] = _Reservoir(
                            seed=self._seed + len(self._stage_res))
                        self._stage_tot[stage] = [0, 0.0, 0.0]
                    res.add(d)
                    tot = self._stage_tot[stage]
                    tot[0] += 1
                    tot[1] += d
                    tot[2] = max(tot[2], d)
                cres = self._class_res.get(ctx.slo_class)
                if cres is None:
                    cres = self._class_res[ctx.slo_class] = _Reservoir(
                        seed=self._seed + 101 + len(self._class_res))
                cres.add(wall)
                book = self._class_book.setdefault(ctx.slo_class, {
                    "count": 0, "served": 0, "violations": 0,
                    "budget_ms": ctx.budget_ms,
                })
                book["count"] += 1
                book["served"] += 1
                if book["budget_ms"] is None:
                    book["budget_ms"] = ctx.budget_ms
                if (ctx.budget_ms is not None
                        and wall * 1e3 > ctx.budget_ms):
                    book["violations"] += 1
                heapq.heappush(self._slowest,
                               (wall, next(self._slow_seq), entry))
                if len(self._slowest) > _SLOWEST_K:
                    heapq.heappop(self._slowest)
            else:
                self.partial += 1
                key = (ctx.reason or ctx.outcome or "unknown")[:80]
                self.partial_reasons[key] = \
                    self.partial_reasons.get(key, 0) + 1
                book = self._class_book.setdefault(ctx.slo_class, {
                    "count": 0, "served": 0, "violations": 0,
                    "budget_ms": ctx.budget_ms,
                })
                book["count"] += 1
            for worker_id, reason in ctx.orphans:
                self.orphan_halves += 1
                key = f"{worker_id}: {reason}"[:120]
                self.orphan_reasons[key] = \
                    self.orphan_reasons.get(key, 0) + 1
            self.reconcile_checked += 1
            self.max_abs_residual_ms = max(self.max_abs_residual_ms,
                                           residual_ms)
            if residual_ms > EPSILON_MS:
                self.reconcile_violations += 1

    def record_orphan(self, worker_id: str, reason: str) -> None:
        """A late orphan half (the owning trace already closed)."""
        with self._lock:
            self.orphan_halves += 1
            key = f"{worker_id}: {reason}"[:120]
            self.orphan_reasons[key] = self.orphan_reasons.get(key, 0) + 1

    def note_batch(self, kind: str, batch_bucket: int, asset_bucket: int,
                   used_lanes: int, pad_lanes: int,
                   fire_reason: str) -> None:
        """One dispatched micro-batch's padding record, keyed by its
        bucket — the goodput-per-bucket book the CLI renders."""
        with self._lock:
            b = self._buckets.setdefault((kind, batch_bucket, asset_bucket), {
                "batches": 0, "used_lanes": 0, "pad_lanes": 0,
                "fire_reasons": {},
            })
            b["batches"] += 1
            b["used_lanes"] += used_lanes
            b["pad_lanes"] += pad_lanes
            b["fire_reasons"][fire_reason] = \
                b["fire_reasons"].get(fire_reason, 0) + 1

    # ----------------------------------------------------------- reading --

    def invariant_violations(self) -> list:
        """The closed-trace-books check (empty = holds)."""
        with self._lock:
            out = []
            if self.complete + self.partial != self.opened:
                out.append(
                    f"trace books broken: complete {self.complete} + "
                    f"partial {self.partial} = "
                    f"{self.complete + self.partial} != opened "
                    f"{self.opened} — a request's trace never closed")
            if self.reconcile_violations:
                out.append(
                    f"{self.reconcile_violations} trace(s) whose stage "
                    f"walls do not sum to the request wall within "
                    f"{EPSILON_MS} ms (max residual "
                    f"{self.max_abs_residual_ms:.3f} ms)")
            return out

    def snapshot(self) -> dict:
        """The books as one JSON-ready dict (the TRACE artifact's core)."""
        with self._lock:
            stages = {}
            for stage, res in self._stage_res.items():
                count, total, mx = self._stage_tot[stage]
                stages[stage] = {
                    "count": count,
                    "total_s": round(total, 6),
                    "max_ms": round(mx * 1e3, 3),
                    **_percentiles_ms(res.samples),
                }
            from csmom_tpu.obs.metrics import budget_burn

            classes = {}
            for name, book in self._class_book.items():
                res = self._class_res.get(name)
                lat = _percentiles_ms(res.samples if res else [])
                burn = budget_burn(book["served"], book["violations"],
                                   self.slo_target)
                classes[name] = {
                    **book,
                    "latency_ms": lat,
                    "slo_target": self.slo_target,
                    "budget_burn": burn,
                }
            slowest = [e for _, _, e in
                       sorted(self._slowest, key=lambda t: -t[0])]
            padding = {
                f"{k}:b{B}xa{A}": dict(v, pad_fraction=round(
                    v["pad_lanes"]
                    / max(1, v["pad_lanes"] + v["used_lanes"]), 4))
                for (k, B, A), v in sorted(self._buckets.items())
            }
            return {
                "books": {
                    "opened": self.opened,
                    "complete": self.complete,
                    "partial": self.partial,
                    "partial_reasons": dict(sorted(
                        self.partial_reasons.items())),
                },
                "orphans": {
                    "count": self.orphan_halves,
                    "reasons": dict(sorted(self.orphan_reasons.items())),
                },
                "stages": stages,
                "classes": classes,
                "slowest": slowest,
                "padding": padding,
                "reconcile": {
                    "checked": self.reconcile_checked,
                    "violations": self.reconcile_violations,
                    "max_abs_residual_ms": round(
                        self.max_abs_residual_ms, 4),
                    "epsilon_ms": EPSILON_MS,
                },
            }

    def stage_samples_ms(self) -> dict:
        """Bounded per-stage reservoir samples in ms, keyed by the ledger
        metric each backs — future TRACE rows get bootstrap CIs instead
        of point-delta verdicts."""
        with self._lock:
            return {
                f"trace_stage_{stage}_p99_ms": [
                    round(v * 1e3, 4) for v in res.samples]
                for stage, res in self._stage_res.items()
            }


# ------------------------------------------------------------- frontend ----

def tracing_armed() -> bool:
    return _BOOK is not None


def current_book() -> TraceBook | None:
    return _BOOK


def arm_tracing(book: TraceBook | None = None, **kwargs) -> TraceBook:
    """Arm request tracing for this process; returns the book.  Re-arming
    replaces the previous book (its traces stay with it)."""
    global _BOOK
    _BOOK = book if book is not None else TraceBook(**kwargs)
    return _BOOK


def disarm_tracing() -> None:
    """Drop the armed book: ``begin()`` returns the shared no-op again."""
    global _BOOK
    _BOOK = None


def begin(endpoint: str, slo_class: str, panel_version: int | None = None,
          budget_ms: float | None = None):
    """Mint a trace context (disarmed: the shared no-op singleton, no
    allocation, no clock read)."""
    book = _BOOK
    if book is None:
        return _NULL_TRACE
    return book.open_trace(TraceContext(
        endpoint, slo_class, panel_version=panel_version,
        budget_ms=budget_ms))


def note_batch(kind: str, batch_bucket: int, asset_bucket: int,
               used_lanes: int, pad_lanes: int, fire_reason: str) -> None:
    """Record one micro-batch's padding record (disarmed: a no-op)."""
    book = _BOOK
    if book is None:
        return
    book.note_batch(kind, batch_bucket, asset_bucket, used_lanes,
                    pad_lanes, fire_reason)


# ------------------------------------------------------------- artifact ----

def build_artifact(book: TraceBook, run_id: str,
                   requests: dict | None = None,
                   fresh_compiles=None,
                   platform: str | None = None,
                   workload: str | None = None,
                   extra: dict | None = None) -> dict:
    """The TRACE artifact (kind ``trace``, schema v1): closed trace books
    + per-stage decomposition + per-class burn + padding goodput, plus
    the matching serve run's request book so the two ledgers reconcile
    BY SCHEMA (``complete == served``, ``partial == rejected +
    expired``)."""
    snap = book.snapshot()
    ex = {
        "platform": platform,
        "workload": workload,
        "samples": book.stage_samples_ms(),
        **(extra or {}),
    }
    return {
        "kind": "trace",
        "schema_version": SCHEMA_VERSION,
        "run_id": run_id,
        "metric": "trace_complete_traces",
        "value": snap["books"]["complete"],
        "unit": "traces",
        "vs_baseline": 1.0,
        **snap,
        "requests": dict(requests) if requests else None,
        "compile": {
            "in_window_fresh_compiles": fresh_compiles,
            "note": "copied from the driven serve run: the trace window "
                    "IS the serving window, so 0 here means the "
                    "decomposition never includes a fresh compile",
        },
        "extra": ex,
    }
