"""Core jit-compiled kernels over masked panels.

Everything in this package is a pure function of arrays, safe under
``jit`` / ``vmap`` / ``shard_map``: static shapes, no data-dependent Python
control flow, masks instead of row drops.
"""

from csmom_tpu.ops.rolling import (
    rolling_sum,
    rolling_mean,
    rolling_std,
    rolling_count,
)
from csmom_tpu.ops.ranking import (
    decile_assign,
    decile_assign_panel,
    sector_decile_assign,
    sector_decile_assign_panel,
)

__all__ = [
    "rolling_sum",
    "rolling_mean",
    "rolling_std",
    "rolling_count",
    "decile_assign",
    "decile_assign_panel",
    "sector_decile_assign",
    "sector_decile_assign_panel",
]
