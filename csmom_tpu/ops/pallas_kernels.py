"""Pallas TPU kernels for the portfolio-aggregation hot op.

The XLA implementation of :func:`csmom_tpu.backtest.monthly.
decile_partial_sums` materializes a one-hot membership tensor
``[B, A, M]`` (10x the panel) before reducing over assets.  XLA usually
fuses it, but at north-star scale (A=3000, M=720, B=10, and x16 grid cells
under vmap) the fusion boundary with the surrounding roll/where ops is
fragile.  This kernel computes the same ``(sums, counts)`` with an explicit
tiling: stream ``[block_a, block_t]`` tiles of (labels, returns) through
VMEM once, accumulate all B bins into a resident ``[B, block_t]`` output
tile — O(A*M) HBM traffic, no [B, A, M] intermediate ever exists.

Contract (same as the XLA version):
  labels i32[A, M] with -1 meaning "not a member of any bin" (invalid lanes
  are pre-folded into -1 by the caller); ret f32[A, M] pre-zeroed at
  invalid slots.  Returns (sums f32[B, M], counts f32[B, M]).

The asset axis is the *last* grid dimension, so consecutive grid steps
revisit the same output tile (sequential TPU grid), which makes the
accumulate-across-tiles pattern valid; the first asset-tile initializes.
``interpret=True`` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(lab_ref, ret_ref, sums_ref, counts_ref, *, n_bins: int):
    a_tile = pl.program_id(1)

    @pl.when(a_tile == 0)
    def _init():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)

    lab = lab_ref[...]
    ret = ret_ref[...]
    for b in range(n_bins):  # static unroll: B rows of the resident tile
        mem = (lab == b).astype(ret.dtype)
        sums_ref[b, :] += jnp.sum(ret * mem, axis=0)
        counts_ref[b, :] += jnp.sum(mem, axis=0)


@partial(jax.jit, static_argnames=("n_bins", "block_a", "block_t", "interpret"))
def decile_partial_sums_pallas(
    ret,
    labels,
    n_bins: int = 10,
    block_a: int = 256,
    block_t: int = 128,
    interpret: bool = False,
):
    """Fused per-(bin, date) sums/counts over the asset axis.

    Args:
      ret: f[A, M] next-period returns, zeroed where invalid.
      labels: i32[A, M] bin ids, -1 where unranked/invalid.
      n_bins: number of bins B.
      block_a/block_t: VMEM tile sizes (asset x time).
      interpret: run in pallas interpreter mode (CPU tests).

    Returns (sums f[B, M], counts f[B, M]) with counts in ret's dtype.
    """
    A, M = ret.shape
    dt = ret.dtype
    block_a = min(block_a, max(A, 8))
    block_t = min(block_t, max(M, 128))
    pad_a = (-A) % block_a
    pad_t = (-M) % block_t
    if pad_a or pad_t:
        # padded lanes carry label -1 / ret 0 -> contribute to no bin
        labels = jnp.pad(labels, ((0, pad_a), (0, pad_t)), constant_values=-1)
        ret = jnp.pad(ret, ((0, pad_a), (0, pad_t)))
    Ap, Mp = ret.shape

    grid = (Mp // block_t, Ap // block_a)
    sums, counts = pl.pallas_call(
        partial(_kernel, n_bins=n_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_a, block_t), lambda t, a: (a, t)),
            pl.BlockSpec((block_a, block_t), lambda t, a: (a, t)),
        ],
        out_specs=[
            pl.BlockSpec((n_bins, block_t), lambda t, a: (0, t)),
            pl.BlockSpec((n_bins, block_t), lambda t, a: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_bins, Mp), dt),
            jax.ShapeDtypeStruct((n_bins, Mp), dt),
        ],
        interpret=interpret,
    )(labels, ret)
    return sums[:, :M], counts[:, :M]
