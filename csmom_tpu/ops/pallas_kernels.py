"""Pallas TPU kernels for the portfolio-aggregation hot op.

The XLA implementation of :func:`csmom_tpu.backtest.monthly.
decile_partial_sums` materializes a one-hot membership tensor
``[B, A, M]`` (10x the panel) before reducing over assets.  XLA usually
fuses it, but at north-star scale (A=3000, M=720, B=10, and x16 grid cells
under vmap) the fusion boundary with the surrounding roll/where ops is
fragile.  This kernel computes the same ``(sums, counts)`` with an explicit
tiling: stream ``[block_a, block_t]`` tiles of (labels, returns) through
VMEM once, accumulate all B bins into a resident ``[B, block_t]`` output
tile — O(A*M) HBM traffic, no [B, A, M] intermediate ever exists.

Contract (same as the XLA version):
  labels i32[A, M] with -1 meaning "not a member of any bin" (invalid lanes
  are pre-folded into -1 by the caller); ret f32[A, M] pre-zeroed at
  invalid slots.  Returns (sums f32[B, M], counts f32[B, M]).

The asset axis is the *last* grid dimension, so consecutive grid steps
revisit the same output tile (sequential TPU grid), which makes the
accumulate-across-tiles pattern valid; the first asset-tile initializes.
``interpret=True`` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _cohort_kernel(lab_ref, ret_cur_ref, ret_nxt_ref, vf_cur_ref, vf_nxt_ref,
                   sums_ref, counts_ref, *, n_bins: int, max_hold: int,
                   block_t: int):
    """Cohort x horizon partial sums for one (time, asset) tile pair.

    For each side (bottom decile 0, top decile B-1) and horizon h=1..H,
    accumulate ``sum_a member(a, s) * r(a, s+h)`` and the matching counts
    into the resident ``[2, block_t, H]`` output tile.  The s+h reads are
    served from a 2-tile VMEM window (current + next time tile), so H must
    be <= block_t and the caller pads time with >= one full dead tile.
    """
    a_tile = pl.program_id(1)

    @pl.when(a_tile == 0)
    def _init():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)

    lab = lab_ref[...]
    r_w = jnp.concatenate([ret_cur_ref[...], ret_nxt_ref[...]], axis=1)
    v_w = jnp.concatenate([vf_cur_ref[...], vf_nxt_ref[...]], axis=1)
    members = [(lab == 0).astype(r_w.dtype),
               (lab == (n_bins - 1)).astype(r_w.dtype)]
    for h in range(1, max_hold + 1):  # static unroll over horizons
        r_h = r_w[:, h:h + block_t]   # r at s+h, aligned to formation s
        v_h = v_w[:, h:h + block_t]
        for side, mem in enumerate(members):
            sums_ref[side, :, h - 1] += jnp.sum(mem * r_h, axis=0)
            counts_ref[side, :, h - 1] += jnp.sum(mem * v_h, axis=0)


@partial(jax.jit, static_argnames=("n_bins", "max_hold", "block_a", "block_t",
                                  "interpret"))
def cohort_partial_sums_pallas(
    ret,
    ret_valid,
    labels,
    n_bins: int = 10,
    max_hold: int = 12,
    block_a: int = 256,
    block_t: int = 128,
    interpret: bool = False,
):
    """Fused cohort-forward-return aggregation for the J x K grid engine.

    Same contract as the XLA ``backtest.grid._cohort_partial_sums`` (the
    north-star workload's hot op): for every formation month s and horizon
    h = 1..max_hold, the sum/count of members' returns h months after
    formation, for the bottom (side 0) and top (side 1) deciles.  The XLA
    path materializes H rolled copies of the panel between fusion
    boundaries; this kernel streams each (labels, ret, valid) tile through
    VMEM once and reads the shifted months from a resident 2-tile window —
    O(A*M) HBM traffic independent of H.

    Args:
      ret: f[A, M] next-month return panel (raw, not pre-shifted).
      ret_valid: bool[A, M].
      labels: i32[A, M] decile ids at formation, -1 = unranked.
      max_hold: H, the static horizon bound (must be <= block_t).

    Returns ``(sums f[2, M, H], counts f[2, M, H])`` — counts in
    ``promote(ret.dtype, f32)`` exactly like the XLA path (bf16 would round
    counts past 256).
    """
    A, M = ret.shape
    dt = ret.dtype
    count_dt = jnp.promote_types(dt, jnp.float32)
    if max_hold > block_t:
        raise ValueError(f"max_hold={max_hold} must be <= block_t={block_t}")
    block_a = min(block_a, max(A, 8))

    rf = jnp.where(ret_valid, jnp.nan_to_num(ret), 0.0).astype(dt)
    vf = ret_valid.astype(count_dt)

    pad_a = (-A) % block_a
    # at least one full dead tile beyond the last live month, so the "next
    # time tile" always exists and months past the end read as invalid
    pad_t = ((-M) % block_t) + block_t
    labels = jnp.pad(labels, ((0, pad_a), (0, pad_t)), constant_values=-1)
    rf = jnp.pad(rf, ((0, pad_a), (0, pad_t)))
    vf = jnp.pad(vf, ((0, pad_a), (0, pad_t)))
    Ap, Mp = rf.shape

    n_t_out = Mp // block_t - 1   # output tiles (every month < M is covered)
    grid = (n_t_out, Ap // block_a)
    cur = pl.BlockSpec((block_a, block_t), lambda t, a: (a, t))
    nxt = pl.BlockSpec((block_a, block_t), lambda t, a: (a, t + 1))
    out = pl.BlockSpec((2, block_t, max_hold), lambda t, a: (0, t, 0))
    sums, counts = pl.pallas_call(
        partial(_cohort_kernel, n_bins=n_bins, max_hold=max_hold,
                block_t=block_t),
        grid=grid,
        in_specs=[cur, cur, nxt, cur, nxt],
        out_specs=[out, out],
        out_shape=[
            jax.ShapeDtypeStruct((2, n_t_out * block_t, max_hold), dt),
            jax.ShapeDtypeStruct((2, n_t_out * block_t, max_hold), count_dt),
        ],
        interpret=interpret,
    )(labels, rf.astype(dt), rf.astype(dt), vf, vf)
    return sums[:, :M, :], counts[:, :M, :]


def _kernel(lab_ref, ret_ref, sums_ref, counts_ref, *, n_bins: int):
    a_tile = pl.program_id(1)

    @pl.when(a_tile == 0)
    def _init():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)

    lab = lab_ref[...]
    ret = ret_ref[...]
    for b in range(n_bins):  # static unroll: B rows of the resident tile
        mem = (lab == b).astype(ret.dtype)
        sums_ref[b, :] += jnp.sum(ret * mem, axis=0)
        counts_ref[b, :] += jnp.sum(mem, axis=0)


@partial(jax.jit, static_argnames=("n_bins", "block_a", "block_t", "interpret"))
def decile_partial_sums_pallas(
    ret,
    labels,
    n_bins: int = 10,
    block_a: int = 256,
    block_t: int = 128,
    interpret: bool = False,
):
    """Fused per-(bin, date) sums/counts over the asset axis.

    Args:
      ret: f[A, M] next-period returns, zeroed where invalid.
      labels: i32[A, M] bin ids, -1 where unranked/invalid.
      n_bins: number of bins B.
      block_a/block_t: VMEM tile sizes (asset x time).
      interpret: run in pallas interpreter mode (CPU tests).

    Returns (sums f[B, M], counts f[B, M]) with counts in ret's dtype.
    """
    A, M = ret.shape
    dt = ret.dtype
    block_a = min(block_a, max(A, 8))
    block_t = min(block_t, max(M, 128))
    pad_a = (-A) % block_a
    pad_t = (-M) % block_t
    if pad_a or pad_t:
        # padded lanes carry label -1 / ret 0 -> contribute to no bin
        labels = jnp.pad(labels, ((0, pad_a), (0, pad_t)), constant_values=-1)
        ret = jnp.pad(ret, ((0, pad_a), (0, pad_t)))
    Ap, Mp = ret.shape

    grid = (Mp // block_t, Ap // block_a)
    sums, counts = pl.pallas_call(
        partial(_kernel, n_bins=n_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_a, block_t), lambda t, a: (a, t)),
            pl.BlockSpec((block_a, block_t), lambda t, a: (a, t)),
        ],
        out_specs=[
            pl.BlockSpec((n_bins, block_t), lambda t, a: (0, t)),
            pl.BlockSpec((n_bins, block_t), lambda t, a: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_bins, Mp), dt),
            jax.ShapeDtypeStruct((n_bins, Mp), dt),
        ],
        interpret=interpret,
    )(labels, ret)
    return sums[:, :M], counts[:, :M]
