"""Cross-sectional decile assignment.

The reference assigns deciles per date with ``pd.qcut(s, 10, labels=False,
duplicates='drop')`` and, when qcut raises, falls back to ordinal-rank
flooring (``/root/reference/run_demo.py:18-29``).  This is the one
genuinely *global* op of the whole framework: every other kernel is
independent per asset, but ranking needs the full cross-section — which is
why it is also the op that needs a collective once the asset axis is sharded
(see ``csmom_tpu.parallel``).

Two modes, both pure jax (static shapes, vmapped over dates):

- ``"qcut"``  — bit-exact replication of pandas semantics for parity:
  linear-interpolated quantile edges over the valid cross-section, duplicate
  edges dropped, right-closed intervals with the lowest edge included, and
  all-invalid labels when fewer than two distinct edges survive (what
  ``duplicates='drop'`` really does — it never raises, so the reference's
  rank fallback is dead code in its live path).
- ``"rank"``  — ordinal-rank flooring (the formula of the reference's
  fallback, and the standard choice at scale): O(A log A) sort, no quantile
  gathers, ties broken by position exactly like ``rank(method='first')``.

Labels are int32 in ``[0, n_bins)`` with ``-1`` for masked lanes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

_BIG = jnp.inf


def sortable_bits(x, valid):
    """Monotone float -> unsigned-int key map; invalid lanes get the max
    key, STRICTLY above every valid value including ``+inf`` — an ``inf``
    sentinel would tie with valid ``+inf`` lanes and let stable-sort
    position decide whether an invalid lane steals a boundary slot
    (mislabeling real data whose momentum hits ``inf``, e.g. a zero
    formation price).  Signed zeros are canonicalized first:
    ``jnp.argsort``'s comparator treats -0.0 and +0.0 as equal (stable tie
    by position), so they must map to one bit key.  ``x + 0.0`` would do
    it in IEEE arithmetic but XLA's algebraic simplifier folds
    ``a + 0.0 -> a`` under jit (verified: the sign bit survives jit but
    not eager), so use a compare-select, which the simplifier cannot
    legally fold (-0.0 == +0.0 is true yet their bits differ).

    Shared by single-device ranking (here) and the distributed radix rank
    (:mod:`csmom_tpu.parallel.histrank`) — one key map, one total order.
    """
    from jax import lax

    x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
    if x.dtype == jnp.float64:
        ib, ub, nbits = jnp.int64, jnp.uint64, 64
    else:
        x = x.astype(jnp.float32)
        ib, ub, nbits = jnp.int32, jnp.uint32, 32
    b = lax.bitcast_convert_type(x, ib)
    u = lax.bitcast_convert_type(b, ub)
    top = jnp.array(1, ub) << (nbits - 1)
    flipped = jnp.where(b < 0, ~u, u | top)
    return jnp.where(valid, flipped, ~jnp.array(0, ub)), nbits


def _rank_labels(x, valid, n_bins: int):
    """The reference's fallback binning: ``floor(pct_rank * n_bins)`` capped
    at ``n_bins-1`` (``run_demo.py:26-29``), ties by position like
    ``Series.rank(method='first')``.

    One argsort only.  Bin ``k``'s lowest member sits at 1-based ordinal
    rank ``ceil(k*n/B)``, so a lane's label equals how many of the B-1
    boundary pairs ``(value, position)`` it lexicographically dominates —
    O(A*B) elementwise compares instead of the inverse permutation (a
    second argsort; TPU scatters serialize and are worse still), which
    makes rank mode strictly cheaper than the qcut parity path.

    Documented deviation (rank mode is the fast path, qcut the parity
    mode): boundaries use *exact integer* arithmetic, while the reference
    evaluates ``floor((r/n)*B)`` in float64, whose rounding can misplace a
    boundary by one lane when ``k*n/B`` is an exact integer that ``r/n``
    cannot represent (e.g. B=100, n=50, rank 29).  For the reference's
    only bin count, B=10, the two agree for every n up to at least 20,000
    assets (checked exhaustively); larger B may differ on ~1 boundary lane
    per affected date, and the exact-arithmetic answer is the intended
    binning.

    Ranks on :func:`sortable_bits` keys, not a float-``inf`` sentinel:
    invalid lanes sort STRICTLY after every valid value (including a
    valid ``+inf``), so a boundary slot can never land on an invalid
    lane — and the total order is the same one the histogram form uses,
    which is what makes ``mode='hist'`` label-identical by construction."""
    A = x.shape[0]
    key, _ = sortable_bits(x, valid)
    order = jnp.argsort(key, stable=True)  # invalid lanes sort last, strictly
    n = jnp.sum(valid).astype(jnp.int32)
    k = jnp.arange(1, n_bins, dtype=jnp.int32)
    r_k = (k * n + n_bins - 1) // n_bins   # ceil(k*n/B): label >= k iff rank >= r_k
    b = order[jnp.clip(r_k - 1, 0, A - 1)]  # boundary lanes, one per bin edge
    v = key[b]
    pos = jnp.arange(A, dtype=b.dtype)
    ge = (key[:, None] > v[None, :]) | (
        (key[:, None] == v[None, :]) & (pos[:, None] >= b[None, :])
    )
    labels = jnp.sum(ge, axis=1).astype(jnp.int32)
    return jnp.where(valid, labels, -1)


def _qcut_edges(x, valid, n_bins: int):
    """Linear-interpolated quantile edges over the valid lanes.

    Equivalent to ``np.quantile(v, linspace(0, 1, n_bins+1))`` on the
    compacted valid vector, computed at static shape by sorting invalid
    lanes to the back.
    """
    import numpy as np

    A = x.shape[0]
    v_sorted = jnp.sort(jnp.where(valid, x, _BIG))
    n = jnp.sum(valid)
    # pandas >= 2.0 passes the raw linspace probabilities to Series.quantile
    # (the pre-2.0 one-ulp nextafter nudge in tile.py is gone), which routes
    # them through np.percentile: q -> q*100 -> /100.  That percent roundtrip
    # is lossy — (1/3)*100/100 lands one ulp BELOW 1/3 — so an edge that
    # "should" fall on an exact order statistic interpolates a hair below the
    # data value, and searchsorted(side='left') sends a tied value to the
    # UPPER bin.  Bit-exact parity requires the same roundtripped
    # probabilities.  Static given n_bins, so computed host-side.
    q = np.linspace(0.0, 1.0, n_bins + 1)
    q = jnp.asarray((q * 100.0) / 100.0, dtype=x.dtype)
    pos = q * jnp.maximum(n - 1, 0).astype(x.dtype)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, jnp.maximum(n - 1, 0)).astype(jnp.int32)
    frac = pos - lo.astype(x.dtype)
    lo = jnp.clip(lo, 0, A - 1)
    hi = jnp.clip(hi, 0, A - 1)
    a, b = v_sorted[lo], v_sorted[hi]
    # numpy's _lerp, bit-for-bit: switches formulation at t=0.5 so that
    # identical endpoints interpolate to exactly that value (anything else
    # splits "duplicate" edges by 1 ulp and silently changes the bin count)
    d = b - a
    return jnp.where(frac < 0.5, a + d * frac, b - d * (1 - frac))


def _qcut_labels(x, valid, n_bins: int):
    edges = _qcut_edges(x, valid, n_bins)  # [n_bins+1]
    # duplicates='drop': keep first occurrence of each distinct edge
    keep = jnp.concatenate(
        [jnp.ones(1, dtype=bool), edges[1:] != edges[:-1]]
    )
    n_edges = jnp.sum(keep)

    # searchsorted(side='left') over *kept* edges == count of kept edges < x;
    # intervals are right-closed with the lowest edge included, so a value
    # equal to an interior edge lands in the lower bin and x == min lands in 0.
    xe = x[:, None]
    idx = jnp.sum(keep[None, :] & (edges[None, :] < xe), axis=1).astype(jnp.int32)
    labels = jnp.maximum(idx - 1, 0)

    # degenerate cross-section (all values identical, or a single value):
    # fewer than 2 distinct edges -> pandas emits all-NaN labels, it does NOT
    # raise, so the reference's rank fallback (run_demo.py:25-29) never runs
    # with duplicates='drop' (verified empirically; it only fires for
    # duplicates='raise').  We mirror the real behaviour: every lane invalid.
    # (n>0 guard: with zero valid lanes every edge is NaN and NaN != NaN would
    # let all 11 "distinct" edges through, reporting phantom live bins)
    qcut_ok = (n_edges >= 2) & (jnp.sum(valid) > 0)
    labels = jnp.where(qcut_ok, labels, -1)
    n_bins_eff = jnp.where(qcut_ok, n_edges - 1, 0)
    return jnp.where(valid, labels, -1), n_bins_eff.astype(jnp.int32)


@partial(jax.jit, static_argnames=("n_bins", "mode"))
def decile_assign(x, valid, n_bins: int = 10, mode: str = "qcut"):
    """Assign cross-sectional bins for one date.

    Args:
      x: f[A] signal values (NaN allowed at masked lanes).
      valid: bool[A].
      n_bins: number of quantile bins (10 = deciles).
      mode: "qcut" (pandas parity), "rank" (fast ordinal binning) or
        "hist" (sort-free radix-histogram form of rank — same labels).

    Returns:
      (labels i32[A] with -1 at masked lanes, n_bins_effective i32 scalar)
    """
    if mode == "hist":
        labels, n_eff = decile_assign_panel(
            x[:, None], valid[:, None], n_bins=n_bins, mode="hist"
        )
        return labels[:, 0], n_eff[0]
    if mode == "qcut":
        return _qcut_labels(x, valid, n_bins)
    if mode == "rank":
        labels = _rank_labels(x, valid, n_bins)
        n_eff = jnp.minimum(jnp.sum(valid), n_bins).astype(jnp.int32)
        return labels, n_eff
    raise ValueError(f"unknown mode {mode!r}")


@partial(jax.jit, static_argnames=("n_bins", "mode"))
def decile_assign_panel(x, valid, n_bins: int = 10, mode: str = "qcut"):
    """Vectorize ``decile_assign`` over the time axis of an ``[A, T]`` panel.

    ``mode='hist'`` bins without sorting: the radix-histogram boundary
    selection (``parallel.histrank.histogram_rank_labels`` with its
    collectives degenerated to identities) replaces the O(A log A) batched
    sort with O(A * rounds) bucket scans — label-identical to ``'rank'``
    by construction (same order statistics, same stable tie rule), it is
    the candidate kernel for the >=50k-asset regime where the sort owns
    the phase profile (ROOFLINE.md; measured by benchmarks/grid_phases.py).

    Returns ``(labels i32[A, T], n_bins_effective i32[T])``.
    """
    if mode == "hist":
        from csmom_tpu.parallel.histrank import histogram_rank_labels

        labels_t = histogram_rank_labels(x, valid, n_bins, axis_name=None)
        n_eff = jnp.minimum(
            jnp.sum(valid, axis=0), n_bins
        ).astype(jnp.int32)
        return labels_t, n_eff
    labels_t, n_eff = jax.vmap(
        lambda xv, mv: decile_assign(xv, mv, n_bins=n_bins, mode=mode),
        in_axes=1,
        out_axes=(1, 0),
    )(x, valid)
    return labels_t, n_eff


@partial(jax.jit, static_argnames=("n_sectors", "n_bins", "mode"))
def sector_decile_assign(x, valid, sector_ids, n_sectors: int, n_bins: int = 10,
                         mode: str = "qcut"):
    """Sector-neutral cross-sectional bins for one date (BASELINE config 3).

    Ranks each asset only against peers in its own sector: the quantile
    edges are recomputed per sector over the sector's valid lanes, exactly
    as a pandas ``groupby('sector').transform(qcut)`` would.  The pooled
    label space is shared across sectors (bin b of sector s and bin b of
    sector s' both map to label b), which is what makes the downstream
    long-short "sector-neutral": the top-bin portfolio holds every sector's
    local winners in proportion to sector breadth.

    Args:
      x: f[A] signal values.
      valid: bool[A].
      sector_ids: i32[A] in ``[0, n_sectors)``; negative = unclassified
        (treated as invalid, like a masked lane).
      n_sectors: static sector count.

    Returns:
      (labels i32[A] with -1 at masked/unclassified lanes,
       n_bins_effective i32[n_sectors] per sector)
    """
    sectors = jnp.arange(n_sectors, dtype=sector_ids.dtype)

    def per_sector(s):
        return decile_assign(x, valid & (sector_ids == s), n_bins=n_bins, mode=mode)

    labels_s, n_eff = jax.vmap(per_sector)(sectors)  # [S, A], [S]
    a_idx = jnp.arange(x.shape[0])
    own = labels_s[jnp.clip(sector_ids, 0, n_sectors - 1), a_idx]
    labels = jnp.where(valid & (sector_ids >= 0), own, -1)
    return labels, n_eff


@partial(jax.jit, static_argnames=("n_sectors", "n_bins", "mode"))
def sector_decile_assign_panel(x, valid, sector_ids, n_sectors: int,
                               n_bins: int = 10, mode: str = "qcut"):
    """``sector_decile_assign`` vmapped over the time axis of an ``[A, T]``
    panel (sector membership is static over time, as in CRSP-style SIC
    classification snapshots).

    Returns ``(labels i32[A, T], n_bins_effective i32[n_sectors, T])``.
    """
    labels_t, n_eff = jax.vmap(
        lambda xv, mv: sector_decile_assign(
            xv, mv, sector_ids, n_sectors, n_bins=n_bins, mode=mode
        ),
        in_axes=1,
        out_axes=(1, 1),
    )(x, valid)
    return labels_t, n_eff
