"""Masked rolling-window reductions along the time axis.

pandas semantics, panel-shaped: the reference leans on
``groupby(ticker).rolling(w, min_periods=1)`` sums/means/stds throughout its
feature engineering (``/root/reference/src/features.py:126,131-135``).
pandas rolling reductions *skip* NaN observations and emit NaN only when the
window holds fewer than ``min_periods`` valid points; these kernels reproduce
that with prefix-sum differences — O(T) work, one fused XLA pass, no Python
window loop (the reference's per-window ``rolling.apply`` lambda at
``features.py:50`` is its slowest signal op).

All kernels take ``x[..., T]`` + ``valid[..., T]`` and return
``(value[..., T], out_valid[..., T])``; positions outside ``out_valid`` hold
NaN.  The window at position t covers ``[t-window+1, t]`` clipped to the
series start — exactly pandas' trailing window.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _windowed_prefix_diff(x, window: int):
    """sum of x over the trailing window via padded inclusive prefix sums."""
    c = jnp.cumsum(x, axis=-1)
    pad = jnp.zeros_like(c[..., :1])
    c = jnp.concatenate([pad, c], axis=-1)  # c[..., t+1] = sum x[..., :t+1]
    # trailing window [t-window+1, t]:   c[t+1] - c[max(t+1-window, 0)]
    T = x.shape[-1]
    hi = c[..., 1:]
    lo = c[..., jnp.maximum(jnp.arange(T) + 1 - window, 0)]
    return hi - lo


@partial(jax.jit, static_argnames=("window", "min_periods"))
def rolling_count(valid, window: int, min_periods: int = 1):
    """Number of valid observations in each trailing window."""
    return _windowed_prefix_diff(valid.astype(jnp.int32), window)


@partial(jax.jit, static_argnames=("window", "min_periods"))
def rolling_sum(x, valid, window: int, min_periods: int = 1):
    """NaN-skipping rolling sum (pandas ``rolling(w, min_periods).sum()``)."""
    filled = jnp.where(valid, jnp.nan_to_num(x), 0.0)
    s = _windowed_prefix_diff(filled, window)
    n = _windowed_prefix_diff(valid.astype(filled.dtype), window)
    out_valid = n >= min_periods
    return jnp.where(out_valid, s, jnp.nan), out_valid


@partial(jax.jit, static_argnames=("window", "min_periods"))
def rolling_mean(x, valid, window: int, min_periods: int = 1):
    filled = jnp.where(valid, jnp.nan_to_num(x), 0.0)
    s = _windowed_prefix_diff(filled, window)
    n = _windowed_prefix_diff(valid.astype(filled.dtype), window)
    out_valid = n >= min_periods
    mean = s / jnp.maximum(n, 1)
    return jnp.where(out_valid, mean, jnp.nan), out_valid


@partial(jax.jit, static_argnames=("window", "min_periods", "ddof"))
def rolling_std(x, valid, window: int, min_periods: int = 1, ddof: int = 1):
    """NaN-skipping rolling standard deviation.

    Uses the prefix-sum-of-squares identity after centering each series by its
    global valid mean.  The centering is mathematically a no-op for a variance
    but slashes catastrophic cancellation in f32: raw intraday volumes reach
    ~1e8, whose squares exhaust f32's 24-bit mantissa long before the
    window difference is taken.
    """
    filled = jnp.where(valid, jnp.nan_to_num(x), 0.0)
    n_total = jnp.maximum(jnp.sum(valid, axis=-1, keepdims=True), 1)
    center = jnp.sum(filled, axis=-1, keepdims=True) / n_total
    xc = jnp.where(valid, filled - center, 0.0)

    s1 = _windowed_prefix_diff(xc, window)
    s2 = _windowed_prefix_diff(xc * xc, window)
    n = _windowed_prefix_diff(valid.astype(filled.dtype), window)

    out_valid = (n >= min_periods) & (n > ddof)
    denom = jnp.maximum(n - ddof, 1)
    var = (s2 - s1 * s1 / jnp.maximum(n, 1)) / denom
    var = jnp.maximum(var, 0.0)  # clamp tiny negative fp residue
    return jnp.where(out_valid, jnp.sqrt(var), jnp.nan), out_valid
