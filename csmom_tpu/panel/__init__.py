"""Masked dense panels: ingest, calendars, containers, synthetic generators."""

from csmom_tpu.panel.panel import Panel
from csmom_tpu.panel.ingest import (
    read_price_csv,
    load_daily,
    load_intraday,
    long_to_panel,
)
from csmom_tpu.panel.calendar import month_end_segments, month_end_aggregate
from csmom_tpu.panel.pack import save_packed, load_packed, pack_csv_cache
from csmom_tpu.panel.fetch import (
    fetch_daily,
    fetch_intraday,
    get_shares_info,
    cache_path,
)

__all__ = [
    "Panel",
    "read_price_csv",
    "load_daily",
    "load_intraday",
    "long_to_panel",
    "month_end_segments",
    "month_end_aggregate",
    "save_packed",
    "load_packed",
    "pack_csv_cache",
    "fetch_daily",
    "fetch_intraday",
    "get_shares_info",
    "cache_path",
]
