"""Calendar utilities: month-end segmentation and aggregation.

The reference aggregates daily bars to month-end with
``groupby(['ticker', pd.Grouper(key='date', freq='ME')]).agg(last, sum)``
(``/root/reference/src/features.py:34-39``).  The panel-world equivalent:
assign each trading day a month segment id, then reduce each segment with
``jax.ops.segment_*`` — one fused pass over ``[A, T_daily]``, no Python
loops, shardable along assets.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from functools import partial


def month_end_segments(times: np.ndarray):
    """Host-side: map daily timestamps -> (segment_ids, month_end_times).

    Returns:
      seg_ids:  int32[T_daily], 0..M-1, nondecreasing — month index per day.
      month_ends: datetime64[M] calendar month-end stamps (pandas 'ME' labels).
    """
    t = np.asarray(times, dtype="datetime64[D]")
    if t.size and (np.diff(t.view("int64")) < 0).any():
        raise ValueError("times must be nondecreasing (segment kernels tell XLA "
                         "indices_are_sorted=True; unsorted ids would be UB on TPU)")
    months = t.astype("datetime64[M]")
    uniq, seg_ids = np.unique(months, return_inverse=True)
    # label each month by its calendar month-end, as pandas Grouper(freq='ME')
    month_ends = (uniq + 1).astype("datetime64[D]") - np.timedelta64(1, "D")
    return seg_ids.astype(np.int32), month_ends.astype("datetime64[ns]")


@partial(jax.jit, static_argnames=("num_segments",))
def month_end_aggregate(values, mask, seg_ids, num_segments: int):
    """Month-end 'last valid price' + 'summed volume'-style reductions.

    Mirrors ``features.py:34-39``: per (asset, month), the last *valid*
    observation of ``values`` and whether any observation existed.  Implemented
    with segment maxima over masked day ordinals + a gather, entirely inside
    jit (static M keeps shapes fixed for XLA).

    Args:
      values: f[A, T] daily panel (NaN at masked slots).
      mask:   bool[A, T].
      seg_ids: i32[T] month index per day (from ``month_end_segments``).
      num_segments: M, static.

    Returns:
      (last_vals f[A, M], any_mask bool[A, M])
    """
    A, T = values.shape
    day_idx = jnp.arange(T, dtype=jnp.int32)
    # per (asset, month): index of last valid day, -1 if none
    masked_idx = jnp.where(mask, day_idx[None, :], -1)
    last_idx = jax.vmap(
        lambda row: jax.ops.segment_max(
            row, seg_ids, num_segments=num_segments, indices_are_sorted=True
        )
    )(masked_idx)
    any_mask = last_idx >= 0
    gather_idx = jnp.clip(last_idx, 0, T - 1)
    last_vals = jnp.take_along_axis(values, gather_idx, axis=1)
    last_vals = jnp.where(any_mask, last_vals, jnp.nan)
    return last_vals, any_mask


@partial(jax.jit, static_argnames=("num_segments",))
def segment_sum_panel(values, mask, seg_ids, num_segments: int):
    """Per (asset, month) sum of valid observations (volume aggregation).

    The reference fills missing volume with 0 before summing
    (``features.py:31``); masked slots contribute 0 here likewise.
    """
    filled = jnp.where(mask, jnp.nan_to_num(values), 0.0)
    return jax.vmap(
        lambda row: jax.ops.segment_sum(
            row, seg_ids, num_segments=num_segments, indices_are_sorted=True
        )
    )(filled)
