"""Network fetch + write-once CSV cache (the reference's live-data path).

Mirrors ``/root/reference/src/data_io.py:131-249`` behaviorally — per-ticker
download with a CSV cache in ``data_dir``, per-ticker fault isolation (one
failing name is skipped with a warning, never fatal), ``force_refresh`` to
bust the cache, and a ``get_shares_info`` metadata fetch — with two
deliberate fixes:

- **The cache always roundtrips.**  Caches are written in the canonical long
  schema (lowercase columns, ISO timestamps, a ``# csmom-cache-v1`` version
  marker) and re-read through the same dialect-tolerant reader as the
  shipped reference caches, so the §2.1.1 class of bug (a newer yfinance
  header silently zeroing a ticker) cannot recur: an unreadable cache raises
  instead of returning 0 rows.
- **The network backend is injectable.**  ``yfinance`` is an optional
  dependency (this image does not ship it); callers pass any
  ``fetcher(ticker, ...) -> DataFrame`` for testing or alternative vendors,
  and the default raises a clear error when yfinance is unavailable and no
  cache exists.  There is no 0.05 s politeness sleep here — rate limiting
  belongs to the vendor-specific fetcher, not the cache layer.
"""

from __future__ import annotations

import os
from typing import Callable, Mapping, Sequence

import pandas as pd

from csmom_tpu.panel.ingest import (
    DAILY_SCHEMA,
    INTRADAY_SCHEMA,
    read_price_csv,
)
from csmom_tpu.utils.logging import get_logger

log = get_logger(__name__)

CACHE_VERSION = "csmom-cache-v1"


def cache_path(data_dir: str, ticker: str, kind: str) -> str:
    """``<data_dir>/<TICKER>_<kind>.csv`` — same layout as the reference
    (``data_io.py:11-12``), so its shipped ``data/`` directory is a valid
    cache for this fetcher."""
    return os.path.join(data_dir, f"{ticker}_{kind}.csv")


def _default_daily_fetcher(ticker: str, start: str, end: str) -> pd.DataFrame:
    try:
        import yfinance as yf  # optional; absent in this image
    except ImportError as e:  # pragma: no cover - exercised via injection
        raise RuntimeError(
            f"no cache for {ticker} and yfinance is not installed; pass "
            "fetcher= or pre-populate the cache directory"
        ) from e
    return yf.download(ticker, start=start, end=end, progress=False,
                       auto_adjust=False)  # pragma: no cover


def _default_intraday_fetcher(ticker: str, period: str, interval: str) -> pd.DataFrame:
    try:
        import yfinance as yf
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            f"no cache for {ticker} and yfinance is not installed; pass "
            "fetcher= or pre-populate the cache directory"
        ) from e
    return yf.download(ticker, period=period, interval=interval,
                       progress=False, auto_adjust=False)  # pragma: no cover


def _normalize_vendor_daily(df: pd.DataFrame, ticker: str) -> pd.DataFrame:
    """Vendor frame (datetime index, title-case columns, possibly MultiIndex)
    -> canonical daily long schema."""
    if df is None or len(df) == 0:
        return pd.DataFrame(columns=DAILY_SCHEMA)
    out = df.copy()
    if isinstance(out.columns, pd.MultiIndex):
        out.columns = [c[0] for c in out.columns]
    out.columns = [str(c).strip().lower().replace(" ", "_") for c in out.columns]
    out = out.reset_index()
    tcol = out.columns[0]
    res = pd.DataFrame({"date": pd.to_datetime(out[tcol], errors="coerce")})
    res["ticker"] = ticker
    for col in ("open", "high", "low", "close", "adj_close", "volume"):
        res[col] = pd.to_numeric(out.get(col), errors="coerce")
    if "adj_close" not in out.columns or res["adj_close"].isna().all():
        res["adj_close"] = res["close"]
    return res.dropna(subset=["date"])[DAILY_SCHEMA]


def _normalize_vendor_intraday(df: pd.DataFrame, ticker: str) -> pd.DataFrame:
    if df is None or len(df) == 0:
        return pd.DataFrame(columns=INTRADAY_SCHEMA)
    out = df.copy()
    if isinstance(out.columns, pd.MultiIndex):
        out.columns = [c[0] for c in out.columns]
    out.columns = [str(c).strip().lower().replace(" ", "_") for c in out.columns]
    out = out.reset_index()
    tcol = out.columns[0]
    res = pd.DataFrame({"datetime": pd.to_datetime(out[tcol], errors="coerce")})
    res["ticker"] = ticker
    price = out.get("close", out.get("price"))
    res["price"] = pd.to_numeric(price, errors="coerce")
    res["volume"] = pd.to_numeric(out.get("volume"), errors="coerce")
    return res.dropna(subset=["datetime"])[INTRADAY_SCHEMA]


def _write_cache(df: pd.DataFrame, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(f"# {CACHE_VERSION}\n")
        df.drop(columns=["ticker"]).to_csv(f, index=False)


def _read_cache(path: str, ticker: str, kind: str) -> pd.DataFrame:
    """Read either our versioned cache or a reference-dialect cache; raise
    (not empty) when a present file yields zero rows — loud beats silent."""
    with open(path) as f:
        first = f.readline()
    skip = 1 if first.startswith(f"# {CACHE_VERSION}") else 0
    if skip:
        df = pd.read_csv(path, skiprows=1)
        time_col = "date" if kind == "daily" else "datetime"
        df[time_col] = pd.to_datetime(df[time_col])
        df["ticker"] = ticker
        schema = DAILY_SCHEMA if kind == "daily" else INTRADAY_SCHEMA
        df = df[schema]
    else:
        df = read_price_csv(path, ticker, kind=kind)
    if len(df) == 0:
        raise ValueError(
            f"cache {path} parsed to 0 rows — corrupt or unknown dialect "
            "(refusing to silently drop the ticker; delete the file or pass "
            "force_refresh=True)"
        )
    return df


def _fetch_universe(
    tickers: Sequence[str],
    kind: str,
    data_dir: str,
    force_refresh: bool,
    fetch_one: Callable[[str], pd.DataFrame],
    normalize: Callable[[pd.DataFrame, str], pd.DataFrame],
    schema: Sequence[str],
    time_col: str,
) -> pd.DataFrame:
    frames = []
    for t in tickers:
        path = cache_path(data_dir, t, kind)
        try:
            if os.path.exists(path) and not force_refresh:
                df = _read_cache(path, t, kind)
            else:
                df = normalize(fetch_one(t), t)
                if len(df):
                    _write_cache(df, path)
                else:
                    log.warning("%s: fetch returned no rows; skipping", t)
                    continue
            frames.append(df)
        except Exception as e:  # per-ticker isolation (data_io.py:173-175)
            log.warning("%s: %s (skipped)", t, e)
    if not frames:
        return pd.DataFrame(columns=schema)
    return pd.concat(frames, ignore_index=True).sort_values(
        [time_col, "ticker"], kind="stable"
    ).reset_index(drop=True)


def fetch_daily(
    tickers: Sequence[str],
    start: str = "2018-01-01",
    end: str = "2024-12-31",
    data_dir: str = "data",
    force_refresh: bool = False,
    fetcher: Callable[..., pd.DataFrame] | None = None,
) -> pd.DataFrame:
    """Daily bars for a universe, cache-first (``data_io.py:131-180``).

    ``fetcher(ticker, start, end)`` returns a vendor frame (yfinance-shaped:
    datetime index, OHLCV columns); default requires yfinance.
    """
    fetch = fetcher or _default_daily_fetcher
    return _fetch_universe(
        tickers, "daily", data_dir, force_refresh,
        lambda t: fetch(t, start, end), _normalize_vendor_daily,
        DAILY_SCHEMA, "date",
    )


def fetch_intraday(
    tickers: Sequence[str],
    period: str = "7d",
    interval: str = "1m",
    data_dir: str = "data",
    force_refresh: bool = False,
    fetcher: Callable[..., pd.DataFrame] | None = None,
) -> pd.DataFrame:
    """Minute bars for a universe, cache-first (``data_io.py:182-228``)."""
    fetch = fetcher or _default_intraday_fetcher
    return _fetch_universe(
        tickers, "intraday", data_dir, force_refresh,
        lambda t: fetch(t, period, interval), _normalize_vendor_intraday,
        INTRADAY_SCHEMA, "datetime",
    )


def get_shares_info(
    tickers: Sequence[str],
    info_fn: Callable[[str], Mapping] | None = None,
) -> dict:
    """Per-ticker ``{'shares_outstanding', 'market_cap'}``, None on failure
    (``data_io.py:230-249``).  ``info_fn(ticker)`` returns a vendor info
    mapping (yfinance ``Ticker(t).info``-shaped); default requires yfinance.
    """
    def default_info(t):  # pragma: no cover - needs network
        import yfinance as yf

        return yf.Ticker(t).info

    fn = info_fn or default_info
    out = {}
    for t in tickers:
        try:
            info = fn(t)
            out[t] = {
                "shares_outstanding": info.get("sharesOutstanding"),
                "market_cap": info.get("marketCap"),
            }
        except Exception as e:
            log.warning("shares info %s: %s", t, e)
            out[t] = {"shares_outstanding": None, "market_cap": None}
    return out
