"""CSV ingest: cached yfinance dialects -> canonical long frames -> panels.

The reference caches one CSV per (ticker, freq) and normalizes columns
defensively on re-read (``/root/reference/src/data_io.py:131-228``).  Its
cache dialects (observed in ``/root/reference/data/``) are:

- dialect A (most files)::

      Date,Adj Close,Close,High,Low,Open,Volume
      ,AMD,AMD,AMD,AMD,AMD,AMD          <- junk "ticker" row
      2018-01-02,10.97,...

- dialect B (newer yfinance, e.g. ``AAPL_daily.csv``)::

      Price,Close,High,Low,Open,Volume
      Ticker,AAPL,AAPL,AAPL,AAPL,AAPL
      Date,,,,,
      2018-01-02,40.38,...

The reference's normalizer cannot find a date column in dialect B and
silently drops the whole file (``data_io.py:55-58,163`` — the bug recorded
in SURVEY §2.1.1).  This ingest recognizes both dialects, so the full
universe survives a cache roundtrip; the 19-ticker behaviour needed for
golden-parity tests is obtained simply by loading 19 tickers.

Output schemas match the reference's canonical ones (``data_io.py:15-16``):
daily ``['date','ticker','open','high','low','close','adj_close','volume']``,
intraday ``['datetime','ticker','price','volume']``.
"""

from __future__ import annotations

import os
from typing import Iterable, Sequence

import numpy as np
import pandas as pd

from csmom_tpu.panel.panel import Panel, PanelBundle
from csmom_tpu.utils.logging import get_logger

log = get_logger(__name__)

DAILY_SCHEMA = ["date", "ticker", "open", "high", "low", "close", "adj_close", "volume"]
INTRADAY_SCHEMA = ["datetime", "ticker", "price", "volume"]

_FIELD_ALIASES = {
    "open": "open",
    "high": "high",
    "low": "low",
    "close": "close",
    "adj close": "adj_close",
    "adj_close": "adj_close",
    "volume": "volume",
    "price": "price",
}


def _strip_preamble(raw: pd.DataFrame) -> pd.DataFrame:
    """Drop the junk header rows both yfinance cache dialects carry.

    A data row is one whose first cell parses as a date; preamble rows have
    first cell empty, 'Ticker', or 'Date'.
    """
    first = raw.iloc[:, 0].astype(str).str.strip()
    junk = first.isin(["", "nan", "None", "Ticker", "Date", "Datetime"])
    # only the leading block is preamble; stop at the first real row
    keep_from = int(np.argmax(~junk.values)) if (~junk).any() else len(raw)
    return raw.iloc[keep_from:]


def read_price_csv(path: str, ticker: str, kind: str = "daily",
                   engine: str = "auto") -> pd.DataFrame:
    """Read one cached CSV (either dialect) into the canonical long schema.

    Unlike the reference's ``_normalize_daily_columns`` (``data_io.py:23-73``),
    the timestamp is always taken from the *first column* once the preamble is
    stripped — which is what both dialects actually put there — rather than
    from a column literally named ``Date``.

    ``engine``: 'auto' (native C++ parser when available, else pandas),
    'native' (require the C++ parser), or 'pandas'.  Both engines produce
    identical frames (pinned by tests/test_native.py).
    """
    if engine in ("auto", "native"):
        out = _read_native(path, ticker, kind)
        if out is not None:
            return out
        if engine == "native":
            raise RuntimeError("native CSV engine unavailable (no compiler?)")

    # index_col=False: without it, a ragged over-long FIRST data row makes
    # read_csv silently shift the timestamp column into the index (data
    # corruption); with it, a long first row truncates to the header width
    # (matching the native engine) and a long later row raises loudly —
    # caught by the universe-level fault isolation in _load_universe
    raw = pd.read_csv(path, low_memory=False, dtype=str, index_col=False)
    cols = [str(c).strip() for c in raw.columns]
    body = _strip_preamble(raw)

    time_col = "date" if kind == "daily" else "datetime"
    out = pd.DataFrame()
    # format="mixed" parses each element independently; the default infers
    # a format from the first row and NaT-coerces every row that differs,
    # silently dropping valid data when a file mixes timestamp spellings
    out[time_col] = pd.to_datetime(body.iloc[:, 0], errors="coerce",
                                   utc=(kind != "daily"), format="mixed")
    if kind != "daily":
        # store tz-naive UTC timestamps; panels index by absolute instants
        out[time_col] = out[time_col].dt.tz_localize(None)

    for pos, col in enumerate(cols):
        canon = _FIELD_ALIASES.get(col.lower())
        if canon and pos > 0:
            out[canon] = pd.to_numeric(body.iloc[:, pos], errors="coerce")

    return _canonize(out, kind, ticker)


def _canonize(out: pd.DataFrame, kind: str, ticker: str) -> pd.DataFrame:
    """Shared schema tail for both CSV engines."""
    if kind == "daily":
        if "adj_close" not in out:
            # dialect B ships no Adj Close; yfinance's Close there is already
            # the adjusted series (reference mirrors this at data_io.py:32-33)
            out["adj_close"] = out.get("close", np.nan)
        return _finalize(out, DAILY_SCHEMA, "date", ticker)

    if "price" not in out:
        for fallback in ("adj_close", "close"):
            if fallback in out:
                out["price"] = out[fallback]
                break
        else:
            out["price"] = np.nan
    return _finalize(out, INTRADAY_SCHEMA, "datetime", ticker)


def _sniff_header(path: str):
    """First real header of a price CSV: ``(columns, had_marker)``.

    The one place header sniffing lives (native fast path and parity-
    universe detection both use it): skips the versioned fetch-cache
    marker line, unquotes names the way ``read_csv`` does (``'"Close"'``
    -> ``'Close'``) — price-cache headers never contain embedded commas,
    so a plain split is safe even when names are quoted.  Returns
    ``(None, False)`` on an unreadable file.
    """
    try:
        with open(path, "r") as f:
            header = f.readline()
            had_marker = header.startswith("#")
            if had_marker:
                header = f.readline()
    except OSError:
        return None, False
    cols = [c.strip().strip('"').strip() for c in header.rstrip("\r\n").split(",")]
    return cols, had_marker


def _read_native(path: str, ticker: str, kind: str) -> pd.DataFrame | None:
    """C++ fast path: header sniffed host-side, data rows parsed natively.

    Returns None when the native library can't be built/loaded so the
    caller falls back to pandas.
    """
    from csmom_tpu.native import parse_price_csv_native

    cols, _ = _sniff_header(path)
    if cols is None or len(cols) < 2:
        return None
    try:
        parsed = parse_price_csv_native(path, len(cols) - 1)
    except Exception as e:  # pragma: no cover - defensive
        log.warning("native parse failed for %s (%r); pandas fallback", path, e)
        return None
    if parsed is None:
        return None
    epochs, values = parsed

    time_col = "date" if kind == "daily" else "datetime"
    out = pd.DataFrame({time_col: pd.to_datetime(epochs, unit="ns")})
    for pos, col in enumerate(cols):
        canon = _FIELD_ALIASES.get(col.lower())
        if canon and pos > 0:
            out[canon] = values[:, pos - 1]
    return _canonize(out, kind, ticker)


def _finalize(out: pd.DataFrame, schema, time_col: str, ticker: str) -> pd.DataFrame:
    for c in schema:
        if c not in out:
            out[c] = np.nan
    out["ticker"] = ticker
    out = out.dropna(subset=[time_col])
    # vendor caches occasionally repeat a timestamp (a re-download
    # appended instead of replacing, a provider correction row): keep
    # the LAST occurrence — the correction — and say how many were
    # dropped.  Silently keeping both used to leak duplicate rows into
    # long_to_panel, where pivot_table's aggfunc quietly picked one.
    n_dup = int(out.duplicated(subset=[time_col]).sum())
    if n_dup:
        log.warning(
            "%s: %d duplicate %s row(s) in cache — deduplicated "
            "keep-last (provider corrections win)",
            ticker, n_dup, time_col,
        )
        # .copy() detaches the result from its parent frame so the dtype
        # normalization below writes a real frame, not a flagged slice
        out = out.drop_duplicates(subset=[time_col], keep="last").copy()
    # uniform engine-independent dtypes: ns timestamps, f64 numerics
    out[time_col] = out[time_col].astype("datetime64[ns]")
    for c in schema:
        if c not in (time_col, "ticker"):
            out[c] = out[c].astype(np.float64)
    return out[schema].reset_index(drop=True)


def _load_universe(
    data_dir: str, tickers: Sequence[str], kind: str, suffix: str
) -> pd.DataFrame:
    """Per-ticker load with the reference's fault isolation: a bad ticker is
    skipped with a warning, never fatal (``data_io.py:173-175``)."""
    frames = []
    for t in tickers:
        path = os.path.join(data_dir, f"{t}_{suffix}.csv")
        try:
            if not os.path.exists(path):
                log.warning("no cache file for %s (%s) — skipping", t, path)
                continue
            df = read_price_csv(path, t, kind=kind)
            if df.empty:
                log.warning("no valid rows for %s after normalization — skipping", t)
                continue
            frames.append(df)
        except Exception as e:  # noqa: BLE001 — universe-level fault isolation
            log.warning("failed to load %s: %r — skipping", t, e)
    schema = DAILY_SCHEMA if kind == "daily" else INTRADAY_SCHEMA
    if not frames:
        return pd.DataFrame(columns=schema)
    return pd.concat(frames, ignore_index=True)


def load_daily(data_dir: str, tickers: Sequence[str]) -> pd.DataFrame:
    """Load the daily universe from cached CSVs into the canonical schema."""
    return _load_universe(data_dir, tickers, "daily", "daily")


def reference_readable_daily(data_dir: str, tickers: Sequence[str]) -> list:
    """Tickers whose daily cache the REFERENCE's own loader can read.

    The reference's normalizer finds no date column in dialect-B files
    (header ``Price,Close,...``) and silently drops every row
    (``/root/reference/src/data_io.py:55-58,163``; SURVEY §2.1.1) — on the
    shipped data that loses AAPL and shrinks its effective daily universe
    to 19 names.  Parity mode needs to reproduce that shrunken universe
    for the risk maps, so this detects dialect B the same way the
    reference fails on it: by the first header cell.  Missing files are
    excluded too (the reference would have no rows for them either), and
    so are files carrying our fetch-cache marker line — the reference's
    bare ``pd.read_csv`` takes the marker as a one-field header and then
    finds no date column, losing the file regardless of its dialect.
    """
    out = []
    for t in tickers:
        cols, had_marker = _sniff_header(
            os.path.join(data_dir, f"{t}_daily.csv")
        )
        if cols is None or had_marker:
            continue
        if cols[0].lower() != "price":
            out.append(t)
    return out


def load_intraday(data_dir: str, tickers: Sequence[str]) -> pd.DataFrame:
    """Load the intraday universe from cached CSVs into the canonical schema."""
    return _load_universe(data_dir, tickers, "intraday", "intraday")


def long_to_panel(
    df: pd.DataFrame,
    value_col: str,
    time_col: str = "date",
    tickers: Sequence[str] | None = None,
    times: np.ndarray | None = None,
) -> Panel:
    """Pivot a canonical long frame into a masked dense Panel.

    The time axis is the sorted union of observed timestamps (or an explicit
    calendar); missing (asset, time) cells become masked NaN lanes — the
    dense-panel replacement for pandas' implicit row dropping.
    """
    if tickers is None:
        tickers = sorted(df["ticker"].unique())
    if times is None:
        times = np.sort(df[time_col].unique())
    wide = (
        df.pivot_table(index="ticker", columns=time_col, values=value_col, aggfunc="last")
        .reindex(index=list(tickers), columns=pd.Index(times))
    )
    return Panel.from_dense(wide.values, tickers, np.asarray(times), name=value_col)


def to_bundle(
    df: pd.DataFrame,
    value_cols: Iterable[str],
    time_col: str = "date",
    tickers: Sequence[str] | None = None,
) -> PanelBundle:
    """Pivot several value columns onto one shared (tickers, times) grid."""
    if tickers is None:
        tickers = sorted(df["ticker"].unique())
    times = np.sort(df[time_col].unique())
    panels = {
        c: long_to_panel(df, c, time_col=time_col, tickers=tickers, times=times)
        for c in value_cols
    }
    return PanelBundle(panels=panels, tickers=tuple(tickers), times=np.asarray(times))


def daily_bundle(df: pd.DataFrame, tickers: Sequence[str] | None = None) -> PanelBundle:
    return to_bundle(
        df, ["open", "high", "low", "close", "adj_close", "volume"], "date", tickers
    )


def intraday_bundle(df: pd.DataFrame, tickers: Sequence[str] | None = None) -> PanelBundle:
    return to_bundle(df, ["price", "volume"], "datetime", tickers)
