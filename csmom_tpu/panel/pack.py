"""Packed binary panel cache: the at-scale data path.

The reference's only persistence is its per-ticker CSV cache
(``/root/reference/src/data_io.py:131-159``), which is re-parsed from text
on every run — fine at 20 tickers x 1,760 bars, hopeless at the north-star
3,000 x 15,120 (the CSV text alone would be ~1 GB and minutes of pandas
parsing).  This module is the scale analogue: dense ``[A, T]`` arrays
written once as raw ``.npy`` (one file per field) next to a tiny JSON
manifest, re-read with ``numpy`` memory mapping so a load touches pages
only as kernels pull them.

Why a directory of flat ``.npy`` and not the compressed ``.npz`` snapshot
(:meth:`csmom_tpu.panel.panel.Panel.save`): ``np.load`` cannot memory-map
members of a zip archive — it would decompress the whole panel into RAM at
open.  The snapshot stays the right answer for small panels that travel as
one file; the pack is the bulk format the bench and grid feed from.

Layout (version 1)::

    <dir>/
      meta.json          {"version": 1, "tickers": [...], "fields": [...],
                          "times_dtype": "datetime64[ns]"}
      times.npy          i64[T] (datetime64 ticks, dtype in meta)
      <field>.values.npy f32/f64[A, T] per field, NaN at masked slots
      <field>.mask.npy   bool[A, T]

Masks are stored explicitly (not re-derived from NaN) so a pack of a
non-float field or an all-finite panel with deliberate invalid lanes
roundtrips exactly.
"""

from __future__ import annotations

import json
import os

import numpy as np

from csmom_tpu.panel.panel import Panel, PanelBundle

_PACK_VERSION = 1


def is_packed(path: str) -> bool:
    """True iff ``path`` is a packed panel directory (manifest present).

    The one place pack detection lives: the API and every CLI surface that
    accepts a pack as ``--data-dir`` route through this, so a future layout
    change cannot diverge between them.
    """
    return os.path.isfile(os.path.join(path, "meta.json"))


def save_packed(obj, path: str) -> str:
    """Write a :class:`Panel` or :class:`PanelBundle` as a packed directory.

    Overwrites field files already present; returns ``path``.
    """
    panels = obj.panels if isinstance(obj, PanelBundle) else {obj.name: obj}
    if not panels:
        raise ValueError("nothing to pack: empty bundle")
    first = next(iter(panels.values()))
    os.makedirs(path, exist_ok=True)
    times = np.asarray(first.times)
    np.save(os.path.join(path, "times.npy"), times.view("i8"))
    for field, p in panels.items():
        if not np.array_equal(np.asarray(p.times), times):
            raise ValueError(f"field {field!r} is not on the shared calendar")
        if tuple(p.tickers) != tuple(first.tickers):
            raise ValueError(f"field {field!r} is not on the shared tickers")
        np.save(os.path.join(path, f"{field}.values.npy"), p.values)
        np.save(os.path.join(path, f"{field}.mask.npy"), p.mask)
    meta = {
        "version": _PACK_VERSION,
        "tickers": list(first.tickers),
        "fields": sorted(panels),
        "times_dtype": str(times.dtype),
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    # Re-packing over an existing directory with fewer fields must not leave
    # the old fields' arrays orphaned: load_packed is meta-driven so they are
    # invisible to it, but they inflate the pack's on-disk size and mislead a
    # plain dir listing (ADVICE r4). Meta is written first, so a crash here
    # leaves a correct pack plus removable orphans, never a broken manifest.
    keep = {"times.npy", "meta.json"} | {
        f"{f}.{kind}.npy" for f in panels for kind in ("values", "mask")
    }
    for name in os.listdir(path):
        if name not in keep and (
            name.endswith(".values.npy") or name.endswith(".mask.npy")
        ):
            try:
                os.remove(os.path.join(path, name))
            except OSError:
                pass  # a vanished/locked orphan is harmless
    return path


def load_packed(path: str, mmap: bool = True):
    """Re-open a packed directory.

    Returns a :class:`Panel` when the pack holds one field, else a
    :class:`PanelBundle`.  With ``mmap=True`` (default) the arrays are
    ``np.memmap`` views — pages fault in as they are read, so opening a
    north-star-sized pack is O(metadata); ``Panel.device()`` streams them
    straight to HBM.  Unknown future versions fail loudly (the §2.1.1
    lesson: an unreadable cache must never quietly shrink the universe).
    """
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    ver = int(meta.get("version", -1))
    if ver > _PACK_VERSION or ver < 1:
        raise ValueError(
            f"{path}: pack version {ver} is not understood by this library "
            f"(supports 1..{_PACK_VERSION}) — refusing to guess at the layout"
        )
    mode = "r" if mmap else None
    times = np.load(os.path.join(path, "times.npy"), mmap_mode=None)
    times = times.view(meta["times_dtype"])
    tickers = tuple(meta["tickers"])
    panels = {}
    for field in meta["fields"]:
        values = np.load(os.path.join(path, f"{field}.values.npy"), mmap_mode=mode)
        mask = np.load(os.path.join(path, f"{field}.mask.npy"), mmap_mode=mode)
        panels[field] = Panel(
            values=values, mask=mask, tickers=tickers, times=times, name=field
        )
    if len(panels) == 1:
        return next(iter(panels.values()))
    return PanelBundle(panels=panels, tickers=tickers, times=times)


def pack_csv_cache(data_dir: str, tickers, out: str,
                   fields=("adj_close", "volume"), df=None,
                   dtype=None) -> str:
    """One-shot CSV cache -> packed directory conversion (``csmom fetch
    --pack``): load the per-ticker daily CSVs through the normal ingest
    path, pivot each requested field to a dense panel, write the pack.

    Pass ``df`` (the canonical long daily frame) when the caller already
    holds it — ``csmom fetch`` does — so the CSVs are not re-parsed; that
    double parse is the exact cost this format exists to eliminate.
    ``dtype`` (e.g. ``np.float32``) downcasts the stored values — at
    north-star scale f32 halves the pack and matches the TPU compute
    dtype anyway; default keeps the ingest's f64.
    """
    import dataclasses

    from csmom_tpu.panel.ingest import load_daily, long_to_panel

    if df is None:
        df = load_daily(data_dir, list(tickers))
    if df.empty:
        raise ValueError(f"no readable daily caches for {len(tickers)} "
                         f"tickers under {data_dir}")
    panels = {f: long_to_panel(df, f) for f in fields}
    if dtype is not None:
        panels = {
            f: dataclasses.replace(p, values=p.values.astype(dtype))
            for f, p in panels.items()
        }
    first = next(iter(panels.values()))
    return save_packed(
        PanelBundle(panels=panels, tickers=tuple(first.tickers),
                    times=np.asarray(first.times)),
        out,
    )
