"""Panel: the core data container of the framework.

Where the reference keeps long-format DataFrames with canonical columns
(``/root/reference/src/data_io.py:15-16`` defines the daily / intraday
schemas), this framework keeps a dense ``values[A, T]`` array plus a boolean
``mask[A, T]`` of observation validity.  The mask is the panel-world
equivalent of pandas' implicit row-dropping (``dropna`` at
``/root/reference/run_demo.py:41,49,127``): instead of removing rows, lanes
are masked and every kernel is mask-aware.

Design notes (TPU-first):

- Static shapes: a Panel is built once per (universe, calendar) and every
  jitted kernel sees a fixed ``[A, T]``; no dynamic shapes reach XLA.
- ``values`` carries NaN at masked slots by convention so that an unmasked
  reduction poisons loudly rather than silently reading garbage.
- Axis layout is assets-major ``[A, T]`` so the asset axis (the scaling axis:
  thousands of names vs. hundreds of months) is the leading, shardable axis.
- The container itself is host-side metadata + device arrays; jit-compiled
  functions take the raw ``(values, mask)`` arrays, never the Panel object,
  keeping tracing free of Python objects.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

try:  # jax is the compute backend but the container also works with numpy only
    import jax.numpy as jnp

    _HAS_JAX = True
except Exception:  # pragma: no cover
    jnp = None
    _HAS_JAX = False


@dataclasses.dataclass(frozen=True)
class Panel:
    """A dense masked (assets x time) panel.

    Attributes:
      values:  float array ``[A, T]``; NaN at masked slots.
      mask:    bool array ``[A, T]``; True where an observation exists.
      tickers: length-A asset identifiers.
      times:   length-T ``np.datetime64`` timestamps (host-side; never traced).
      name:    what the values are (e.g. ``"adj_close"``, ``"volume"``).
    """

    values: np.ndarray
    mask: np.ndarray
    tickers: tuple
    times: np.ndarray
    name: str = "values"

    def __post_init__(self):
        if self.values.shape != self.mask.shape:
            raise ValueError(
                f"values{self.values.shape} and mask{self.mask.shape} differ"
            )
        if self.values.shape[0] != len(self.tickers):
            raise ValueError(
                f"{len(self.tickers)} tickers but A={self.values.shape[0]}"
            )
        if self.values.shape[1] != len(self.times):
            raise ValueError(f"{len(self.times)} times but T={self.values.shape[1]}")

    # -- shape sugar ------------------------------------------------------
    @property
    def n_assets(self) -> int:
        return self.values.shape[0]

    @property
    def n_times(self) -> int:
        return self.values.shape[1]

    @property
    def shape(self):
        return self.values.shape

    # -- construction -----------------------------------------------------
    @classmethod
    def from_dense(cls, values, tickers: Sequence[str], times, name: str = "values"):
        """Build from a dense array; mask is derived from NaN-ness."""
        values = np.asarray(values, dtype=np.float64)
        mask = np.isfinite(values)
        return cls(
            values=values,
            mask=mask,
            tickers=tuple(tickers),
            times=np.asarray(times),
            name=name,
        )

    def device(self, dtype=None):
        """Return ``(values, mask)`` as jax arrays, optionally cast.

        This is the hand-off point host -> HBM; everything downstream is jit.
        """
        if not _HAS_JAX:  # pragma: no cover
            raise RuntimeError("jax unavailable")
        v = jnp.asarray(self.values, dtype=dtype) if dtype else jnp.asarray(self.values)
        m = jnp.asarray(self.mask)
        return v, m

    # -- host-side views --------------------------------------------------
    def to_dataframe(self):
        """Wide DataFrame view (tickers x times) for debugging / oracles."""
        import pandas as pd

        return pd.DataFrame(
            np.where(self.mask, self.values, np.nan),
            index=list(self.tickers),
            columns=self.times,
        )

    def select_assets(self, keep: Sequence[str]) -> "Panel":
        idx = [self.tickers.index(t) for t in keep]
        return Panel(
            values=self.values[idx],
            mask=self.mask[idx],
            tickers=tuple(keep),
            times=self.times,
            name=self.name,
        )

    # -- persistence -------------------------------------------------------
    #
    # SURVEY §5 checkpoint/resume: the reference's only persistence is its
    # fragile per-ticker CSV cache (one dialect of which fails to re-read,
    # §2.1.1).  A Panel snapshot is one versioned .npz holding the dense
    # arrays + axes; save->load is exact by construction (binary arrays,
    # no header-dialect surface at all) and ~100x faster to load than
    # re-parsing CSVs at 3000x15000 scale.

    _SNAPSHOT_VERSION = 1

    def save(self, path: str) -> str:
        """Write a versioned snapshot (.npz)."""
        np.savez_compressed(
            path,
            __version__=np.int64(self._SNAPSHOT_VERSION),
            values=self.values,
            mask=self.mask,
            tickers=np.asarray(self.tickers, dtype=object),
            times=self.times,
            name=np.asarray(self.name),
        )
        return path if path.endswith(".npz") else path + ".npz"

    @classmethod
    def load(cls, path: str) -> "Panel":
        """Re-read a snapshot; raises on unknown snapshot versions rather
        than guessing (the §2.1.1 lesson: unreadable caches must be loud)."""
        with np.load(path, allow_pickle=True) as z:
            ver = int(z["__version__"])
            if ver > cls._SNAPSHOT_VERSION:
                raise ValueError(
                    f"{path}: snapshot version {ver} is newer than this "
                    f"library understands ({cls._SNAPSHOT_VERSION})"
                )
            return cls(
                values=z["values"],
                mask=z["mask"],
                tickers=tuple(z["tickers"].tolist()),
                times=z["times"],
                name=str(z["name"]),
            )

    def __repr__(self) -> str:  # pragma: no cover
        a, t = self.shape
        cov = float(self.mask.mean()) if self.mask.size else 0.0
        return f"Panel({self.name!r}, A={a}, T={t}, coverage={cov:.1%})"


@dataclasses.dataclass(frozen=True)
class PanelBundle:
    """Several aligned panels over one (tickers, times) grid.

    The daily bundle carries what the reference's canonical daily schema
    carries (``data_io.py:15``): open/high/low/close/adj_close/volume; the
    intraday bundle carries price/volume (``data_io.py:16``).
    """

    panels: dict
    tickers: tuple
    times: np.ndarray

    def __getitem__(self, key: str) -> Panel:
        return self.panels[key]

    def __contains__(self, key: str) -> bool:
        return key in self.panels

    @property
    def fields(self):
        return tuple(self.panels)
