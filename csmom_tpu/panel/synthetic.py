"""Synthetic market data generators (seeded, vectorized).

Two generators:

- ``synthetic_daily_panel`` — a CRSP-like equity panel at arbitrary scale
  (the north-star benchmark shape is 3000 assets x 60 years); geometric
  Brownian daily prices with per-asset vol/drift draws, optional listing /
  delisting windows for masked-lane realism.
- ``synthetic_minute_bars`` — the panel-world analogue of the reference's
  synthetic intraday fallback (``/root/reference/src/data_io.py:251-300``):
  per day, a linear open->close path with N(0, 0.0005) multiplicative noise
  and a sinusoidal U-shaped volume profile normalized to the day's volume.
  The reference builds it with a per-minute Python dict-append loop (its
  third-hottest loop, SURVEY §3); here it is one vectorized array program
  with an explicit PRNG key instead of unseeded global numpy RNG.
"""

from __future__ import annotations

import numpy as np

from csmom_tpu.panel.panel import Panel


# bump when any generator's output changes for the same (shape, seed): disk
# caches of synthesized panels (bench.py) key on this so they can never
# silently serve stale data after a generator edit
SYNTH_VERSION = 1


def synthetic_daily_panel(
    n_assets: int,
    n_days: int,
    seed: int = 0,
    start: str = "1963-07-01",
    annual_vol_range=(0.15, 0.60),
    annual_drift_range=(-0.05, 0.15),
    listing_gaps: bool = False,
) -> Panel:
    """Geometric-Brownian daily close panel with business-day timestamps."""
    rng = np.random.default_rng(seed)
    vol = rng.uniform(*annual_vol_range, size=(n_assets, 1)) / np.sqrt(252.0)
    drift = rng.uniform(*annual_drift_range, size=(n_assets, 1)) / 252.0
    shocks = rng.standard_normal((n_assets, n_days)).astype(np.float64)
    log_prices = np.cumsum(drift + vol * shocks, axis=1)
    prices = 30.0 * np.exp(log_prices - log_prices[:, :1])

    mask = np.ones_like(prices, dtype=bool)
    if listing_gaps:
        # a third of assets list late, a third delist early
        third = n_assets // 3
        starts = rng.integers(0, n_days // 2, size=third)
        ends = rng.integers(n_days // 2, n_days, size=third)
        for i, s in enumerate(starts):
            mask[i, :s] = False
        for i, e in enumerate(ends):
            mask[third + i, e:] = False
        prices = np.where(mask, prices, np.nan)

    # business-day-ish calendar: skip Sat/Sun
    start_d = np.datetime64(start, "D")
    all_days = np.arange(start_d, start_d + np.timedelta64(n_days * 2, "D"))
    dow = (all_days.astype("datetime64[D]").view("int64") + 4) % 7
    bdays = all_days[dow < 5][:n_days]
    return Panel(values=prices, mask=mask, tickers=tuple(f"S{i:05d}" for i in range(n_assets)),
                 times=bdays.astype("datetime64[ns]"), name="synthetic_close")


def synthetic_minute_bars(
    open_p: np.ndarray,
    close_p: np.ndarray,
    day_volume: np.ndarray,
    minutes_per_day: int = 390,
    noise: float = 0.0005,
    seed: int = 0,
):
    """Minute price/volume paths for a block of (asset, day) bars.

    Mirrors ``minute_fallback_from_daily``'s construction exactly, minus its
    Python loop: price path = linspace(open, close) * (1 + N(0, noise));
    volume = sin^2 U-curve + 0.1, normalized, scaled to day volume, floored
    to int.

    Args:
      open_p, close_p, day_volume: f[A, D] daily panels.

    Returns:
      (prices f[A, D, T], volumes i64[A, D, T]) with T = minutes_per_day.
    """
    rng = np.random.default_rng(seed)
    A, D = open_p.shape
    T = minutes_per_day
    frac = np.linspace(0.0, 1.0, T)
    path = open_p[..., None] + (close_p - open_p)[..., None] * frac
    path = path * (1.0 + rng.normal(0.0, noise, size=(A, D, T)))

    base = np.sin(np.linspace(0.0, np.pi, T)) ** 2 + 0.1
    base = base / base.sum()
    vols = np.maximum(day_volume, 1.0)[..., None] * base
    return path, vols.astype(np.int64)
