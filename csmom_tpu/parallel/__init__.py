"""Device-mesh parallelism.

The reference is single-threaded (SURVEY §2 rows 13-15: no parallelism, no
communication backend).  Here distribution is first-class:

- the **asset axis** is the scaling axis (thousands of names): sharded over
  the mesh's ``'assets'`` axis; every signal/backtest kernel is per-asset
  independent except the cross-sectional rank;
- the **grid axis** (J x K parameter cells) shards over an optional
  ``'grid'`` mesh axis — embarrassingly parallel;
- the **time axis** is replicated for the monthly engines (60 years of
  months is tiny) but shardable for the minute-bar event engine: the
  engine's time-serial dependencies are all prefix ops, so the sequence
  axis splits into per-device blocks with small carry exchanges
  (``event_time`` — the framework's sequence parallelism);
- the only collectives are an ``all_gather`` of the [A, T] signal for the
  rank (the one truly global op) and ``psum`` for portfolio reductions —
  both ride ICI on a real pod, and the same code runs multi-host over DCN
  via ``jax.distributed.initialize`` + the process-spanning mesh.
"""

from csmom_tpu.parallel.mesh import (
    auto_mesh,
    distributed_init,
    make_hybrid_mesh,
    make_mesh,
    mesh_topology,
)
from csmom_tpu.parallel.collectives import (
    sharded_banded_backtest,
    sharded_monthly_spread_backtest,
    sharded_jk_grid_backtest,
)
from csmom_tpu.parallel.bootstrap import sharded_block_bootstrap
from csmom_tpu.parallel.event import sharded_event_backtest
from csmom_tpu.parallel.online_ridge import time_sharded_online_ridge_scores
from csmom_tpu.parallel.event_time import (
    time_sharded_event_backtest,
    time_sharded_hysteresis_backtest,
)

__all__ = [
    "time_sharded_online_ridge_scores",
    "make_mesh",
    "auto_mesh",
    "make_hybrid_mesh",
    "mesh_topology",
    "distributed_init",
    "sharded_banded_backtest",
    "time_sharded_hysteresis_backtest",
    "sharded_monthly_spread_backtest",
    "sharded_jk_grid_backtest",
    "sharded_block_bootstrap",
    "sharded_event_backtest",
    "time_sharded_event_backtest",
]
