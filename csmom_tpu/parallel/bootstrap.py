"""Sharded block bootstrap: the resample batch axis over the device mesh.

SURVEY §2 row 14(c): bootstrap resamples are the framework's third
parallelism axis (after assets and grid cells).  Resamples are
embarrassingly parallel — each is an independent gather + reduction over the
same T-month series — so the sample axis shards with **zero collectives**:
each device draws its own slice of the sample axis locally (the same
``circular_block_indices`` under a per-shard fold of the key would change
draws, so the full index matrix is computed identically everywhere and each
shard slices its rows), evaluates its resamples, and only the final
percentile step gathers the S-vector of scalars (bytes, not panels).

Equality with the single-device :func:`csmom_tpu.analytics.block_bootstrap`
is pinned by tests on the CPU mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from csmom_tpu.parallel.compat import shard_map
from jax.sharding import PartitionSpec as P

from csmom_tpu.analytics.bootstrap import BootstrapResult, circular_block_indices
from csmom_tpu.analytics.stats import masked_mean, sharpe


def sharded_block_bootstrap(
    returns,
    valid,
    key,
    mesh,
    n_samples: int = 1000,
    block_len: int = 6,
    freq: int = 12,
    ci_level: float = 0.95,
    axis_name: str = "assets",
) -> BootstrapResult:
    """Block bootstrap with the sample axis sharded over ``mesh[axis_name]``.

    ``n_samples`` must divide by the mesh axis size.  Draws are identical to
    the single-device path (same key -> same index matrix), so results match
    :func:`csmom_tpu.analytics.block_bootstrap` exactly — the device count
    changes wall-clock, never statistics.
    """
    n_shards = mesh.shape[axis_name]
    if n_samples % n_shards:
        raise ValueError(
            f"n_samples={n_samples} not divisible by mesh axis "
            f"{axis_name!r} size {n_shards}"
        )
    T = returns.shape[-1]
    idx = circular_block_indices(key, n_samples, T, block_len)

    @partial(jax.jit, static_argnames=())
    def run(returns, valid, idx):
        def local_fn(r, v, idx_l):
            rs = r[0][idx_l]          # [S_local, T]
            vs = v[0][idx_l]
            return (
                masked_mean(rs, vs)[None],
                sharpe(rs, vs, freq_per_year=freq)[None],
            )

        spec_rep = P()
        means_l, sharpes_l = shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(spec_rep, spec_rep, P(axis_name)),
            out_specs=(P(None, axis_name), P(None, axis_name)),
        )(returns[None, :], valid[None, :], idx)
        return means_l[0], sharpes_l[0]

    means, sharpes = run(jnp.asarray(returns), jnp.asarray(valid), idx)
    alpha = (1.0 - ci_level) / 2.0
    q = jnp.array([alpha, 1.0 - alpha])
    return BootstrapResult(
        mean_samples=means,
        sharpe_samples=sharpes,
        mean_point=masked_mean(jnp.asarray(returns), jnp.asarray(valid)),
        sharpe_point=sharpe(jnp.asarray(returns), jnp.asarray(valid), freq_per_year=freq),
        mean_ci=jnp.nanquantile(means, q),
        sharpe_ci=jnp.nanquantile(sharpes, q),
    )
