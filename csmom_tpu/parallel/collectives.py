"""Sharded backtest engines: shard_map + all_gather/psum over the asset axis.

Communication pattern (SURVEY §2 rows 14-15, §5 'distributed backend'):

- every signal kernel (returns, momentum) runs shard-local — per-asset math;
- the cross-sectional rank is the ONE global op: each shard ``all_gather``s
  the [A_local, M] formation signal into the full [A, M] cross-section
  (12 KB/date at A=3000 — trivial on ICI), computes identical labels, and
  keeps its local slice;
- portfolio aggregation: shard-local one-hot partial sums, one ``psum``
  over the ``'assets'`` mesh axis, then the division — the classic
  reduce-then-finalize split;
- the parameter grid shards over an optional ``'grid'`` mesh axis with NO
  communication at all (cells are independent).

The same code path scales multi-host: build the mesh over
``jax.distributed`` process-spanning devices and the collectives ride DCN
between slices, ICI within.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from csmom_tpu.parallel.compat import shard_map

from csmom_tpu.backtest.grid import (
    GridResult,
    _cohort_partial_sums,
    _finalize_cohorts,
    _holding_month_spreads,
    validate_grid_args,
)
from csmom_tpu.backtest.monthly import decile_partial_sums, decile_means
from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.signals.momentum import (
    formation_listed_mask,
    momentum_dynamic,
    monthly_returns,
)
from csmom_tpu.analytics.stats import sharpe, masked_mean, t_stat, nw_t_stat


def _local_slice(full, axis_name: str, n_local: int):
    """This shard's rows of a gathered array."""
    i = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(full, i * n_local, n_local, axis=0)


def _ranked_labels_local(mom_l, momv_l, n_bins, mode, axis_name="assets"):
    """Distributed cross-sectional rank.

    ``mode='qcut'``/``'rank'``: gather -> rank -> take the local slice (the
    O(A) baseline — 12 KB/date at the north star's A=3000).
    ``mode='rank_hist'``: rank-mode labels via radix-histogram boundary
    selection (:mod:`csmom_tpu.parallel.histrank`) — communication
    independent of A, for universes past ~10k assets.
    """
    if mode == "rank_hist":
        from csmom_tpu.parallel.histrank import histogram_rank_labels

        labels = histogram_rank_labels(mom_l, momv_l, n_bins, axis_name)
        n = lax.psum(jnp.sum(momv_l, axis=0, dtype=jnp.int32), axis_name)
        return labels, jnp.minimum(n, n_bins)
    mom_f = lax.all_gather(mom_l, axis_name, axis=0, tiled=True)
    momv_f = lax.all_gather(momv_l, axis_name, axis=0, tiled=True)
    labels_f, n_eff = decile_assign_panel(mom_f, momv_f, n_bins=n_bins, mode=mode)
    return _local_slice(labels_f, axis_name, mom_l.shape[0]), n_eff


def sharded_monthly_spread_backtest(
    prices,
    mask,
    mesh: Mesh,
    lookback: int = 12,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    freq: int = 12,
):
    """Asset-sharded monthly decile backtest.

    ``prices/mask`` are [A, M] with A divisible by the mesh's asset-shard
    count (use ``parallel.mesh.pad_assets``).  Returns replicated
    ``(spread f[M], spread_valid bool[M], mean, sharpe, tstat)``.
    """

    def local_fn(pv, mv):
        ret_l, retv_l = monthly_returns(pv, mv)
        mom_l, momv_l = momentum_dynamic(pv, mv, lookback, skip)
        # same delisting rule as the single-device engine (shard-local:
        # the time axis is unsharded, so the per-asset last print is exact)
        momv_l = momv_l & formation_listed_mask(mv, skip)
        mom_l = jnp.where(momv_l, mom_l, jnp.nan)
        labels_l, _ = _ranked_labels_local(mom_l, momv_l, n_bins, mode)

        next_ret = jnp.roll(ret_l, -1, axis=1)
        next_valid = jnp.roll(retv_l, -1, axis=1).at[:, -1].set(False) & momv_l

        sums, counts = decile_partial_sums(next_ret, next_valid, labels_l, n_bins)
        sums = lax.psum(sums, "assets")
        counts = lax.psum(counts, "assets")
        means = decile_means(sums, counts)

        spread = means[n_bins - 1] - means[0]
        valid = (counts[n_bins - 1] > 0) & (counts[0] > 0)
        spread = jnp.where(valid, spread, jnp.nan)
        return spread, valid

    spec_in = P("assets", None)
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec_in, spec_in),
        out_specs=(P(), P()),
        check_vma=False,
    )
    spread, valid = jax.jit(fn)(prices, mask)
    return (
        spread,
        valid,
        masked_mean(spread, valid),
        sharpe(spread, valid, freq_per_year=freq),
        t_stat(spread, valid),
    )


def sharded_banded_backtest(
    prices,
    mask,
    mesh: Mesh,
    lookback: int = 12,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    band: int = 1,
    freq: int = 12,
):
    """Asset-sharded hysteresis-banded backtest (``backtest/banded.py``).

    The band recursion is per-asset (an associative-scan parallel prefix,
    see ``banded_books``), so it runs entirely shard-local on each
    shard's book slice — distribution adds
    exactly two communication steps: the shared distributed rank
    (:func:`_ranked_labels_local`) and one ``psum`` of the four per-month
    book partials (long/short sums and counts).  Bit-equal to the
    single-device :func:`banded_from_labels` on the same panel (pinned by
    ``tests/test_sharding.py``).

    Returns replicated ``(spread f[M], spread_valid bool[M], mean,
    sharpe, tstat_nw)``.
    """
    from csmom_tpu.backtest.banded import (
        banded_books,
        book_partials,
        finalize_book_spread,
        validate_band,
    )

    validate_band(band, n_bins)

    def local_fn(pv, mv):
        ret_l, retv_l = monthly_returns(pv, mv)
        mom_l, momv_l = momentum_dynamic(pv, mv, lookback, skip)
        # same delisting rule as the single-device engine (shard-local:
        # the time axis is unsharded, so the per-asset last print is exact)
        momv_l = momv_l & formation_listed_mask(mv, skip)
        mom_l = jnp.where(momv_l, mom_l, jnp.nan)
        labels_l, _ = _ranked_labels_local(mom_l, momv_l, n_bins, mode)
        long_l, short_l = banded_books(labels_l, n_bins, band)
        # the single-device aggregation, distributed by exactly one psum
        partials = lax.psum(
            book_partials(long_l, short_l, ret_l, retv_l), "assets"
        )
        spread, valid, _, _ = finalize_book_spread(partials)
        return spread, valid

    spec_in = P("assets", None)
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec_in, spec_in),
        out_specs=(P(), P()),
        check_vma=False,
    )
    spread, valid = jax.jit(fn)(prices, mask)
    return (
        spread,
        valid,
        masked_mean(spread, valid),
        sharpe(spread, valid, freq_per_year=freq),
        nw_t_stat(spread, valid),
    )


@lru_cache(maxsize=32)
def grid_shard_fn(mesh: Mesh, skip: int, n_bins: int, mode: str,
                  max_hold: int, impl: str):
    """The jitted sharded-grid spread kernel for one (mesh, params) —
    cached so repeated calls (bench reps, the live dispatch after an
    AOT warm) reuse ONE callable instead of retracing per call, and so
    the ``bench-mesh`` manifest profile (:mod:`csmom_tpu.registry.
    builtin`) can lower the exact callable the sharded leg dispatches.

    Returns ``fn(prices, mask, Js, Ks) -> (spreads f[nJ, nK, M],
    live bool[nJ, nK, M])`` with prices/mask asset-sharded, Js
    grid-sharded, Ks replicated.
    """
    H = max_hold

    def local_fn(prices, mask, Js, Ks):
        ret_l, retv_l = monthly_returns(prices, mask)
        listed_l = formation_listed_mask(mask, skip)

        def per_J(J):
            mom_l, momv_l = momentum_dynamic(prices, mask, J, skip)
            momv_l = momv_l & listed_l
            mom_l = jnp.where(momv_l, mom_l, jnp.nan)
            labels_l, _ = _ranked_labels_local(mom_l, momv_l, n_bins, mode)
            return _cohort_partial_sums(labels_l, ret_l, retv_l, n_bins, H,
                                        impl=impl)

        sums, counts = jax.vmap(per_J)(Js)          # [nJ_l, 2, M, H]
        sums = lax.psum(sums, "assets")
        counts = lax.psum(counts, "assets")
        R, R_valid = jax.vmap(_finalize_cohorts)(sums, counts)
        return _holding_month_spreads(R, R_valid, Ks)

    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P("assets", None), P("assets", None), P("grid"), P()),
        out_specs=(P("grid", None, None), P("grid", None, None)),
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_jk_grid_backtest(
    prices,
    mask,
    Js,
    Ks,
    mesh: Mesh,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    max_hold: int | None = None,
    freq: int = 12,
    impl: str = "xla",
) -> GridResult:
    """J x K grid sharded over a ('grid', 'assets') mesh.

    J cells split across the ``'grid'`` mesh axis (nJ divisible by its
    size); assets shard across ``'assets'``.  Returns the same
    :class:`~csmom_tpu.backtest.grid.GridResult` as the single-device
    engine — spreads grid-sharded [nJ, nK, M], stats (incl. the
    Newey–West t-stat, lag=K: overlap spreads are serially correlated by
    construction) replicated — so the two paths are drop-in equivalent.
    ``impl='pallas'`` streams the cohort aggregation through the fused
    VMEM kernel shard-locally, exactly as in ``jk_grid_backtest``.
    """
    max_hold = validate_grid_args(Ks, max_hold)
    Js = jnp.asarray(Js)
    Ks = jnp.asarray(Ks)
    spreads, live = grid_shard_fn(mesh, skip, n_bins, mode, max_hold,
                                  impl)(prices, mask, Js, Ks)
    return GridResult(
        spreads=spreads,
        spread_valid=live,
        mean_spread=masked_mean(spreads, live),
        ann_sharpe=sharpe(spreads, live, freq_per_year=freq),
        tstat=t_stat(spreads, live),
        tstat_nw=nw_t_stat(spreads, live, lags=Ks[None, :], max_lag=max_hold),
        Js=Js,
        Ks=Ks,
        skip=jnp.asarray(skip),
        n_bins=n_bins,
        mode=mode,
    )
