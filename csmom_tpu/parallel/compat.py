"""jax API compatibility shims for the parallel layer.

One symbol today: ``shard_map``.  Newer jax exports it at the top level
with a ``check_vma`` kwarg; the 0.4.x line this image ships keeps it in
``jax.experimental.shard_map`` under the older ``check_rep`` name for the
same replication/varying-manual-axes check.  Every sharded engine in this
package imports from here so the version split lives in exactly one
place (and so the AOT shape manifest can import the sharded entry points
on either jax line).
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)

except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


__all__ = ["shard_map"]
