"""Sharded event backtest: the intraday engine over the asset mesh axis.

The event engine is per-asset independent except three global reductions —
signed order flow per bar (cash ledger), the mark-to-market sum (portfolio
value) and trade counts — all ``psum``s of [T]-vectors or scalars, so
sharding the minute panel's asset axis costs 3 small collectives per call
and no resharding.  Equality with the single-device engine is pinned on the
CPU mesh (tests/test_sharded_event.py).
"""

from __future__ import annotations

from functools import partial

import jax
from csmom_tpu.parallel.compat import shard_map
from jax.sharding import PartitionSpec as P

from csmom_tpu.backtest.event import EventResult, event_backtest


def sharded_event_backtest(
    price,
    valid,
    score,
    adv,
    vol,
    mesh,
    axis_name: str = "assets",
    **kwargs,
) -> EventResult:
    """Run :func:`csmom_tpu.backtest.event.event_backtest` with the asset
    axis sharded over ``mesh[axis_name]``.

    A must divide by the mesh axis size (pad with dead lanes via
    :func:`csmom_tpu.parallel.mesh.pad_assets` — a lane with ``valid=False``
    everywhere never trades and never marks).  Limit mode works sharded:
    the engine's fill draws are counter-keyed by global (asset, bar) cell
    (:func:`csmom_tpu.backtest.event.counter_uniform`), so a replicated
    ``fill_key`` yields exactly the single-device fills on any shard count.
    """
    A = price.shape[0]
    n_shards = mesh.shape[axis_name]
    if A % n_shards:
        raise ValueError(f"A={A} not divisible by {n_shards} shards; pad_assets first")

    fn = shard_map(
        partial(event_backtest, axis_name=axis_name, **kwargs),
        mesh=mesh,
        in_specs=(
            P(axis_name, None), P(axis_name, None), P(axis_name, None),
            P(axis_name), P(axis_name),
        ),
        out_specs=EventResult(
            pnl=P(),
            bar_mask=P(),
            portfolio_value=P(),
            cash=P(),
            positions=P(axis_name, None),
            trade_side=P(axis_name, None),
            exec_price=P(axis_name, None),
            impact=P(axis_name),
            total_pnl=P(),
            n_trades=P(),
            n_buys=P(),
            n_sells=P(),
            net_notional=P(),
        ),
    )
    return fn(price, valid, score, adv, vol)
