"""Sequence-parallel event backtest: the minute axis sharded over the mesh.

The single-device engine (:mod:`csmom_tpu.backtest.event`) is already a
panel program whose only time-serial dependencies are prefix ops: the
position book and cash ledger are cumulative sums, the mark price is a
running "last observed" (associative max), and PnL differences portfolio
value at consecutive bars.  Every one of those admits a *blocked scan*:
each device computes its local prefix over its time block, exchanges one
small per-block carry (an ``all_gather`` over the ``'time'`` mesh axis),
and adds the exclusive prefix of the earlier blocks' carries.  This is the
framework's sequence parallelism — the direct analogue of sharding a
transformer's sequence axis, with prefix carries in place of a KV ring —
and it composes with the asset axis for a full 2D sharding of the minute
panel.

Per-call carries (for an [A, T] panel on an (assets=a, time=t) mesh):

- position book:   i32[A/a] block trade sum        -> all_gather [t, A/a]
- cash ledger:     one block flow sum (price dtype) -> all_gather [t]
- mark price:      (bool[A/a], f[A/a]) last price observed in block
- portfolio value: (bool, f) last bar's PV in block
- trade counters:  5 scalars (psum)

Nothing scales with T; all carries ride ICI.  Cross-asset reductions
(order flow, marks, bar occupancy, counters) additionally ``psum`` over
the asset axis exactly as in the 1D asset-sharded engine
(:mod:`csmom_tpu.parallel.event`).

Reference semantics pinned: ``SimpleEventBacktester``
(``/root/reference/src/backtester.py:20-65``) via equality with
:func:`csmom_tpu.backtest.event.event_backtest` on the CPU mesh
(tests/test_sequence_parallel.py) — integer state (positions, sides) is
exact; float state matches to tight tolerance (blocked summation changes
fp association, so it is not bit-identical).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from csmom_tpu.backtest.event import EventResult, market_fill_prices, threshold_sides
from csmom_tpu.costs.impact import square_root_impact


def pad_time(price, valid, score, n_shards: int):
    """Pad the trailing time axis to a multiple of the shard count.

    Padded columns are ``valid=False`` NaN minutes: no bar, no trade, no
    mark refresh — results over the original columns are unchanged.
    Returns ``(price, valid, score, T_original)`` (host-side helper,
    mirror of :func:`csmom_tpu.parallel.mesh.pad_assets`).
    """
    T = price.shape[1]
    pad = (-T) % n_shards
    if pad == 0:
        return price, valid, score, T
    ppad = np.full(price.shape[:1] + (pad,), np.nan, dtype=price.dtype)
    spad = np.zeros(score.shape[:1] + (pad,), dtype=score.dtype)
    mpad = np.zeros(valid.shape[:1] + (pad,), dtype=bool)
    return (
        np.concatenate([price, ppad], axis=1),
        np.concatenate([valid, mpad], axis=1),
        np.concatenate([score, spad], axis=1),
        T,
    )


def _exclusive_prefix_sum(block_total, axis_name: str):
    """Sum of this quantity over all earlier blocks along ``axis_name``."""
    g = lax.all_gather(block_total, axis_name)          # [nb, ...]
    i = lax.axis_index(axis_name)
    nb = g.shape[0]
    m = (jnp.arange(nb) < i).reshape((nb,) + (1,) * (g.ndim - 1))
    return jnp.sum(jnp.where(m, g, 0), axis=0)


def _carry_from_left(has_blk, val_blk, axis_name: str):
    """Rightmost earlier block's value: ``(exists, value)`` per element.

    ``has_blk``/``val_blk`` are this block's carry (did the block observe
    the quantity; its last value).  Returns, for each element, whether any
    earlier block observed it and the most recent such value — the
    exclusive prefix of a "take the right operand if set" monoid.
    """
    has_g = lax.all_gather(has_blk, axis_name)          # [nb, X]
    val_g = lax.all_gather(val_blk, axis_name)          # [nb, X]
    i = lax.axis_index(axis_name)
    nb = has_g.shape[0]
    idx = jnp.arange(nb)
    cand = jnp.where(has_g & (idx[:, None] < i), idx[:, None], -1)
    jbest = jnp.max(cand, axis=0)                       # [X]
    val = jnp.take_along_axis(val_g, jnp.clip(jbest, 0, nb - 1)[None, :], axis=0)[0]
    return jbest >= 0, val


@lru_cache(maxsize=32)
def _compiled(mesh, time_axis, asset_axis, size_shares, threshold, cash0, spread):
    """Build + jit the sharded program once per (mesh, axes, params)."""
    asum = (lambda x: lax.psum(x, asset_axis)) if asset_axis else (lambda x: x)

    def local_fn(price, valid, score, adv, vol):
        A_l, T_l = price.shape
        dtype = price.dtype

        # ---- block-local order generation + fills (backtester.py:25-44),
        #      shared helpers pin semantics to the single-device engine ----
        side = threshold_sides(valid, score, threshold)
        traded = side != 0
        impact = square_root_impact(
            jnp.asarray(float(size_shares), dtype), adv.astype(dtype), vol.astype(dtype)
        )
        exec_base = jnp.nan_to_num(price)
        fill = market_fill_prices(exec_base, side, traded, impact, spread)
        shares = side * size_shares
        notional = fill * shares.astype(dtype)

        # ---- position book: blocked cumsum + position carry ----
        pos_local = jnp.cumsum(shares, axis=1)
        positions = pos_local + _exclusive_prefix_sum(pos_local[:, -1], time_axis)[:, None]

        # ---- cash ledger: blocked cumsum of cross-asset order flow ----
        flow = asum(jnp.sum(notional, axis=0))          # [T_l]
        cum_flow = jnp.cumsum(flow)
        cash = cash0 - (cum_flow + _exclusive_prefix_sum(cum_flow[-1], time_axis))

        # ---- mark price: blocked last-observed + (has, price) carry ----
        t_loc = jnp.arange(T_l, dtype=jnp.int32)
        obs = jnp.where(valid, t_loc[None, :], -1)
        last_obs = lax.associative_scan(jnp.maximum, obs, axis=1)
        mark_local = jnp.take_along_axis(exec_base, jnp.clip(last_obs, 0, T_l - 1), axis=1)
        blk_has = last_obs[:, -1] >= 0
        blk_price = jnp.take_along_axis(
            exec_base, jnp.clip(last_obs[:, -1:], 0, T_l - 1), axis=1
        )[:, 0]
        prev_has, prev_price = _carry_from_left(
            blk_has, jnp.where(blk_has, blk_price, 0.0), time_axis
        )
        mark = jnp.where(
            last_obs >= 0,
            mark_local,
            jnp.where(prev_has[:, None], prev_price[:, None], 0.0),
        )

        pv = cash + asum(jnp.sum(positions.astype(dtype) * mark, axis=0))

        # ---- per-bar PnL: blocked prev-bar gather + (has, pv) carry ----
        bar_mask = asum(jnp.sum(valid, axis=0)) > 0
        obs_bar = jnp.where(bar_mask, t_loc, -1)
        last_bar = lax.associative_scan(jnp.maximum, obs_bar)
        prev_bar = jnp.where(bar_mask, jnp.roll(last_bar, 1).at[0].set(-1), -1)
        pv_prev = pv[jnp.clip(prev_bar, 0, T_l - 1)]
        blk_has_bar = last_bar[-1:] >= 0
        blk_pv = jnp.where(blk_has_bar, pv[jnp.clip(last_bar[-1:], 0, T_l - 1)], 0.0)
        pv_carry_has, pv_carry = _carry_from_left(blk_has_bar, blk_pv, time_axis)
        pnl = jnp.where(
            bar_mask,
            jnp.where(
                prev_bar >= 0,
                pv - pv_prev,
                jnp.where(pv_carry_has[0], pv - pv_carry[0], 0.0),
            ),
            0.0,
        )

        tsum = lambda x: lax.psum(x, time_axis)
        return EventResult(
            pnl=pnl,
            bar_mask=bar_mask,
            portfolio_value=pv,
            cash=cash,
            positions=positions,
            trade_side=side.astype(jnp.int8),
            exec_price=fill,
            impact=impact,
            total_pnl=tsum(jnp.sum(pnl)),
            n_trades=tsum(asum(jnp.sum(traded))).astype(jnp.int32),
            n_buys=tsum(asum(jnp.sum(side > 0))).astype(jnp.int32),
            n_sells=tsum(asum(jnp.sum(side < 0))).astype(jnp.int32),
            net_notional=tsum(jnp.sum(flow)),
        )

    aspec = asset_axis  # None -> unsharded axis
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            P(aspec, time_axis), P(aspec, time_axis), P(aspec, time_axis),
            P(aspec), P(aspec),
        ),
        out_specs=EventResult(
            pnl=P(time_axis),
            bar_mask=P(time_axis),
            portfolio_value=P(time_axis),
            cash=P(time_axis),
            positions=P(aspec, time_axis),
            trade_side=P(aspec, time_axis),
            exec_price=P(aspec, time_axis),
            impact=P(aspec),
            total_pnl=P(),
            n_trades=P(),
            n_buys=P(),
            n_sells=P(),
            net_notional=P(),
        ),
    )
    return jax.jit(fn)


def time_sharded_event_backtest(
    price,
    valid,
    score,
    adv,
    vol,
    mesh: Mesh,
    time_axis: str = "time",
    asset_axis: str | None = None,
    size_shares: int = 50,
    threshold: float = 1e-5,
    cash0: float = 1_000_000.0,
    spread: float = 0.001,
    latency_bars: int = 0,
    order_type: str = "market",
) -> EventResult:
    """Run the event backtest with the minute axis sharded over
    ``mesh[time_axis]`` (and optionally assets over ``mesh[asset_axis]``).

    T must divide by the time-shard count (:func:`pad_time`), and A by the
    asset-shard count when ``asset_axis`` is given
    (:func:`csmom_tpu.parallel.mesh.pad_assets`).  Build a 2D mesh with
    ``make_mesh(devices, grid_axis=a, axis_names=('assets', 'time'))``.
    The compiled program is cached per (mesh, axes, scalar params).

    Only the deterministic market path is supported sharded: latency
    fills can land in a later time block (a halo exchange, not a prefix
    carry) and limit-mode PRNG draws are not shard-invariant — run those
    single-device or asset-sharded (latency) instead.
    """
    if order_type != "market":
        raise NotImplementedError(
            "time-sharded engine supports order_type='market' only; limit "
            "draws are not shard-invariant across time blocks"
        )
    if latency_bars != 0:
        raise NotImplementedError(
            "latency fills cross time-block boundaries (halo, not prefix "
            "carry); use the single-device or asset-sharded engine"
        )
    A, T = price.shape
    if time_axis not in mesh.shape:
        raise ValueError(
            f"mesh has axes {tuple(mesh.shape)}, no {time_axis!r}; build it "
            "with make_mesh(devices, grid_axis=a, axis_names=('assets', 'time'))"
        )
    nt = mesh.shape[time_axis]
    if T % nt:
        raise ValueError(f"T={T} not divisible by {nt} time shards; pad_time first")
    if asset_axis is not None:
        na = mesh.shape[asset_axis]
        if A % na:
            raise ValueError(f"A={A} not divisible by {na} asset shards; pad_assets first")

    fn = _compiled(
        mesh, time_axis, asset_axis, int(size_shares), float(threshold),
        float(cash0), float(spread),
    )
    return fn(price, valid, score, adv, vol)
