"""Sequence-parallel event backtest: the minute axis sharded over the mesh.

The single-device engine (:mod:`csmom_tpu.backtest.event`) is already a
panel program whose only time-serial dependencies are prefix ops: the
position book and cash ledger are cumulative sums, the mark price is a
running "last observed" (associative max), and PnL differences portfolio
value at consecutive bars.  Every one of those admits a *blocked scan*:
each device computes its local prefix over its time block, exchanges one
small per-block carry (an ``all_gather`` over the ``'time'`` mesh axis),
and adds the exclusive prefix of the earlier blocks' carries.  This is the
framework's sequence parallelism — the direct analogue of sharding a
transformer's sequence axis, with prefix carries in place of a KV ring —
and it composes with the asset axis for a full 2D sharding of the minute
panel.

Per-call carries (for an [A, T] panel on an (assets=a, time=t) mesh):

- position book:   i32[A/a] block trade sum        -> all_gather [t, A/a]
- cash ledger:     one block flow sum (price dtype) -> all_gather [t]
- mark price:      (bool[A/a], f[A/a]) last price observed in block
- portfolio value: (bool, f) last bar's PV in block
- trade counters:  5 scalars (psum)

Nothing scales with T; all carries ride ICI.  Cross-asset reductions
(order flow, marks, bar occupancy, counters) additionally ``psum`` over
the asset axis exactly as in the 1D asset-sharded engine
(:mod:`csmom_tpu.parallel.event`).

Reference semantics pinned: ``SimpleEventBacktester``
(``/root/reference/src/backtester.py:20-65``) via equality with
:func:`csmom_tpu.backtest.event.event_backtest` on the CPU mesh
(tests/test_sequence_parallel.py) — integer state (positions, sides) is
exact; float state matches to tight tolerance (blocked summation changes
fp association, so it is not bit-identical).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from csmom_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from csmom_tpu.backtest.event import (
    EventResult,
    counter_uniform,
    limit_fill_price,
    limit_fill_probability,
    market_fill_prices,
    threshold_sides,
)
from csmom_tpu.costs.impact import square_root_impact


def pad_time(price, valid, score, n_shards: int):
    """Pad the trailing time axis to a multiple of the shard count.

    Padded columns are ``valid=False`` NaN minutes: no bar, no trade, no
    mark refresh — results over the original columns are unchanged.
    Returns ``(price, valid, score, T_original)`` (host-side helper,
    mirror of :func:`csmom_tpu.parallel.mesh.pad_assets`).
    """
    T = price.shape[1]
    pad = (-T) % n_shards
    if pad == 0:
        return price, valid, score, T
    ppad = np.full(price.shape[:1] + (pad,), np.nan, dtype=price.dtype)
    spad = np.zeros(score.shape[:1] + (pad,), dtype=score.dtype)
    mpad = np.zeros(valid.shape[:1] + (pad,), dtype=bool)
    return (
        np.concatenate([price, ppad], axis=1),
        np.concatenate([valid, mpad], axis=1),
        np.concatenate([score, spad], axis=1),
        T,
    )


def _exclusive_prefix_sum(block_total, axis_name: str):
    """Sum of this quantity over all earlier blocks along ``axis_name``."""
    g = lax.all_gather(block_total, axis_name)          # [nb, ...]
    i = lax.axis_index(axis_name)
    nb = g.shape[0]
    m = (jnp.arange(nb) < i).reshape((nb,) + (1,) * (g.ndim - 1))
    return jnp.sum(jnp.where(m, g, 0), axis=0)


def _carry_from_left(has_blk, val_blk, axis_name: str):
    """Rightmost earlier block's value: ``(exists, value)`` per element.

    ``has_blk``/``val_blk`` are this block's carry (did the block observe
    the quantity; its last value).  Returns, for each element, whether any
    earlier block observed it and the most recent such value — the
    exclusive prefix of a "take the right operand if set" monoid.
    """
    has_g = lax.all_gather(has_blk, axis_name)          # [nb, X]
    val_g = lax.all_gather(val_blk, axis_name)          # [nb, X]
    i = lax.axis_index(axis_name)
    nb = has_g.shape[0]
    idx = jnp.arange(nb)
    cand = jnp.where(has_g & (idx[:, None] < i), idx[:, None], -1)
    jbest = jnp.max(cand, axis=0)                       # [X]
    val = jnp.take_along_axis(val_g, jnp.clip(jbest, 0, nb - 1)[None, :], axis=0)[0]
    return jbest >= 0, val


def _latency_settle(price, valid, side, traded, impact, spread, size_shares,
                    latency_bars, time_axis: str, nt: int, fill_fn=None):
    """Latency fills under time sharding: the halo exchange.

    Single-device semantics (``backtest.event``): an order decided at event
    row t executes at the asset's first event row >= t+L at *that* row's
    price; no such row -> dropped.  Sharded, a fill lands in one of three
    places, each with its own delivery mechanism:

      1. this block            -> local scatter-add;
      2. the next block        -> one ``ppermute`` halo: the neighbor's
         next-event-index and price blocks come left, the settled
         (shares, notional) buffer goes right;
      3. two-plus blocks ahead -> every such order from one (block, asset)
         fills at the *same* row (the asset's first event >= the next-next
         block start) at the same price, so they aggregate into per-asset
         (shares, notional) totals exchanged via one ``all_gather`` of
         [n_blocks, A_l] summaries; each block scatter-adds the totals
         whose fill row lands in its range.

    Requires L <= block length (a fill target then never skips past the
    next block).  Returns ``(side, traded, fill, settle_shares,
    settle_notional)`` — side/traded with dropped orders zeroed, fill =
    per-decision exec price (reference keeps the trade log at decision
    timestamps), settle_* on fill rows.  ``fill_fn(exec_base, side)``
    overrides the market fill-price formula (limit mode's side-independent
    price improvement); default = market.
    """
    A_l, T_l = price.shape
    dtype = price.dtype
    L = latency_bars
    BIG = jnp.int32(2 ** 30)
    blk = lax.axis_index(time_axis)
    t_loc = jnp.arange(T_l, dtype=jnp.int32)
    rows = jnp.arange(A_l)[:, None]
    pz = jnp.nan_to_num(price)
    cost = spread / 2.0 + impact[:, None]              # [A_l, 1]

    # local first event at/after each slot (T_l sentinel = none)
    nxt_loc = lax.associative_scan(
        jnp.minimum, jnp.where(valid, t_loc[None, :], T_l), axis=1, reverse=True
    )

    # per-block first event + its price -> faraway carry [nt, A_l]
    first_idx = nxt_loc[:, 0]
    has_first = first_idx < T_l
    first_price = jnp.take_along_axis(
        pz, jnp.clip(first_idx, 0, T_l - 1)[:, None], axis=1
    )[:, 0]
    first_glob = jnp.where(has_first, blk * T_l + first_idx, BIG)
    g_idx = lax.all_gather(first_glob, time_axis)       # [nt, A_l]
    g_price = lax.all_gather(jnp.where(has_first, first_price, 0.0), time_axis)
    # first event in blocks >= blk+2, with its price
    b_ids = jnp.arange(nt, dtype=jnp.int32)
    m2 = (b_ids >= blk + 2)[:, None]
    fut_idx = jnp.min(jnp.where(m2, g_idx, BIG), axis=0)           # [A_l]
    fut_arg = jnp.argmin(jnp.where(m2, g_idx, BIG), axis=0)
    fut_price = jnp.take_along_axis(g_price, fut_arg[None, :], axis=0)[0]

    # right halo: neighbor blk+1's next-event indices and prices
    perm_left = [(i, i - 1) for i in range(1, nt)]      # data moves to lower blk
    nxt_r = lax.ppermute(nxt_loc, time_axis, perm_left)
    price_r = lax.ppermute(pz, time_axis, perm_left)
    halo_ok = lax.ppermute(jnp.ones((), jnp.int32), time_axis, perm_left) > 0

    # resolve each decision's fill row / price ---------------------------
    t_glob = blk * T_l + t_loc
    target = t_glob + L                                 # [T_l] global
    tgt_loc = target - blk * T_l                        # = t_loc + L
    loc_ok = tgt_loc <= T_l - 1
    nxt1 = nxt_loc[:, jnp.clip(tgt_loc, 0, T_l - 1)]    # [A_l, T_l]
    case1 = loc_ok[None, :] & (nxt1 < T_l)
    t2 = jnp.clip(target - (blk + 1) * T_l, 0, T_l - 1)
    nxt2 = nxt_r[:, t2]                                 # [A_l, T_l]
    case2 = ~case1 & halo_ok & (nxt2 < T_l)
    case3 = ~case1 & ~case2 & (fut_idx < BIG)[:, None]
    filled = case1 | case2 | case3

    side = jnp.where(traded & filled, side, 0)          # drop unfilled
    traded = side != 0
    price1 = jnp.take_along_axis(pz, jnp.clip(nxt1, 0, T_l - 1), axis=1)
    price2 = jnp.take_along_axis(price_r, jnp.clip(nxt2, 0, T_l - 1), axis=1)
    exec_base = jnp.where(case1, price1,
                          jnp.where(case2, price2, fut_price[:, None]))
    if fill_fn is None:
        fill_fn = lambda eb, s: eb * (1.0 + s * cost)  # market (execution_models.py:9-12)
    fill = jnp.where(traded, fill_fn(exec_base, side), 0.0)
    shares = side * size_shares
    notional = fill * shares.astype(dtype)

    # deliver settles ----------------------------------------------------
    dump = jnp.int32(T_l)                               # spill column
    def scatter(idx, mask, vals, dt):
        buf = jnp.zeros((A_l, T_l + 1), dt)
        return buf.at[rows, jnp.where(mask, idx, dump)].add(
            jnp.where(mask, vals, jnp.zeros((), dt))
        )[:, :T_l]

    m1 = case1 & traded
    settle_sh = scatter(nxt1, m1, shares, shares.dtype)
    settle_no = scatter(nxt1, m1, notional, dtype)

    m2d = case2 & traded
    buf_sh = scatter(nxt2, m2d, shares, shares.dtype)
    buf_no = scatter(nxt2, m2d, notional, dtype)
    perm_right = [(i, i + 1) for i in range(nt - 1)]
    settle_sh = settle_sh + lax.ppermute(buf_sh, time_axis, perm_right)
    settle_no = settle_no + lax.ppermute(buf_no, time_axis, perm_right)

    m3 = case3 & traded
    far_sh = jnp.sum(jnp.where(m3, shares, 0), axis=1,
                     dtype=shares.dtype)                          # [A_l]
    far_no = jnp.sum(jnp.where(m3, notional, 0.0), axis=1)
    gf_sh = lax.all_gather(far_sh, time_axis)                     # [nt, A_l]
    gf_no = lax.all_gather(far_no, time_axis)
    gf_row = lax.all_gather(jnp.where(fut_idx < BIG, fut_idx, BIG), time_axis)
    mine = (gf_row >= blk * T_l) & (gf_row < (blk + 1) * T_l)     # [nt, A_l]
    row_loc = jnp.where(mine, gf_row - blk * T_l, dump)
    for j in range(nt):  # nt is small and static; scatter one source block at a time
        settle_sh = jnp.concatenate(
            [settle_sh, jnp.zeros((A_l, 1), settle_sh.dtype)], axis=1
        ).at[rows[:, 0], row_loc[j]].add(
            jnp.where(mine[j], gf_sh[j], 0)
        )[:, :T_l]
        settle_no = jnp.concatenate(
            [settle_no, jnp.zeros((A_l, 1), dtype)], axis=1
        ).at[rows[:, 0], row_loc[j]].add(
            jnp.where(mine[j], gf_no[j], 0.0)
        )[:, :T_l]
    return side, traded, fill, settle_sh, settle_no


def _validate_time_layout(mesh, A: int, T: int, time_axis: str,
                          asset_axis) -> int:
    """Shared layout validation for the time-sharded engines; returns the
    time-shard count."""
    if time_axis not in mesh.shape:
        raise ValueError(
            f"mesh has axes {tuple(mesh.shape)}, no {time_axis!r}; build it "
            "with make_mesh(devices, grid_axis=a, axis_names=('assets', 'time'))"
        )
    nt = mesh.shape[time_axis]
    if T % nt:
        raise ValueError(f"T={T} not divisible by {nt} time shards; pad_time first")
    if asset_axis is not None:
        na = mesh.shape[asset_axis]
        if A % na:
            raise ValueError(f"A={A} not divisible by {na} asset shards; pad_assets first")
    return nt


def _blocked_settle_tail(price, valid, shares_settle, notional_settle, side,
                         fill, traded, impact, cash0, asum, time_axis: str):
    """Blocked form of the event engines' shared accounting tail (the
    single-device twin is ``event._settle_mark_and_wrap``): every global
    prefix becomes a block-local prefix plus one small carry exchange —
    position/cash cumsums via :func:`_exclusive_prefix_sum`, marks and
    prev-bar PV via :func:`_carry_from_left`.  Used by the plain and
    hysteresis time-sharded engines so the accounting cannot drift."""
    A_l, T_l = price.shape
    dtype = price.dtype

    # ---- position book: blocked cumsum + position carry ----
    pos_local = jnp.cumsum(shares_settle, axis=1)
    positions = pos_local + _exclusive_prefix_sum(pos_local[:, -1], time_axis)[:, None]

    # ---- cash ledger: blocked cumsum of cross-asset order flow ----
    flow = asum(jnp.sum(notional_settle, axis=0))   # [T_l]
    cum_flow = jnp.cumsum(flow)
    cash = cash0 - (cum_flow + _exclusive_prefix_sum(cum_flow[-1], time_axis))

    # ---- mark price: blocked last-observed + (has, price) carry ----
    pz = jnp.nan_to_num(price)
    t_loc = jnp.arange(T_l, dtype=jnp.int32)
    obs = jnp.where(valid, t_loc[None, :], -1)
    last_obs = lax.associative_scan(jnp.maximum, obs, axis=1)
    mark_local = jnp.take_along_axis(pz, jnp.clip(last_obs, 0, T_l - 1), axis=1)
    blk_has = last_obs[:, -1] >= 0
    blk_price = jnp.take_along_axis(
        pz, jnp.clip(last_obs[:, -1:], 0, T_l - 1), axis=1
    )[:, 0]
    prev_has, prev_price = _carry_from_left(
        blk_has, jnp.where(blk_has, blk_price, 0.0), time_axis
    )
    mark = jnp.where(
        last_obs >= 0,
        mark_local,
        jnp.where(prev_has[:, None], prev_price[:, None], 0.0),
    )

    pv = cash + asum(jnp.sum(positions.astype(dtype) * mark, axis=0))

    # ---- per-bar PnL: blocked prev-bar gather + (has, pv) carry ----
    bar_mask = asum(jnp.sum(valid, axis=0)) > 0
    obs_bar = jnp.where(bar_mask, t_loc, -1)
    last_bar = lax.associative_scan(jnp.maximum, obs_bar)
    prev_bar = jnp.where(bar_mask, jnp.roll(last_bar, 1).at[0].set(-1), -1)
    pv_prev = pv[jnp.clip(prev_bar, 0, T_l - 1)]
    blk_has_bar = last_bar[-1:] >= 0
    blk_pv = jnp.where(blk_has_bar, pv[jnp.clip(last_bar[-1:], 0, T_l - 1)], 0.0)
    pv_carry_has, pv_carry = _carry_from_left(blk_has_bar, blk_pv, time_axis)
    pnl = jnp.where(
        bar_mask,
        jnp.where(
            prev_bar >= 0,
            pv - pv_prev,
            jnp.where(pv_carry_has[0], pv - pv_carry[0], 0.0),
        ),
        0.0,
    )

    tsum = lambda x: lax.psum(x, time_axis)
    return EventResult(
        pnl=pnl,
        bar_mask=bar_mask,
        portfolio_value=pv,
        cash=cash,
        positions=positions,
        trade_side=side.astype(jnp.int8),
        exec_price=fill,
        impact=impact,
        total_pnl=tsum(jnp.sum(pnl)),
        n_trades=tsum(asum(jnp.sum(traded))).astype(jnp.int32),
        n_buys=tsum(asum(jnp.sum(side > 0))).astype(jnp.int32),
        n_sells=tsum(asum(jnp.sum(side < 0))).astype(jnp.int32),
        net_notional=tsum(jnp.sum(flow)),
    )


@lru_cache(maxsize=32)
def _compiled(mesh, time_axis, asset_axis, size_shares, threshold, cash0, spread,
              latency_bars=0, order_type="market", aggressiveness=0.5):
    """Build + jit the sharded program once per (mesh, axes, params)."""
    asum = (lambda x: lax.psum(x, asset_axis)) if asset_axis else (lambda x: x)
    nt = mesh.shape[time_axis]

    def local_fn(price, valid, score, adv, vol, fill_key):
        A_l, T_l = price.shape
        dtype = price.dtype

        # ---- block-local order generation + fills (backtester.py:25-44),
        #      shared helpers pin semantics to the single-device engine ----
        side = threshold_sides(valid, score, threshold)
        traded = side != 0
        if order_type == "limit":
            # counter-keyed draws (global cell ids) == single-device stream
            p_fill = limit_fill_probability(adv, size_shares, aggressiveness, dtype)
            a_off = lax.axis_index(asset_axis) * A_l if asset_axis else 0
            t_off = lax.axis_index(time_axis) * T_l
            u = counter_uniform(fill_key, (A_l, T_l), a_off, t_off, dtype)
            side = jnp.where(u < p_fill[:, None], side, 0)
            traded = side != 0
        impact = square_root_impact(
            jnp.asarray(float(size_shares), dtype), adv.astype(dtype), vol.astype(dtype)
        )
        limit_fill_fn = (
            (lambda eb, s: limit_fill_price(eb, aggressiveness, spread))
            if order_type == "limit" else None
        )
        if latency_bars > 0:
            side, traded, fill, shares_settle, notional_settle = _latency_settle(
                price, valid, side, traded, impact, spread, size_shares,
                latency_bars, time_axis, nt, fill_fn=limit_fill_fn,
            )
            shares = side * size_shares
        else:
            exec_base = jnp.nan_to_num(price)
            if order_type == "limit":
                fill = jnp.where(traded, limit_fill_fn(exec_base, side), 0.0)
            else:
                fill = market_fill_prices(exec_base, side, traded, impact, spread)
            shares = side * size_shares
            shares_settle = shares
            notional_settle = fill * shares.astype(dtype)

        return _blocked_settle_tail(
            price, valid, shares_settle, notional_settle, side, fill,
            traded, impact, cash0, asum, time_axis,
        )

    aspec = asset_axis  # None -> unsharded axis
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            P(aspec, time_axis), P(aspec, time_axis), P(aspec, time_axis),
            P(aspec), P(aspec), P(),
        ),
        out_specs=EventResult(
            pnl=P(time_axis),
            bar_mask=P(time_axis),
            portfolio_value=P(time_axis),
            cash=P(time_axis),
            positions=P(aspec, time_axis),
            trade_side=P(aspec, time_axis),
            exec_price=P(aspec, time_axis),
            impact=P(aspec),
            total_pnl=P(),
            n_trades=P(),
            n_buys=P(),
            n_sells=P(),
            net_notional=P(),
        ),
    )
    return jax.jit(fn)


def time_sharded_event_backtest(
    price,
    valid,
    score,
    adv,
    vol,
    mesh: Mesh,
    time_axis: str = "time",
    asset_axis: str | None = None,
    size_shares: int = 50,
    threshold: float = 1e-5,
    cash0: float = 1_000_000.0,
    spread: float = 0.001,
    latency_bars: int = 0,
    order_type: str = "market",
    aggressiveness: float = 0.5,
    fill_key=None,
) -> EventResult:
    """Run the event backtest with the minute axis sharded over
    ``mesh[time_axis]`` (and optionally assets over ``mesh[asset_axis]``).

    T must divide by the time-shard count (:func:`pad_time`), and A by the
    asset-shard count when ``asset_axis`` is given
    (:func:`csmom_tpu.parallel.mesh.pad_assets`).  Build a 2D mesh with
    ``make_mesh(devices, grid_axis=a, axis_names=('assets', 'time'))``.
    The compiled program is cached per (mesh, axes, scalar params).

    Latency fills are supported for ``latency_bars <= T // n_time_shards``
    via the halo exchange in :func:`_latency_settle` (neighbor ppermute for
    next-block fills, aggregated all_gather for farther ones).  Limit mode
    works sharded: fill draws are counter-keyed by global (asset, bar)
    cell (:func:`csmom_tpu.backtest.event.counter_uniform`), so the
    replicated ``fill_key`` reproduces the single-device fills on any
    (assets x time) layout.
    """
    if order_type == "limit":
        if fill_key is None:
            raise ValueError("order_type='limit' requires fill_key")
    elif order_type != "market":
        raise ValueError(f"unknown order_type {order_type!r}")
    A, T = price.shape
    nt = _validate_time_layout(mesh, A, T, time_axis, asset_axis)
    if latency_bars < 0 or latency_bars > T // nt:
        raise ValueError(
            f"latency_bars={latency_bars} exceeds the time-block length "
            f"{T // nt}; a fill target would skip past the halo neighbor — "
            "use fewer time shards or the asset-sharded engine"
        )

    fn = _compiled(
        mesh, time_axis, asset_axis, int(size_shares), float(threshold),
        float(cash0), float(spread), int(latency_bars), order_type,
        float(aggressiveness),
    )
    if fill_key is None:
        fill_key = jax.random.PRNGKey(0)  # unused dummy in market mode
    return fn(price, valid, score, adv, vol, fill_key)


@lru_cache(maxsize=32)
def _compiled_hysteresis(mesh, time_axis, asset_axis, size_shares,
                         threshold_hi, threshold_lo, cash0, spread):
    """Build + jit the time-sharded Schmitt-trigger program once per
    (mesh, axes, params)."""
    asum = (lambda x: lax.psum(x, asset_axis)) if asset_axis else (lambda x: x)

    def local_fn(price, valid, score, adv, vol):
        A_l, T_l = price.shape
        dtype = price.dtype
        t_loc = jnp.arange(T_l, dtype=jnp.int32)
        t_glob = lax.axis_index(time_axis) * T_l + t_loc  # global bar ids

        # the single-device engine's state resolution (backtest/event.py:
        # hysteresis_event_backtest) blockwise: last-event indices become
        # block-local cummaxes over GLOBAL bar ids plus one small
        # rightmost-earlier-block carry per event type
        e_long = valid & (score > threshold_hi)
        e_short = valid & (score < -threshold_hi)
        e_exit = valid & (jnp.abs(score) < threshold_lo)

        def last_idx(ev):
            loc = lax.associative_scan(
                jnp.maximum, jnp.where(ev, t_glob[None, :], -1), axis=1
            )
            blk_last = loc[:, -1]
            has = blk_last >= 0
            prev_has, prev_val = _carry_from_left(
                has, jnp.where(has, blk_last, 0), time_axis
            )
            prev = jnp.where(prev_has, prev_val, -1)
            return jnp.maximum(loc, prev[:, None]), prev

        iL, pL = last_idx(e_long)
        iS, pS = last_idx(e_short)
        iX, pX = last_idx(e_exit)

        def resolve(l, s, x):
            return jnp.where(
                (l > s) & (l > x), 1, jnp.where((s > l) & (s > x), -1, 0)
            ).astype(jnp.int32)

        target = resolve(iL, iS, iX)
        # state entering this block: resolved from the carries alone
        boundary = resolve(pL, pS, pX)
        prev_target = jnp.concatenate(
            [boundary[:, None], target[:, :-1]], axis=1
        )
        delta = target - prev_target
        sgn = jnp.sign(delta).astype(jnp.int32)
        traded = sgn != 0

        impact = square_root_impact(
            jnp.asarray(float(size_shares), dtype), adv.astype(dtype),
            vol.astype(dtype),
        )
        fill = market_fill_prices(jnp.nan_to_num(price), sgn, traded,
                                  impact, spread)
        shares = delta * size_shares
        notional = fill * shares.astype(dtype)
        # stored side = signed UNITS (delta; flips ±2), as single-device
        return _blocked_settle_tail(
            price, valid, shares, notional, delta, fill, traded, impact,
            cash0, asum, time_axis,
        )

    aspec = asset_axis
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            P(aspec, time_axis), P(aspec, time_axis), P(aspec, time_axis),
            P(aspec), P(aspec),
        ),
        out_specs=EventResult(
            pnl=P(time_axis),
            bar_mask=P(time_axis),
            portfolio_value=P(time_axis),
            cash=P(time_axis),
            positions=P(aspec, time_axis),
            trade_side=P(aspec, time_axis),
            exec_price=P(aspec, time_axis),
            impact=P(aspec),
            total_pnl=P(),
            n_trades=P(),
            n_buys=P(),
            n_sells=P(),
            net_notional=P(),
        ),
    )
    return jax.jit(fn)


def time_sharded_hysteresis_backtest(
    price,
    valid,
    score,
    adv,
    vol,
    mesh: Mesh,
    time_axis: str = "time",
    asset_axis: str | None = None,
    threshold_hi: float = 1e-4,
    threshold_lo: float = 1e-5,
    size_shares: int = 50,
    cash0: float = 1_000_000.0,
    spread: float = 0.001,
) -> EventResult:
    """Schmitt-trigger event engine with the minute axis sharded.

    The trigger's sequential state is three "last event index" prefixes,
    so time sharding follows the module's standard recipe: block-local
    cummaxes over global bar ids + one rightmost-earlier-block carry per
    event type (:func:`_carry_from_left`), with the block-boundary state
    resolved from the carries alone.  Equals
    :func:`csmom_tpu.backtest.event.hysteresis_event_backtest` on any
    (assets x time) layout — integer state (positions, sides) exactly,
    float state to tight tolerance (blocked summation reassociates fp,
    per the module header) — pinned in tests/test_sequence_parallel.py.
    """
    if float(threshold_lo) > float(threshold_hi):
        raise ValueError(
            f"threshold_lo={threshold_lo} > threshold_hi={threshold_hi}: "
            "the exit threshold must not exceed the entry threshold"
        )
    A, T = price.shape
    _validate_time_layout(mesh, A, T, time_axis, asset_axis)
    fn = _compiled_hysteresis(
        mesh, time_axis, asset_axis, int(size_shares), float(threshold_hi),
        float(threshold_lo), float(cash0), float(spread),
    )
    return fn(price, valid, score, adv, vol)
