"""Distributed cross-sectional rank without the all_gather: radix-histogram
selection of the decile boundaries.

The baseline distributed rank (``collectives._ranked_labels_local``)
all_gathers the full ``[A, M]`` signal to every shard and re-ranks it
redundantly — fine at the north star (A=3,000 is 12 KB/date) but O(A) in
communication and the one spot the design doesn't scale past ~10k assets
(VERDICT r1 weak #5).  This module finds the same labels with
communication independent of A:

1. a lane's rank-mode label is determined by the B-1 *global order
   statistics* at ranks ``ceil(k*n/B)`` (``ops.ranking._rank_labels``:
   label = how many boundary (value, position) pairs the lane dominates);
2. each boundary value is found by radix selection over sortable bit-keys:
   ``nbits/bpr`` rounds, each psum-ing a ``[R, M, E]`` bucket histogram of
   the still-candidate lanes — O(M * E * R) bytes per round, no A;
3. ties at the boundary value resolve by *global lane position* exactly
   like the single-device stable argsort: count values below, locate the
   j-th equal lane via an exclusive shard-prefix of per-shard equal
   counts, and psum the one shard's answer.

Labels are then a shard-local comparison against the B-1 (value, position)
pairs.  Output is bit-identical to ``decile_assign_panel(mode='rank')`` on
the gathered panel (property-tested for shard-count invariance in
tests/test_histrank.py).  qcut mode keeps the all_gather path: its
linear-interpolated edges (``ops.ranking._qcut_edges``) need two order
statistics per edge plus pandas' duplicate-edge semantics, and parity mode
runs at reference scale where the gather is free.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["histogram_rank_labels"]


# the float->uint key map lives with the single-device ranking kernels
# (ops.ranking.sortable_bits): one key map defines THE total order that
# both the argsort rank and this histogram rank bin by — keeping them
# label-identical by construction, including the invalid-above-+inf rule
from csmom_tpu.ops.ranking import sortable_bits as _sortable_bits


def histogram_rank_labels(x_l, valid_l, n_bins: int, axis_name: str | None,
                          bits_per_round: int = 4):
    """Shard-local rank-mode decile labels for an asset-sharded panel.

    Call inside ``shard_map`` with ``x_l/valid_l`` this shard's
    ``[A_local, M]`` rows (shard i holding global rows
    ``[i*A_local, (i+1)*A_local)``, as ``P('assets', None)`` lays out).

    With ``axis_name=None`` the collectives degenerate to identities and
    this IS the single-device histogram binning kernel: O(A·rounds)
    bucket scans + O(A·(B-1)) boundary compares instead of the O(A log A)
    sort — the sort-free form of ``decile_assign_panel(mode='rank')``
    (exposed there as ``mode='hist'``), worth it exactly when the batched
    per-date sort is the dominant phase (benchmarks/grid_phases.py).

    Returns ``labels i32[A_local, M]`` (-1 at invalid lanes), equal to the
    local slice of ``decile_assign_panel(gathered, mode='rank')``.
    """
    if axis_name is None:
        psum = lambda v, _=None: v
        axis_index = lambda _=None: jnp.int32(0)
        all_gather = lambda v, _=None: v[None]
    else:
        psum = lambda v, _=None: lax.psum(v, axis_name)
        axis_index = lambda _=None: lax.axis_index(axis_name)
        all_gather = lambda v, _=None: lax.all_gather(v, axis_name)

    A_l, M = x_l.shape
    key, nbits = _sortable_bits(x_l, valid_l)
    R = 1 << bits_per_round
    shard = axis_index()
    gpos = shard * A_l + jnp.arange(A_l, dtype=jnp.int32)          # [A_l]
    n = psum(jnp.sum(valid_l, axis=0, dtype=jnp.int32))            # [M]
    E = n_bins - 1
    ks = jnp.arange(1, n_bins, dtype=jnp.int32)
    r_k = (ks[:, None] * n[None, :] + n_bins - 1) // n_bins        # [E, M]

    # --- radix selection of the E boundary key values ------------------
    prefix = jnp.zeros((E, M), key.dtype)     # high bits fixed so far
    rank = r_k                                # 1-based rank among candidates
    for t in range(nbits // bits_per_round):
        shift = nbits - (t + 1) * bits_per_round
        bucket = (key >> shift) & (R - 1)                          # [A_l, M]
        if t == 0:
            cand = jnp.broadcast_to(valid_l[:, :, None], (A_l, M, E))
        else:
            high = key >> (shift + bits_per_round)
            cand = valid_l[:, :, None] & (
                high[:, :, None] == prefix.T[None, :, :]
            )
        hist = jnp.stack(
            [jnp.sum(cand & (bucket == b)[:, :, None], axis=0,
                     dtype=jnp.int32) for b in range(R)], axis=0
        )                                                          # [R, M, E]
        hist = psum(hist)
        cum = jnp.cumsum(hist, axis=0)
        rk = rank.T                                                # [M, E]
        bstar = jnp.sum(cum < rk[None, :, :], axis=0)              # [M, E]
        below = jnp.take_along_axis(
            cum, jnp.clip(bstar - 1, 0, R - 1)[None, :, :], axis=0
        )[0]
        rank = (rk - jnp.where(bstar > 0, below, 0)).T
        prefix = (prefix << bits_per_round) | bstar.T.astype(key.dtype)

    v = prefix.T                                                   # [M, E] boundary bit-keys

    # --- tie resolution: global position of each boundary lane, among
    #     *bit-identical* keys (the stable argsort's total order) ---------
    below_v = valid_l[:, :, None] & (key[:, :, None] < v[None, :, :])
    c_lt = psum(jnp.sum(below_v, axis=0, dtype=jnp.int32))
    eq = valid_l[:, :, None] & (key[:, :, None] == v[None, :, :])  # [A_l, M, E]
    loc_eq = jnp.sum(eq, axis=0, dtype=jnp.int32)                  # [M, E]
    g_eq = all_gather(loc_eq)                                      # [nsh, M, E]
    sh_ids = jnp.arange(g_eq.shape[0])
    prev_eq = jnp.sum(
        jnp.where((sh_ids < shard)[:, None, None], g_eq, 0), axis=0
    )
    need_j = r_k.T - c_lt                  # 1-based index among equal lanes
    local_j = need_j - prev_eq
    ceq = jnp.cumsum(eq, axis=0)
    match = eq & (ceq == local_j[None]) & (local_j > 0)[None] \
        & (local_j <= loc_eq)[None]
    bpos = psum(
        jnp.sum(jnp.where(match, gpos[:, None, None], 0), axis=0)
    )                                                              # [M, E]

    # --- labels: dominated boundary pairs, exactly _rank_labels' rule
    #     (bit compares == float compares after zero canonicalization) ---
    gt = key[:, :, None] > v[None, :, :]
    ge = gt | (eq & (gpos[:, None, None] >= bpos[None, :, :]))
    labels = jnp.sum(ge, axis=2).astype(jnp.int32)
    return jnp.where(valid_l, labels, -1)
