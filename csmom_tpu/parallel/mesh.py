"""Mesh construction helpers."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(devices=None, grid_axis: int = 1, axis_names=("grid", "assets")) -> Mesh:
    """Build a 2D (grid, assets) mesh from a flat device list.

    ``grid_axis`` devices are dedicated to parameter-grid parallelism; the
    rest shard the asset axis.  ``grid_axis=1`` degenerates to a pure
    asset-sharded mesh (the common case on one slice).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % grid_axis != 0:
        raise ValueError(f"{n} devices not divisible by grid_axis={grid_axis}")
    arr = np.asarray(devices).reshape(grid_axis, n // grid_axis)
    return Mesh(arr, axis_names)


def auto_mesh(n_devices: int | None = None, prefer_grid: bool = False) -> Mesh:
    """Mesh over the first ``n_devices`` devices; optionally split a grid axis
    of 2 when the device count is even and ``prefer_grid`` is set."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    grid = 2 if (prefer_grid and len(devices) % 2 == 0 and len(devices) > 1) else 1
    return make_mesh(devices, grid_axis=grid)


def pad_assets(values, mask, n_shards: int):
    """Pad the leading asset axis to a multiple of the shard count.

    Padded lanes are masked-out NaN rows, so every kernel treats them as
    never-observed assets; results are unchanged (host-side helper).
    """
    A = values.shape[0]
    pad = (-A) % n_shards
    if pad == 0:
        return values, mask, A
    vp = np.concatenate([values, np.full((pad,) + values.shape[1:], np.nan, values.dtype)])
    mp = np.concatenate([mask, np.zeros((pad,) + mask.shape[1:], bool)])
    return vp, mp, A
