"""Mesh construction helpers (single-slice and multi-host hybrid).

Multi-host layout principle (the scaling-book recipe, SURVEY §2 row 15):
axes that need collectives stay on fast links, axes that don't can cross
slow ones.  Here the asset axis is the only one with communication (one
``all_gather`` for the cross-sectional rank + ``psum``s for portfolio
reductions), so it must ride **ICI** — i.e. stay within one host/slice.
The grid and bootstrap axes are embarrassingly parallel (zero collectives),
so they span **DCN** across hosts for free.  :func:`make_hybrid_mesh`
encodes exactly that placement.
"""

from __future__ import annotations

import collections

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(devices=None, grid_axis: int = 1, axis_names=("grid", "assets")) -> Mesh:
    """Build a 2D (grid, assets) mesh from a flat device list.

    ``grid_axis`` devices are dedicated to parameter-grid parallelism; the
    rest shard the asset axis.  ``grid_axis=1`` degenerates to a pure
    asset-sharded mesh (the common case on one slice).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % grid_axis != 0:
        raise ValueError(f"{n} devices not divisible by grid_axis={grid_axis}")
    arr = np.asarray(devices).reshape(grid_axis, n // grid_axis)
    return Mesh(arr, axis_names)


def auto_mesh(n_devices: int | None = None, prefer_grid: bool = False) -> Mesh:
    """Mesh over the first ``n_devices`` devices; optionally split a grid axis
    of 2 when the device count is even and ``prefer_grid`` is set."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    grid = 2 if (prefer_grid and len(devices) % 2 == 0 and len(devices) > 1) else 1
    return make_mesh(devices, grid_axis=grid)


def distributed_init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join a multi-host run via ``jax.distributed.initialize``.

    The reference has no distributed anything (SURVEY §2 row 15); this is
    the rebuild's equivalent of an NCCL/MPI bootstrap: after it returns,
    ``jax.devices()`` spans every process and meshes built from it run XLA
    collectives over ICI within a slice and DCN between slices.

    MUST run before any JAX computation touches the backend (jax's own
    contract for ``distributed.initialize``).  Arguments are optional:
    jax auto-detects TPU pods, SLURM, and Open MPI.  Returns True when the
    distributed service came up, False for a plain single-process run
    (no cluster environment and no coordinator given) or when the service
    is already up (e.g. the launcher initialized it).  Genuine
    initialization failures — including calling this after the backend
    already initialized — propagate.
    """
    if jax.distributed.is_initialized():
        return False
    # Let jax's own cluster auto-detection run first (it recognizes
    # environments no env var announces, e.g. GCE TPU pods via the metadata
    # server).  Only when it fails with the missing-arguments ValueError do
    # we classify: no coordinator given and no multi-process markers in the
    # environment == a plain single-process run (return False); otherwise
    # the failure is a genuine bootstrap error and propagates.  Unlike the
    # round-1 code this matches no message wording, and unlike a pure env
    # pre-check it does not replace jax's detection logic with our own.
    # RuntimeErrors (e.g. initialize-after-backend-init misuse) always
    # propagate, per this docstring's contract.
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except ValueError:
        if coordinator_address is None and not _cluster_env_present():
            return False
        raise


def _cluster_env_present() -> bool:
    """Did the environment *intend* a multi-process run?  Used only to
    classify an ``initialize`` failure as fatal vs "no cluster here".
    Presence alone is not enough — single-host TPU images set
    ``TPU_WORKER_HOSTNAMES=localhost`` and MPI launchers export world size
    1 — so cardinality is checked where the variable carries one."""
    import os

    for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS"):
        if os.environ.get(v):
            return True
    hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
             if h.strip()]
    if len(hosts) > 1:
        return True
    for v in ("OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "SLURM_NTASKS"):
        try:
            if int(os.environ.get(v, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


def _group_by_host(devices, n_hosts: int | None):
    """Split a flat device list into per-host rows.

    Real multi-process runs group by ``device.process_index`` (each row is
    one host's ICI domain).  When every device reports the same process
    (single host, or a CPU-simulated mesh), an explicit ``n_hosts`` splits
    the list evenly to emulate the topology for tests.
    """
    by_proc = collections.defaultdict(list)
    for d in devices:
        by_proc[getattr(d, "process_index", 0)].append(d)
    if len(by_proc) > 1:
        rows = [by_proc[p] for p in sorted(by_proc)]
        sizes = {len(r) for r in rows}
        if len(sizes) != 1:
            raise ValueError(f"uneven devices per host: {sorted(sizes)}")
        if n_hosts is not None and n_hosts != len(rows):
            raise ValueError(f"n_hosts={n_hosts} but {len(rows)} processes present")
        return rows
    n = len(devices)
    n_hosts = n_hosts or 1
    if n % n_hosts != 0:
        raise ValueError(f"{n} devices not divisible by n_hosts={n_hosts}")
    per = n // n_hosts
    return [list(devices[i * per : (i + 1) * per]) for i in range(n_hosts)]


def make_hybrid_mesh(
    devices=None,
    n_hosts: int | None = None,
    axis_names=("grid", "assets"),
) -> Mesh:
    """2D hybrid mesh: first axis spans hosts (DCN), second stays ICI-local.

    ``axis_names[0]`` names the collective-free axis (parameter grid,
    bootstrap resamples, walk-forward folds — anything embarrassingly
    parallel) and gets one mesh slot per host, so its traffic is zero and
    DCN latency is irrelevant.  ``axis_names[1]`` is the asset axis whose
    all_gather/psum collectives then never leave a host's ICI domain.

    On a single host this degenerates to ``make_mesh(grid_axis=1)`` unless
    ``n_hosts`` explicitly simulates a topology (the CPU-mesh test path).
    """
    if devices is None:
        devices = jax.devices()
    rows = _group_by_host(devices, n_hosts)
    return Mesh(np.asarray(rows), axis_names)


def mesh_topology(mesh: Mesh) -> dict:
    """Describe which mesh axes cross process (DCN) boundaries — the thing
    to assert in tests and log at startup."""
    arr = mesh.devices
    out = {}
    for ax, name in enumerate(mesh.axis_names):
        moved = np.moveaxis(arr, ax, 0)
        crosses = any(
            len({getattr(d, "process_index", 0) for d in col}) > 1
            for col in np.reshape(moved, (moved.shape[0], -1)).T
        )
        out[name] = {"size": arr.shape[ax], "crosses_hosts": bool(crosses)}
    return out


def pad_assets(values, mask, n_shards: int):
    """Pad the leading asset axis to a multiple of the shard count.

    Padded lanes are masked-out NaN rows, so every kernel treats them as
    never-observed assets; results are unchanged (host-side helper).
    """
    A = values.shape[0]
    pad = (-A) % n_shards
    if pad == 0:
        return values, mask, A
    vp = np.concatenate([values, np.full((pad,) + values.shape[1:], np.nan, values.dtype)])
    mp = np.concatenate([mask, np.zeros((pad,) + mask.shape[1:], bool)])
    return vp, mp, A
