"""Sequence-parallel online ridge: the walk-forward scan, time-sharded.

The single-device online ridge (:mod:`csmom_tpu.models.online_ridge`) is
an R-step sequential scan — the classic long-context problem.  It
parallelizes because everything the recursion carries is a sum of
per-row contributions:

- the regularized Gram ``G_t = sum w x x^T`` and label vector
  ``b_t = sum w x y`` are plain additions, and
- the raw-feature scaler moments ``(count, mean, M2)`` merge with
  Chan's parallel-Welford formula,

so each time shard can be seeded with an EXCLUSIVE prefix of tiny block
summaries and then run the same per-row scan locally.  Three phases, all
shard-local scans plus two ``all_gather``s of O(F^2) summaries over the
``'time'`` mesh axis:

1. **moment summaries** — each block computes its raw-feature
   ``(count, mean, M2)`` in one batch pass; an exclusive Chan-merge fold
   over the gathered summaries gives every block the scaler state it
   inherits.
2. **scaled Gram** — each block scans its rows (seeded with phase 1's
   carry, so the causal scaling is identical to the sequential run)
   accumulating its ``(dG, db)``; an exclusive prefix-sum gives every
   block the Gram/label state it inherits.
3. **local Sherman–Morrison** — each block seeds
   ``P = inv(alpha I + G_carry)`` (ONE (F+1)x(F+1) inverse per shard —
   this is what the rank-1 recursion avoids per row and what makes the
   seed cheap per block) and runs the SAME row step as the single-device
   scan (:func:`csmom_tpu.models.online_ridge._make_row_step`), emitting
   strictly-causal predictions.

The result is mathematically identical to the sequential scan (same
Gram, same moments, same per-row updates — only float association
differs at the seeds), pinned by an equality test on the virtual CPU
mesh.  Wall-clock depth drops from O(R) to O(R / n_shards) + O(F^3).

The reference has no analogue of any of this (single thread, no model
beyond one sklearn fit — SURVEY §2 rows 9/14/15); this is the
long-context treatment of the MODEL layer, sibling to the event
engine's time sharding (:mod:`csmom_tpu.parallel.event_time`).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from csmom_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from csmom_tpu.models.online_ridge import (
    OnlineRidgeFit,
    _causal_scale,
    _make_row_step,
    _prequential_fit,
    _row_moment_update,
)
from csmom_tpu.parallel.event_time import _exclusive_prefix_sum

__all__ = ["time_sharded_online_ridge_scores"]


def _block_moment_summary(Xb, wb):
    """Batch ``(count, mean, M2)`` of this block's valid raw features.

    ``Xb f[R_l, A, F]``, ``wb f[R_l, A]``; one pass, no scan — block
    summaries are order-free (the variance of a set is not a chain).
    """
    cnt = jnp.sum(wb)
    mean = jnp.einsum("ra,raf->f", wb, Xb) / jnp.maximum(cnt, 1.0)
    M2 = jnp.einsum("ra,raf->f", wb, (Xb - mean) ** 2)
    return cnt, mean, M2


def _exclusive_moment_carry(cnt_b, mean_b, M2_b, axis_name: str):
    """Chan-merge of all EARLIER blocks' moment summaries, in block order."""
    g_cnt = lax.all_gather(cnt_b, axis_name)    # [nb]
    g_mean = lax.all_gather(mean_b, axis_name)  # [nb, F]
    g_M2 = lax.all_gather(M2_b, axis_name)      # [nb, F]
    i = lax.axis_index(axis_name)
    nb = g_cnt.shape[0]

    def fold(j, st):
        cnt, mean, M2 = st
        n2, m2, M22 = g_cnt[j], g_mean[j], g_M2[j]
        n = cnt + n2
        delta = m2 - mean
        merged = (
            n,
            mean + delta * n2 / jnp.maximum(n, 1.0),
            M2 + M22 + delta * delta * cnt * n2 / jnp.maximum(n, 1.0),
        )
        take = j < i
        return jax.tree_util.tree_map(
            lambda new, old: jnp.where(take, new, old), merged, st
        )

    zero = (
        jnp.zeros((), g_mean.dtype),
        jnp.zeros(g_mean.shape[1], g_mean.dtype),
        jnp.zeros(g_mean.shape[1], g_mean.dtype),
    )
    return lax.fori_loop(0, nb, fold, zero)


@lru_cache(maxsize=32)
def _compiled(mesh: Mesh, time_axis: str, A: int, F: int, dt,
              alpha: float, burn_in: int, standardize: bool,
              gather_outputs: bool = False):
    # gather_outputs=True all_gathers every output over the time axis and
    # returns them replicated (out_specs P()): the form a MULTI-PROCESS
    # controller can read whole (process-local addressability), used by
    # benchmarks/multihost_dryrun.py.  The default keeps outputs sharded —
    # no gather traffic — for the single-controller wrapper below.
    spec_x = P(time_axis, None, None)  # [R, A, F] sharded on rows
    spec_v = P(time_axis, None)        # [R, A]

    def block(Xb, yb, wb):
        # phase 1: scaler state this block inherits
        cnt_b, mean_b, M2_b = _block_moment_summary(Xb, wb)
        cnt0, mean0, M20 = _exclusive_moment_carry(
            cnt_b, mean_b, M2_b, time_axis
        )

        # phase 2: scaled Gram/label contribution of this block (seeded
        # with the carry so the causal scaling equals the sequential run)
        def gstep(carry, inp):
            cnt, mean, M2, G, bsum = carry
            X, yt, w = inp
            Xs = _causal_scale(X, cnt, mean, M2, standardize)
            Xa = jnp.concatenate([Xs, jnp.ones((A, 1), dt)], axis=1)
            xw = Xa * w[:, None]
            G = G + xw.T @ xw        # sum_a w * outer(x_a, x_a): order-free
            bsum = bsum + xw.T @ yt
            cnt, mean, M2 = _row_moment_update(cnt, mean, M2, X, w)
            return (cnt, mean, M2, G, bsum), None

        (_, _, _, dG, db), _ = lax.scan(
            gstep,
            (cnt0, mean0, M20,
             jnp.zeros((F + 1, F + 1), dt), jnp.zeros(F + 1, dt)),
            (Xb, yb, wb),
        )
        G0 = _exclusive_prefix_sum(dG, time_axis)
        b0 = _exclusive_prefix_sum(db, time_axis)

        # phase 3: the single-device row step, seeded.  inv() here is the
        # one O(F^3) cost per shard that replaces R/n_shards rank-1 steps
        # of sequential depth.
        P0 = jnp.linalg.inv(
            alpha * jnp.eye(F + 1, dtype=dt) + G0
        )
        step = _make_row_step(A, dt, burn_in, standardize)
        (_, _, _, _, _), (preds, seen) = lax.scan(
            step, (P0, b0, cnt0, mean0, M20), (Xb, yb, wb)
        )

        # full-history totals for the final fit, identical on every shard
        G_tot = lax.psum(dG, time_axis)
        b_tot = lax.psum(db, time_axis)
        # inclusive moment merge = this block's own summary folded into
        # its phase-1 exclusive carry (no second gather needed)
        cnt_f, mean_f, M2_f = cnt0, mean0, M20
        n2, m2, M22 = cnt_b, mean_b, M2_b
        n = cnt_f + n2
        delta = m2 - mean_f
        cnt_f, mean_f, M2_f = (
            n,
            mean_f + delta * n2 / jnp.maximum(n, 1.0),
            M2_f + M22 + delta * delta * cnt_f * n2 / jnp.maximum(n, 1.0),
        )
        if gather_outputs:
            preds_g = lax.all_gather(preds, time_axis).reshape(-1, A)
            seen_g = lax.all_gather(seen, time_axis).reshape(-1, A)
            # full-history moments: the LAST block's inclusive merge,
            # broadcast to every shard so the output is replicated (the
            # multihost benchmark reads only preds/seen; a multi-process
            # fit assembly would consume these)
            nb = lax.psum(jnp.ones((), jnp.int32), time_axis)
            is_last = lax.axis_index(time_axis) == nb - 1
            cnt_g = lax.psum(jnp.where(is_last, cnt_f, 0.0), time_axis)
            mean_g = lax.psum(jnp.where(is_last, mean_f, 0.0), time_axis)
            M2_g = lax.psum(jnp.where(is_last, M2_f, 0.0), time_axis)
            return (preds_g, seen_g, G_tot, b_tot, (cnt_g, mean_g, M2_g))
        # leading length-1 axis: shard_map stacks these per block along
        # the time spec, and the caller takes the LAST block's (full
        # history) values
        return (preds, seen, G_tot, b_tot,
                (cnt_f[None], mean_f[None], M2_f[None]))

    if gather_outputs:
        out_specs = (P(), P(), P(), P(), (P(), P(), P()))
    else:
        out_specs = (spec_v, spec_v, P(), P(),
                     (P(time_axis), P(time_axis, None), P(time_axis, None)))
    return jax.jit(shard_map(
        block,
        mesh=mesh,
        in_specs=(spec_x, spec_v, spec_v),
        out_specs=out_specs,
        check_vma=False,
    ))


def time_sharded_online_ridge_scores(
    features,
    y,
    valid,
    mesh: Mesh,
    time_axis: str = "time",
    alpha: float = 1.0,
    n_splits: int = 3,
    burn_in: int = 30,
    standardize: bool = True,
) -> OnlineRidgeFit:
    """Time-sharded walk-forward ridge, equal to the single-device scan.

    Args mirror :func:`csmom_tpu.models.online_ridge.online_ridge_scores`
    plus the mesh whose ``time_axis`` shards the row axis.  Rows are
    padded to a multiple of the shard count with invalid no-op rows.
    """
    A, R, F = features.shape
    dt = features.dtype
    n_shards = mesh.shape[time_axis]

    Xr = np.nan_to_num(np.swapaxes(np.asarray(features), 0, 1))  # [R, A, F]
    yr = np.nan_to_num(np.swapaxes(np.asarray(y), 0, 1))
    wr = np.swapaxes(np.asarray(valid), 0, 1).astype(dt)

    pad = (-R) % n_shards
    if pad:
        Xr = np.concatenate([Xr, np.zeros((pad, A, F), Xr.dtype)], axis=0)
        yr = np.concatenate([yr, np.zeros((pad, A), yr.dtype)], axis=0)
        wr = np.concatenate([wr, np.zeros((pad, A), wr.dtype)], axis=0)

    fn = _compiled(mesh, time_axis, A, F, dt, alpha, burn_in, standardize)
    with mesh:
        preds, seen, G_tot, b_tot, (cnt_f, mean_f, M2_f) = fn(
            jnp.asarray(Xr), jnp.asarray(yr), jnp.asarray(wr)
        )

    # the LAST block's inclusive moment merge covers the full history
    cnt_f, mean_f, M2_f = cnt_f[-1], mean_f[-1], M2_f[-1]
    w_final = jnp.linalg.solve(
        alpha * jnp.eye(F + 1, dtype=dt) + G_tot, b_tot
    )
    return _prequential_fit(
        preds[:R], seen[:R], jnp.asarray(wr[:R]), jnp.asarray(yr[:R]),
        n_splits, w_final, cnt_f, mean_f, M2_f,
    )
