"""csmom_tpu.registry — register an engine once, get every surface.

Public query API (each call loads the builtin registrations on first
use):

- :func:`serve_endpoints` — the serving tier's endpoint names (what
  ``serve/buckets.py::ENDPOINTS`` used to hard-code);
- :func:`serve_surface` — one endpoint's :class:`ServeSurface` (batch /
  stub factories, output shape, synthetic panel family);
- :func:`workload_kinds` — the loadgen endpoint mix (surface (d));
- :func:`manifest_entries` / :func:`manifest_profiles` — the warmup
  shape manifest (surface (a); ``compile/manifest.py`` builds from
  these);
- :func:`entry_factory` — the raw ``lru_cache``-shared jitted-entry
  factory (what ``bench.py`` fetches);
- :func:`get_engine` / :func:`engine_specs` — spec access (donated
  variants, the sharded hook, descriptions for ``csmom registry
  list``);
- :func:`strategies` — the Strategy plugin zoo (forces the builtin
  strategy module import, which is where strategy registration
  happens);
- :func:`register_engine` / :func:`unregister_engine` — runtime
  registration (plugins, tests).

See :mod:`csmom_tpu.registry.core` for the model and
:mod:`csmom_tpu.registry.builtin` for what ships registered.
"""

from __future__ import annotations

from csmom_tpu.registry.core import (
    REGISTRY,
    EngineRegistry,
    EngineSpec,
    ServeSurface,
    ensure_builtin,
    register_engine,
)

__all__ = [
    "EngineRegistry",
    "EngineSpec",
    "REGISTRY",
    "ServeSurface",
    "engine_specs",
    "entry_factory",
    "get_engine",
    "lint_rules",
    "manifest_entries",
    "manifest_entry_names",
    "manifest_profiles",
    "register_engine",
    "serve_endpoints",
    "serve_surface",
    "strategies",
    "unregister_engine",
    "workload_kinds",
]


def serve_endpoints() -> tuple:
    return ensure_builtin().serve_endpoints()


def serve_surface(name: str) -> ServeSurface:
    return ensure_builtin().serve_surface(name)


def workload_kinds() -> tuple:
    return ensure_builtin().workload_kinds()


def manifest_profiles() -> tuple:
    return ensure_builtin().manifest_profiles()


def manifest_entries(profile: str, dtype=None) -> list:
    return ensure_builtin().manifest_entries(profile, dtype)


def manifest_entry_names(profile: str) -> set:
    """The jax-free warm-coverage declaration: entry names the
    profile's feeders will compile (see ``EngineSpec.manifest_names_fn``
    — what the compile-surface lint rule audits)."""
    return ensure_builtin().manifest_entry_names(profile)


def get_engine(name: str, kind: str | None = None) -> EngineSpec:
    return ensure_builtin().get(name, kind)


def engine_specs(kind: str | None = None) -> tuple:
    return ensure_builtin().specs(kind)


def entry_factory(name: str):
    """The engine's raw jitted-entry factory (``lru_cache``-shared, so
    every caller in one process gets one callable and every caller
    across processes lowers identical HLO)."""
    spec = ensure_builtin().get(name, kind="compile")
    if spec.entry_fn is None:
        raise KeyError(f"engine {name!r} declares no entry factory")
    return spec.entry_fn


def strategies() -> dict:
    """name -> Strategy class; importing the builtin strategy zoo is
    what registers it (strategy modules import jax, so this is the one
    query that is not jax-free)."""
    import csmom_tpu.strategy.builtin  # noqa: F401  (registers the zoo)

    return ensure_builtin().strategies()


def lint_rules() -> tuple:
    """Kind-``lint`` specs in registration order; importing the builtin
    rule modules is what registers the shipped set (stdlib-only — the
    sweep stays jax-free): the per-file rules AND the project-scope
    whole-program rules (ISSUE 12).  A rule registered at runtime (a
    plugin, a test) appears here immediately, which is what enrolls it
    in ``csmom lint``, the tier-1 sweep, and the fixture self-test."""
    import csmom_tpu.analysis.rules  # noqa: F401  (registers the rules)
    import csmom_tpu.analysis.project_rules  # noqa: F401  (project set)

    return ensure_builtin().specs("lint")


def unregister_engine(name: str, kind: str | None = None) -> None:
    ensure_builtin().unregister(name, kind)
