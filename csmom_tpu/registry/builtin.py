"""Builtin engine registrations — the one table the old four lists fed.

Everything that used to be enumerated per-module lands here, attached
to :data:`csmom_tpu.registry.core.REGISTRY`:

- the **serve endpoints** (``serve/buckets.py`` used to hard-code
  ``("momentum", "turnover", "backtest")``; registration now also ships
  the previously research-only ``low_volatility`` and ``zscore_combo``
  strategies as live endpoints — the tentpole's point: a new endpoint
  is one registration, not four edits);
- the **compile entries** (the grid/event/monthly/histrank/online-ridge
  shape tables that used to be ``compile/manifest.py``'s per-profile
  ``if/elif`` dispatch), each engine declaring its own canonical shapes
  per warmup profile;
- the **serve/stream manifest feeders**, which generate their entries by
  iterating the registry AT CALL TIME — so an engine registered later
  (a plugin, a test's toy engine) appears in ``csmom warmup --profiles
  serve`` with no edit here.

jax stays inside factories; numpy inside the stub builders.  Importing
this module costs registrations only, which is what lets the jax-free
consumers (invariants, health fingerprints, the fast rehearse tier)
query endpoint names cheaply.
"""

from __future__ import annotations

from csmom_tpu.registry.core import REGISTRY, EngineSpec, ServeSurface

# ---------------------------------------------------------------------------
# serve endpoint factories: batch_fn(params) -> one(values[A,M], mask[A,M])
# (jax; the engine vmaps+jits), stub_fn(params) -> fn(values[B,A,M], mask)
# (numpy; the plumbing/rehearse engine).
# ---------------------------------------------------------------------------

# days constant the turnover stub shares with signals.turnover's ADV proxy
_TRADING_DAYS_PER_MONTH = 21.0


def _np():
    import numpy as np

    return np


def _nanmean(a, axis: int):
    """All-NaN-slice-safe nanmean (np.nanmean warns on empty slices; a
    padded stub batch is full of them by design)."""
    np = _np()
    ok = np.isfinite(a)
    c = ok.sum(axis=axis)
    s = np.where(ok, a, 0.0).sum(axis=axis)
    return np.where(c > 0, s / np.maximum(c, 1), np.nan)


def _xs_z_np(score, valid):
    """Cross-sectional z-score over the asset axis of f[B, A] (the stub
    mirror of ``strategy.base.xs_zscore`` at the last formation date)."""
    np = _np()
    v = valid & np.isfinite(score)
    n = np.maximum(v.sum(axis=1, keepdims=True), 1)
    x = np.where(v, np.nan_to_num(score), 0.0)
    mu = x.sum(axis=1, keepdims=True) / n
    sd = np.sqrt(np.where(v, (x - mu) ** 2, 0.0).sum(axis=1,
                                                     keepdims=True) / n)
    z = np.where(sd > 0, (x - mu) / np.where(sd == 0, 1.0, sd), 0.0)
    return np.where(v, z, 0.0)


def _momentum_batch(params):
    import jax.numpy as jnp

    from csmom_tpu.signals.momentum import momentum

    lookback, skip = params["lookback"], params["skip"]

    def one(values, mask):
        mom, ok = momentum(values, mask, lookback=lookback, skip=skip)
        return jnp.where(ok[:, -1], mom[:, -1], jnp.nan)

    return one


def _momentum_stub(params):
    lookback, skip = params["lookback"], params["skip"]
    np = _np()

    def fn(values, mask):
        v = np.where(mask, values, np.nan)
        end = v[:, :, -1 - skip]
        start = v[:, :, -1 - skip - lookback]
        with np.errstate(divide="ignore", invalid="ignore"):
            return end / start - 1.0

    return fn


def _turnover_batch(params):
    import jax.numpy as jnp

    from csmom_tpu.signals.turnover import turnover_features

    lookback = params["lookback"]

    def one(values, mask):
        shares = jnp.ones((values.shape[0],), values.dtype)
        turn, ok = turnover_features(
            values, mask, shares, lookback=lookback)["turn_avg"]
        return jnp.where(ok[:, -1], turn[:, -1], jnp.nan)

    return one


def _turnover_stub(params):
    lookback = params["lookback"]
    np = _np()

    def fn(values, mask):
        v = np.where(mask, values, np.nan)
        return (_nanmean(v[:, :, -lookback:], -1)
                / _TRADING_DAYS_PER_MONTH)

    return fn


def _backtest_batch(params):
    import jax.numpy as jnp

    from csmom_tpu.backtest.monthly import monthly_spread_backtest

    lookback, skip = params["lookback"], params["skip"]
    n_bins, mode = params["n_bins"], params["mode"]

    def one(values, mask):
        res = monthly_spread_backtest(
            values, mask, lookback=lookback, skip=skip, n_bins=n_bins,
            mode=mode)
        return jnp.stack([res.mean_spread, res.ann_sharpe])

    return one


def _backtest_stub(params):
    np = _np()

    def fn(values, mask):
        v = np.where(mask, values, np.nan)
        with np.errstate(divide="ignore", invalid="ignore"):
            ret = v[:, :, 1:] / v[:, :, :-1] - 1.0
        mean = _nanmean(_nanmean(ret, 1), -1)
        return np.stack([np.nan_to_num(mean), np.zeros_like(mean)], axis=-1)

    return fn


def _strategy_last_column(make_strategy_instance):
    """The generic strategy -> serve-endpoint adapter: score the panel
    through ``Strategy.signal`` and serve the LAST formation column —
    exactly what a live scoring request wants from a research signal.
    The strategy instance is built once per (endpoint, params) and rides
    as a jit-static closure, so each parametrization compiles once."""

    def batch(params):
        import jax.numpy as jnp

        strat = make_strategy_instance(params)

        def one(values, mask):
            score, valid = strat.signal(values, mask)
            return jnp.where(valid[:, -1], score[:, -1], jnp.nan)

        return one

    return batch


def _low_volatility_stub(params):
    np = _np()
    window = 36  # the registered endpoint's canonical LowVolatility()

    def fn(values, mask):
        v = np.where(mask, values, np.nan)
        with np.errstate(divide="ignore", invalid="ignore"):
            ret = v[:, :, 1:] / v[:, :, :-1] - 1.0
        w = ret[:, :, -window:]
        ok = np.isfinite(w)
        n = ok.sum(-1)
        x = np.where(ok, w, 0.0)
        mean = x.sum(-1) / np.maximum(n, 1)
        var = (np.where(ok, (x - mean[..., None]) ** 2, 0.0).sum(-1)
               / np.maximum(n - 1, 1))
        return np.where(n >= 2, -np.sqrt(var), np.nan)

    return fn


def _zscore_combo_stub(params):
    np = _np()
    mom_stub = _momentum_stub(params)

    def fn(values, mask):
        v = np.where(mask, values, np.nan)
        mom = mom_stub(values, mask)
        with np.errstate(divide="ignore", invalid="ignore"):
            rev = -(v[:, :, -1] / v[:, :, -2] - 1.0)
        valid = np.isfinite(mom) & np.isfinite(rev)
        z = 0.5 * _xs_z_np(mom, valid) + 0.5 * _xs_z_np(rev, valid)
        return np.where(valid, z, np.nan)

    return fn


def _mk_low_volatility(params):
    from csmom_tpu.strategy.builtin import LowVolatility

    return LowVolatility()


def _mk_zscore_combo(params):
    from csmom_tpu.strategy.builtin import ZScoreCombo

    # the canonical live combo: equal-weight momentum + short-term
    # reversal, both z-scored per date (prices-only components, so the
    # serve panel pair is all it needs)
    return ZScoreCombo("momentum:0.5,reversal:0.5")


REGISTRY.register(EngineSpec(
    name="momentum", kind="serve",
    description="compounded (J, skip) price momentum at the last "
                "formation date (the reference's signal)",
    axes="values f[B,A,M] month-end prices, mask bool[B,A,M] -> f[B,A]",
    serve=ServeSurface(batch_fn=_momentum_batch, stub_fn=_momentum_stub,
                       panel_family="price"),
))

REGISTRY.register(EngineSpec(
    name="turnover", kind="serve",
    description="trailing-lookback average turnover proxy (monthly "
                "share volume / ADV denominator)",
    axes="values f[B,A,M] monthly volumes, mask bool[B,A,M] -> f[B,A]",
    serve=ServeSurface(batch_fn=_turnover_batch, stub_fn=_turnover_stub,
                       panel_family="volume"),
))

REGISTRY.register(EngineSpec(
    name="backtest", kind="serve",
    description="full monthly decile spread backtest per request panel "
                "-> (mean_spread, ann_sharpe)",
    axes="values f[B,A,M], mask bool[B,A,M] -> f[B,2]",
    serve=ServeSurface(batch_fn=_backtest_batch, stub_fn=_backtest_stub,
                       output="summary",
                       summary_fields=("mean_spread", "ann_sharpe"),
                       panel_family="price"),
))

REGISTRY.register(EngineSpec(
    name="low_volatility", kind="serve",
    description="Blitz-van Vliet volatility effect: negated trailing "
                "36m return volatility (research-only until ISSUE 9; "
                "now a live endpoint via the strategy adapter)",
    axes="values f[B,A,M] month-end prices, mask bool[B,A,M] -> f[B,A]",
    serve=ServeSurface(
        batch_fn=_strategy_last_column(_mk_low_volatility),
        stub_fn=_low_volatility_stub, panel_family="price"),
))

REGISTRY.register(EngineSpec(
    name="zscore_combo", kind="serve",
    description="equal-weight z-scored momentum + short-term reversal "
                "combo (research-only until ISSUE 9; now a live "
                "endpoint via the strategy adapter)",
    axes="values f[B,A,M] month-end prices, mask bool[B,A,M] -> f[B,A]",
    serve=ServeSurface(
        batch_fn=_strategy_last_column(_mk_zscore_combo),
        stub_fn=_zscore_combo_stub, panel_family="price"),
))


# ---------------------------------------------------------------------------
# compile entries: the per-profile shape tables that used to live as
# compile/manifest.py's if/elif dispatch.  Each engine declares its own
# shapes; REGISTRY.manifest_entries(profile) aggregates them.
# ---------------------------------------------------------------------------

def _dt(profile: str, dtype):
    """The profile's default float dtype (bench policy: f64 on CPU
    profiles, f32 on accelerator-shaped ones), overridable."""
    import numpy as np

    if dtype is not None:
        return np.dtype(dtype)
    return np.dtype(np.float32 if profile == "bench-tpu" else np.float64)


def _manifest_mod():
    from csmom_tpu.compile import manifest as m

    return m


def _grid_manifest(profile: str, dtype) -> list:
    from csmom_tpu.compile import workloads as wl

    m = _manifest_mod()
    dt = _dt(profile, dtype)
    A_r, T_r = wl.REDUCED_GRID
    A_f, T_f = wl.NORTH_STAR_GRID
    if profile == "bench-cpu":
        M_r, M_f = m.months_of(T_r), m.months_of(T_f)
        entries = m.grid_entries(
            A_r, M_r, dt, tag=f"{A_r}x{M_r}", donated=True,
            modes_impls=[("rank", "xla"), ("qcut", "xla"),
                         ("rank", "matmul")],
        )
        entries += m.grid_entries(
            A_f, M_f, dt, tag=f"{A_f}x{M_f}",
            modes_impls=[("rank", "xla"), ("rank", "matmul")],
        )
        return entries
    if profile == "bench-tpu":
        M_f = m.months_of(T_f)
        return m.grid_entries(
            A_f, M_f, dt, tag=f"{A_f}x{M_f}", donated=True,
            modes_impls=[("rank", "xla"), ("qcut", "xla"),
                         ("rank", "matmul"), ("rank", "matmul_bf16"),
                         ("rank", "pallas")],
        )
    # smoke: tiny shapes, every grid code path
    return m.grid_entries(16, 48, dt, tag="16x48", donated=True,
                          modes_impls=[("rank", "xla")])


def _grid_net_manifest(profile: str, dtype) -> list:
    from csmom_tpu.compile import workloads as wl

    m = _manifest_mod()
    dt = _dt(profile, dtype)
    if profile == "bench-cpu":
        A, T = wl.REDUCED_GRID
    elif profile == "bench-tpu":
        A, T = wl.NORTH_STAR_GRID
    else:  # smoke
        return [m.grid_net_entry(16, 48, dt, tag="16x48")]
    M = m.months_of(T)
    return [m.grid_net_entry(A, M, dt, tag=f"{A}x{M}")]


def _monthly_manifest(profile: str, dtype) -> list:
    m = _manifest_mod()
    dt = _dt(profile, dtype)
    if profile == "golden":
        A, M = 20, 60  # the 20-ticker demo universe, ~5y of months
    else:  # smoke
        A, M = 8, 24
    return m.monthly_entries(A, M, dt, tag=f"{A}x{M}")


def _event_manifest(profile: str, dtype) -> list:
    # the golden-shape event entries are data-dependent and resolve via
    # manifest.golden_event_entries; the smoke profile pins the tiny
    # fixed-shape coverage of every event code path
    return _manifest_mod().event_entries(4, 32, _dt(profile, dtype),
                                         tag="4x32")


def _histrank_manifest(profile: str, dtype) -> list:
    import numpy as np

    m = _manifest_mod()
    if profile == "golden":
        return [m.histrank_entry(4096, 120, np.float32, tag="4096x120")]
    return [m.histrank_entry(32, 6, np.float32, tag="32x6")]


def _online_ridge_manifest(profile: str, dtype) -> list:
    m = _manifest_mod()
    dt = _dt(profile, dtype)
    if profile == "golden":
        return [m.online_ridge_entry(64, 8, 4, dt, tag="64x8x4")]
    return [m.online_ridge_entry(12, 3, 2, dt, tag="12x3x2")]


def _grid_entry_factory(*args, **kwargs):
    from csmom_tpu.compile.entries import grid_scalar_fn

    return grid_scalar_fn(*args, **kwargs)


def _batched_event_factory(*args, **kwargs):
    from csmom_tpu.compile.entries import batched_event_fn

    return batched_event_fn(*args, **kwargs)


def _histrank_factory(*args, **kwargs):
    from csmom_tpu.compile.entries import histrank_labels_fn

    return histrank_labels_fn(*args, **kwargs)


def _grid_donated_factory(**params):
    from csmom_tpu.backtest.grid import _jk_grid_backtest_donated

    return _jk_grid_backtest_donated


def _event_donated_factory(**params):
    from csmom_tpu.backtest.event import event_backtest_donated

    return event_backtest_donated


REGISTRY.register(EngineSpec(
    name="grid.jk", kind="compile",
    description="the J x K grid backtest hot entry (in-jit scalar "
                "reduction; bench's grid legs + donated variant)",
    axes="prices f[A,M], mask bool[A,M] -> scalar",
    profiles=("bench-cpu", "bench-tpu", "smoke"),
    manifest_fn=_grid_manifest,
    entry_fn=_grid_entry_factory,
    donated_fn=_grid_donated_factory,
))

REGISTRY.register(EngineSpec(
    name="grid.net_core", kind="compile",
    description="the --tc-bps netting pass over a precomputed grid",
    axes="prices f[A,M] + per-cell label planes -> net grid",
    profiles=("bench-cpu", "bench-tpu", "smoke"),
    manifest_fn=_grid_net_manifest,
))

REGISTRY.register(EngineSpec(
    name="monthly.kernels", kind="compile",
    description="the three jitted monthly kernels (spread, "
                "sector-neutral, net-of-costs) at the golden panel",
    axes="prices f[A,M], mask bool[A,M]",
    profiles=("golden", "smoke"),
    manifest_fn=_monthly_manifest,
))

REGISTRY.register(EngineSpec(
    name="event.panel", kind="compile",
    description="the event panel engines (threshold plain + donated, "
                "hysteresis) and the batched vmapped event leg",
    axes="price/valid/score f[A,T] minute panels",
    profiles=("smoke",),
    manifest_fn=_event_manifest,
    entry_fn=_batched_event_factory,
    donated_fn=_event_donated_factory,
))

REGISTRY.register(EngineSpec(
    name="parallel.histrank", kind="compile",
    description="sort-free histogram-rank decile labels (collectives "
                "degenerate to identities on one device)",
    axes="x f[A,M], valid bool[A,M] -> labels i32[A,M]",
    profiles=("golden", "smoke"),
    manifest_fn=_histrank_manifest,
    entry_fn=_histrank_factory,
))

REGISTRY.register(EngineSpec(
    name="parallel.online_ridge", kind="compile",
    description="time-sharded online-ridge scan on a 1-device mesh",
    axes="X f[R,A,F], y f[R,A], w f[R,A]",
    profiles=("golden", "smoke"),
    manifest_fn=_online_ridge_manifest,
))


# ---------------------------------------------------------------------------
# serve + stream manifest feeders: entries generated by iterating the
# registry AT CALL TIME, so a later-registered endpoint (plugin, toy
# test engine) warms and memory-profiles with no edit here.
# ---------------------------------------------------------------------------

def serve_profile_entries(profile: str, dtype=None) -> list:
    """Surface (a) for every servable engine: the serve bucket grid —
    every (endpoint, batch, assets) shape a micro-batch dispatch may
    take — wrapping the SAME ``lru_cache``-shared jitted callables the
    live service dispatches, so ``csmom warmup --profiles serve``
    AOT-persists byte-identical HLO."""
    import numpy as np

    from csmom_tpu.compile.manifest import ManifestEntry, sds
    from csmom_tpu.serve.buckets import bucket_spec
    from csmom_tpu.serve.engine import serve_entry_fn
    from csmom_tpu.serve.service import ServeConfig

    spec = bucket_spec(profile)
    dt = np.dtype(dtype or spec.dtype)
    cfg = ServeConfig()  # the single source of the service's signal params
    out = []
    for kind in REGISTRY.serve_endpoints():
        fn = serve_entry_fn(kind, cfg.lookback, cfg.skip, cfg.n_bins,
                            cfg.mode)
        for B, A, M in spec.shapes():
            out.append(ManifestEntry(
                name=f"serve.{kind}.b{B}@{A}x{M}",
                fn=fn,
                args=(sds((B, A, M), dt), sds((B, A, M), bool)),
            ))
    return out


def _stream_manifest(profile: str, dtype=None) -> list:
    """The event-time replay's on-device reconciliation entries: the
    REAL jitted ``signals`` engines (momentum + turnover) at the
    canonical replay panel shapes, so a jax-engine replay's periodic
    full-panel reconciliation dispatches only warmed shapes."""
    import numpy as np

    from csmom_tpu.compile.manifest import ManifestEntry, sds
    from csmom_tpu.serve.buckets import bucket_spec
    from csmom_tpu.signals.momentum import momentum
    from csmom_tpu.signals.turnover import turnover_features
    from csmom_tpu.stream.replay import (
        REPLAY_BARS,
        REPLAY_SMOKE_BARS,
        ReplayConfig,
    )

    smoke = profile == "stream-smoke"
    spec = bucket_spec("serve-smoke" if smoke else "serve")
    bars = REPLAY_SMOKE_BARS if smoke else REPLAY_BARS
    cfg = ReplayConfig()  # the single source of the replay signal params
    dt = np.dtype(dtype or cfg.dtype)
    out = []
    for A in spec.asset_buckets:
        p = sds((A, bars), dt)
        m = sds((A, bars), bool)
        out.append(ManifestEntry(
            name=f"stream.momentum@{A}x{bars}",
            fn=momentum, args=(p, m),
            kwargs=dict(lookback=cfg.lookback, skip=cfg.skip),
        ))
        out.append(ManifestEntry(
            name=f"stream.turn_avg@{A}x{bars}",
            fn=turnover_features,
            args=(p, m, sds((A,), dt)),
            kwargs=dict(lookback=cfg.turn_lookback),
        ))
    return out


def serve_profile_entry_names(profile: str) -> set:
    """The jax-free twin of :func:`serve_profile_entries`: the entry
    NAMES the feeder will compile, from bucket geometry + the registry's
    serve endpoints alone.  This is the warm-coverage declaration the
    compile-surface lint rule (ISSUE 12) cross-checks against
    ``health.expected_entry_names`` — the two sides derive the same
    world through different code paths, so a feeder that drifts (or is
    deregistered) fails the sweep instead of compiling in-window."""
    from csmom_tpu.serve.buckets import bucket_spec

    spec = bucket_spec(profile)
    return {f"serve.{kind}.b{B}@{A}x{M}"
            for kind in REGISTRY.serve_endpoints()
            for B, A, M in spec.shapes()}


REGISTRY.register(EngineSpec(
    name="serve.buckets", kind="compile",
    description="the serving tier's closed shape world: every "
                "(endpoint, batch, assets) bucket shape, generated from "
                "the registry's serve endpoints at call time",
    axes="values f[B,A,M], mask bool[B,A,M] per endpoint",
    profiles=("serve", "serve-smoke"),
    manifest_fn=serve_profile_entries,
    manifest_names_fn=serve_profile_entry_names,
))

REGISTRY.register(EngineSpec(
    name="stream.signals", kind="compile",
    description="the replay harness's on-device reconciliation entries "
                "(jitted momentum/turnover at the canonical replay "
                "shapes)",
    axes="prices/volumes f[A,bars], mask bool[A,bars]",
    profiles=("stream", "stream-smoke"),
    manifest_fn=_stream_manifest,
))


# ---------------------------------------------------------------------------
# mesh profiles (ISSUE 10): the SHARDED twins of the serve bucket grid
# and the bench grid, keyed by the live device topology at call time —
# `csmom warmup --profiles serve-mesh bench-mesh` AOT-warms and
# memory-profiles the exact callables the mesh engine / sharded bench
# leg dispatch, so a mesh serving window keeps in_window_fresh_compiles
# == 0 like everything else.
# ---------------------------------------------------------------------------

def mesh_serve_profile_entries(profile: str, dtype=None) -> list:
    """The sharded serve bucket grid: every (endpoint, batch, assets)
    shape's mesh entry at the CURRENT device count.  Shard counts ride
    in the entry name (``.d<n>``) because the compiled world is keyed
    by them — a warmup on 8 host devices and a worker pinned to 2
    compile different programs, and the names must say so."""
    import numpy as np

    from csmom_tpu.compile.manifest import ManifestEntry, sds
    from csmom_tpu.mesh.variants import sharded_serve_jit_for
    from csmom_tpu.serve.buckets import bucket_spec
    from csmom_tpu.serve.service import ServeConfig

    spec = bucket_spec("serve-smoke" if profile.endswith("-smoke")
                       else "serve")
    dt = np.dtype(dtype or spec.dtype)
    cfg = ServeConfig()  # the single source of the service's signal params
    out = []
    for kind in REGISTRY.serve_endpoints():
        for B, A, M in spec.shapes():
            fn, n = sharded_serve_jit_for(kind, B, A, cfg.lookback,
                                          cfg.skip, cfg.n_bins, cfg.mode)
            out.append(ManifestEntry(
                name=f"mesh.serve.{kind}.b{B}@{A}x{M}.d{n}",
                fn=fn,
                args=(sds((B, A, M), dt), sds((B, A, M), bool)),
            ))
    # the scaling probe's single-device REFERENCE entry (MeshJaxEngine
    # warms it before the freshness snapshot): in the profile so a mesh
    # worker start loads it from the AOT cache like everything else
    # instead of paying a hidden pre-snapshot compile per process
    from csmom_tpu.serve.engine import serve_entry_fn

    probe = REGISTRY.serve_endpoints()[0]
    B, A, M = spec.batch_buckets[-1], spec.asset_buckets[-1], spec.months
    out.append(ManifestEntry(
        name=f"mesh.serve.single-probe.{probe}.b{B}@{A}x{M}",
        fn=serve_entry_fn(probe, cfg.lookback, cfg.skip, cfg.n_bins,
                          cfg.mode),
        args=(sds((B, A, M), dt), sds((B, A, M), bool)),
    ))
    return out


def _mesh_grid_manifest(profile: str, dtype=None) -> list:
    """The grid-cell x asset sharded J x K entries (reduced + full-size
    panels, the bench-cpu pair) on the current topology — what
    ``bench.py``'s sharded full-grid leg dispatches."""
    import numpy as np

    import jax

    from csmom_tpu.compile import workloads as wl
    from csmom_tpu.compile.manifest import ManifestEntry, months_of, sds
    from csmom_tpu.mesh.pinning import shards_for
    from csmom_tpu.mesh.rules import grid_asset_mesh
    from csmom_tpu.parallel.collectives import grid_shard_fn

    m = _manifest_mod()
    dt = _dt(profile, dtype)
    idx = np.dtype(np.int64 if dt == np.float64 else np.int32)
    ndev = len(jax.devices())
    nJ = len(wl.GRID_JS)
    g = shards_for(nJ, ndev)
    out = []
    for A, T in (wl.REDUCED_GRID, wl.NORTH_STAR_GRID):
        a = shards_for(A, max(1, ndev // g))
        mesh = grid_asset_mesh(g, a)
        fn = grid_shard_fn(mesh, wl.GRID_SKIP, 10, "rank",
                           max(wl.GRID_KS), "xla")
        M = months_of(T)
        out.append(ManifestEntry(
            name=f"mesh.grid.jk16.rank.xla@{A}x{M}.g{g}a{a}",
            fn=fn,
            args=(sds((A, M), dt), sds((A, M), bool),
                  sds((nJ,), idx), sds((len(wl.GRID_KS),), idx)),
        ))
    return out


REGISTRY.register(EngineSpec(
    name="mesh.serve", kind="compile",
    description="the sharded serve bucket grid: batch-/asset-axis "
                "sharded micro-batch entries per endpoint at the live "
                "device count (csmom_tpu/mesh partition rules)",
    axes="values f[B,A,M], mask bool[B,A,M] per endpoint, batch or "
         "asset axis sharded",
    profiles=("serve-mesh", "serve-mesh-smoke"),
    manifest_fn=mesh_serve_profile_entries,
))

REGISTRY.register(EngineSpec(
    name="mesh.grid", kind="compile",
    description="the grid-cell x asset sharded J x K backtest entries "
                "(reduced + north-star panels) on the live topology",
    axes="prices f[A,M], mask bool[A,M], Js/Ks grid-sharded",
    profiles=("bench-mesh",),
    manifest_fn=_mesh_grid_manifest,
))
