"""The engine registry: register once, get the whole production surface.

Before this package (ISSUE 9), a computation became a production
citizen through four hand-maintained enumerations: the ``lru_cache``'d
jit wrappers in ``compile/entries.py``, the ``ENDPOINTS`` tuple
``serve/buckets.py`` baked into the serving tier, the loadgen/bench
workload mixes, and the ``strategy/base.py`` plugin table.  Adding the
double-sort or low-volatility engine to the serving tier meant an edit
in every one of them — which is why, three serving rounds in, only the
original three endpoints were servable.

This module is the single table.  An engine registered once (name,
callable factory, shape signature, dtype, axis semantics) automatically
receives every surface the production stack offers:

(a) **shape-manifest entries** — ``csmom warmup`` AOT-compiles and
    memory-profiles it like the grid/event entries
    (:func:`EngineRegistry.manifest_entries` is what
    ``compile/manifest.py`` now builds from);
(b) **a donated-buffer jit variant** (:meth:`EngineSpec.donated`);
(c) **a serve endpoint** padded onto the existing shape-bucket grid —
    zero in-window compiles by construction, because the registry is
    also what enumerates the warm set;
(d) **a loadgen workload leg** that lands per-endpoint ledger rows
    (``serve/loadgen.py`` resolves its endpoint mix here);
(e) **a sharded variant** (:meth:`EngineSpec.sharded`) — resolved from
    the mesh subsystem's partition-rule table
    (:mod:`csmom_tpu.mesh.variants`): batch/asset-axis sharding for
    serve endpoints, grid-cell x asset for the J x K engines; an
    explicit ``sharded_fn`` overrides the rules.

Layering: this module is stdlib-only (no numpy, no jax) so the
jax-free consumers — ``chaos/invariants.py`` validating an artifact's
endpoint set, ``serve/health.py`` fingerprinting the warm contract,
the fast rehearse tier — can query names and surfaces without paying
an accelerator import.  The builtin registrations live in
:mod:`csmom_tpu.registry.builtin` (loaded lazily on first query) and
keep jax imports inside their factories for the same reason.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Mapping

__all__ = [
    "EngineRegistry",
    "EngineSpec",
    "REGISTRY",
    "ServeSurface",
    "register_engine",
]

KINDS = ("serve", "compile", "strategy", "lint")


@dataclasses.dataclass(frozen=True)
class ServeSurface:
    """What a servable engine contributes to the serving tier.

    ``batch_fn(params)`` returns the per-request scorer
    ``one(values f[A, M], mask bool[A, M]) -> f[A] | f[len(fields)]``
    (jax; the serve engine vmaps and jits it into the one-dispatch
    micro-batch entry).  ``stub_fn(params)`` returns the jax-free numpy
    mirror over the WHOLE batch ``(values f[B, A, M], mask bool[B, A, M])``
    — a simplified model, not a parity claim: every stub consumer is
    testing queue/batcher/chaos plumbing, never signal values.

    ``params`` is the service's engine-identity dict
    (``lookback``/``skip``/``n_bins``/``mode``); a factory uses what it
    needs and ignores the rest, exactly like a Strategy ignores panels
    it does not consume.
    """

    batch_fn: Callable
    stub_fn: Callable
    output: str = "per_asset"       # "per_asset" (f[B, A]) | "summary"
    summary_fields: tuple = ()      # names of the summary lanes (f[B, len])
    panel_family: str = "price"     # loadgen synthetic family: price|volume

    def __post_init__(self):
        if self.output not in ("per_asset", "summary"):
            raise ValueError(
                f"output must be 'per_asset' or 'summary', got "
                f"{self.output!r}")
        if self.output == "summary" and not self.summary_fields:
            raise ValueError("a summary endpoint must name its fields")


@dataclasses.dataclass(frozen=True)
class EngineSpec:
    """One registered engine and everything the stack derives from it.

    ``kind``:

    - ``"serve"`` — a request-path endpoint; ``serve`` (the
      :class:`ServeSurface`) is required.  Gets surfaces (a)-(e).
    - ``"compile"`` — an offline hot entry (grid/event/histrank/...);
      ``manifest_fn(profile, dtype) -> [ManifestEntry]`` declares its
      canonical shapes for each profile in ``profiles``.
    - ``"strategy"`` — a :class:`csmom_tpu.strategy.base.Strategy`
      plugin class (``strategy_cls``); the CLI/config layer's zoo.
    - ``"lint"`` — a static-analysis rule class (``rule_cls``, a
      :class:`csmom_tpu.analysis.core.LintRule` subclass); registration
      enrolls it in ``csmom lint``, the tier-1 sweep, this listing, and
      the fixture self-test harness (ISSUE 11).

    ``entry_fn`` is the raw (``lru_cache``-shared) jitted-entry factory
    — what ``bench.py`` fetches so bench and warmup keep lowering
    byte-identical HLO.  ``donated_fn`` is the donated-buffer variant
    factory; serve engines get an auto-derived one from the engine
    layer when none is declared.  ``sharded_fn`` is the mesh-variant
    hook: None means *resolve via the partition-rule table*
    (:func:`csmom_tpu.mesh.variants.resolve_sharded`); a kind the
    table has no placement for still raises a pointed
    NotImplementedError from :meth:`sharded`.
    """

    name: str
    kind: str
    description: str = ""
    dtype: str | None = None        # canonical compute dtype, when fixed
    axes: str | None = None         # axis semantics, e.g. "f[B,A,M] panels"
    profiles: tuple = ()            # warmup profiles this engine feeds
    manifest_fn: Callable | None = None
    # jax-free twin of manifest_fn: ``manifest_names_fn(profile) ->
    # set[str]`` declares the entry NAMES the feeder will compile,
    # without paying the jax import the entries themselves need.  This
    # is what the compile-surface lint rule (ISSUE 12) cross-checks
    # against ``health.expected_entry_names`` so "every dispatchable
    # shape is warmed" is a static fact, not a ledger row.
    manifest_names_fn: Callable | None = None
    entry_fn: Callable | None = None
    donated_fn: Callable | None = None
    sharded_fn: Callable | None = None
    serve: ServeSurface | None = None
    strategy_cls: type | None = None
    rule_cls: type | None = None    # kind-"lint": the LintRule subclass
    workload: bool = True           # serve engines default into loadgen

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got "
                             f"{self.kind!r}")
        if self.kind == "serve" and self.serve is None:
            raise ValueError(f"serve engine {self.name!r} needs a "
                             "ServeSurface")
        if self.kind == "strategy" and self.strategy_cls is None:
            raise ValueError(f"strategy {self.name!r} needs strategy_cls")
        if self.kind == "lint" and self.rule_cls is None:
            raise ValueError(f"lint rule {self.name!r} needs rule_cls")

    def donated(self, **params):
        """The donated-buffer jit variant (surface (b)).

        Serve engines fall back to the engine layer's auto-derived
        variant (same scorer, input buffers donated) when no explicit
        ``donated_fn`` was declared.
        """
        if self.donated_fn is not None:
            return self.donated_fn(**params)
        if self.kind == "serve":
            from csmom_tpu.serve.engine import serve_entry_fn_donated

            return serve_entry_fn_donated(
                self.name, params.get("lookback", 12),
                params.get("skip", 1), params.get("n_bins", 10),
                params.get("mode", "rank"))
        raise NotImplementedError(
            f"engine {self.name!r} declares no donated-buffer variant")

    def sharded(self, *args, **kwargs):
        """The sharded-variant hook (surface (e)), filled at r15.

        An explicit ``sharded_fn`` wins; otherwise the mesh subsystem's
        rule table resolves one (:func:`csmom_tpu.mesh.variants.
        resolve_sharded` — batch/asset-axis sharding for serve
        endpoints including runtime-registered ones, grid-cell x asset
        for the J x K engines, asset/time placements for the rest).  A
        kind with no rule — a Strategy plugin class has no dispatchable
        axis of its own — still refuses loudly with the remedy named.
        """
        if self.sharded_fn is not None:
            return self.sharded_fn(*args, **kwargs)
        from csmom_tpu.mesh.variants import resolve_sharded

        fn = resolve_sharded(self)
        if fn is None:
            raise NotImplementedError(
                f"{self.kind} engine {self.name!r} has no sharded "
                "variant: no partition rule in csmom_tpu/mesh/variants "
                "matches it — add a rule there (or register the engine "
                "with sharded_fn=...) if this kind has a meaningful "
                "mesh placement")
        return fn(*args, **kwargs)


class EngineRegistry:
    """Ordered, thread-safe ``(kind, name)`` -> :class:`EngineSpec` table.

    Keys are namespaced by kind: ``momentum`` the serve endpoint and
    ``momentum`` the Strategy plugin are different registrations of the
    same underlying signal family, and each surface queries its own
    kind — collisions are only an error WITHIN a kind.
    """

    def __init__(self):
        self._specs: dict[tuple, EngineSpec] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ mutate --

    def register(self, spec: EngineSpec, replace: bool = False) -> EngineSpec:
        key = (spec.kind, spec.name)
        with self._lock:
            if not replace and key in self._specs \
                    and self._specs[key] != spec:
                raise ValueError(
                    f"{spec.kind} engine {spec.name!r} is already "
                    "registered; pass replace=True to overwrite "
                    "deliberately")
            self._specs[key] = spec
        return spec

    def unregister(self, name: str, kind: str | None = None) -> None:
        with self._lock:
            for key in [k for k in self._specs
                        if k[1] == name and (kind is None or k[0] == kind)]:
                self._specs.pop(key, None)

    # ------------------------------------------------------------- query --

    def get(self, name: str, kind: str | None = None) -> EngineSpec:
        if kind is not None:
            try:
                return self._specs[(kind, name)]
            except KeyError:
                raise KeyError(
                    f"unknown {kind} engine {name!r}; registered "
                    f"{kind} engines: {self.names(kind)}") from None
        with self._lock:
            matches = [s for k, s in self._specs.items() if k[1] == name]
        if not matches:
            raise KeyError(
                f"unknown engine {name!r}; registered: "
                f"{sorted(k[1] for k in self._specs)}")
        if len(matches) > 1:
            raise KeyError(
                f"engine name {name!r} exists in several kinds "
                f"({sorted(s.kind for s in matches)}); pass kind=")
        return matches[0]

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return any(k[1] == name for k in self._specs)

    def _snapshot(self) -> list:
        """A stable view for iteration: a registration may land WHILE a
        query runs (a manifest feeder's factory importing the strategy
        zoo is the canonical case), and iterating the live dict then is
        a RuntimeError."""
        with self._lock:
            return list(self._specs.values())

    def specs(self, kind: str | None = None) -> tuple:
        """Registered specs in registration order (optionally one kind)."""
        return tuple(s for s in self._snapshot()
                     if kind is None or s.kind == kind)

    def names(self, kind: str | None = None) -> tuple:
        return tuple(s.name for s in self.specs(kind))

    def serve_endpoints(self) -> tuple:
        """The serving tier's endpoint names, in registration order —
        what ``ENDPOINTS`` used to hard-code."""
        return self.names("serve")

    def serve_surface(self, name: str) -> ServeSurface:
        return self.get(name, kind="serve").serve

    def workload_kinds(self) -> tuple:
        """The loadgen endpoint mix: every servable engine that opted
        into the synthetic workload (surface (d))."""
        return tuple(s.name for s in self.specs("serve") if s.workload)

    def strategies(self) -> dict:
        """name -> Strategy class for every registered strategy plugin."""
        return {s.name: s.strategy_cls for s in self.specs("strategy")}

    # ---------------------------------------------------------- manifest --

    def manifest_profiles(self) -> tuple:
        """Every warmup profile any engine feeds, registration-ordered."""
        out: list = []
        for s in self._snapshot():
            for p in s.profiles:
                if p not in out:
                    out.append(p)
        return tuple(out)

    def manifest_entry_names(self, profile: str) -> set:
        """The entry NAMES the profile's feeders declare they will warm
        — the jax-free aggregation of ``manifest_names_fn`` (empty for
        a profile whose feeders declare no names).  The compile-surface
        lint rule compares this against the serving tier's dispatchable
        world (``health.expected_entry_names``)."""
        out: set = set()
        for spec in self._snapshot():
            if profile in spec.profiles and spec.manifest_names_fn:
                out |= set(spec.manifest_names_fn(profile))
        return out

    def manifest_entries(self, profile: str, dtype=None) -> list:
        """Surface (a): the profile's manifest, aggregated across every
        engine that declared it.  This is what ``compile/manifest.py``'s
        ``build_manifest`` now returns — the per-profile entry tables
        live on the specs, not in a module-level dispatch."""
        if profile not in self.manifest_profiles():
            raise ValueError(
                f"unknown warmup profile {profile!r}: use one of "
                f"{self.manifest_profiles()}")
        entries: list = []
        for spec in self._snapshot():
            if profile in spec.profiles and spec.manifest_fn is not None:
                entries += spec.manifest_fn(profile, dtype)
        return entries


# the process-wide registry; builtins attach on first query (lazily, so
# importing this module costs nothing beyond the dataclasses above)
REGISTRY = EngineRegistry()

_BUILTIN_LOCK = threading.Lock()
_BUILTIN_LOADED = False


def ensure_builtin() -> EngineRegistry:
    """Load the builtin registrations exactly once; returns REGISTRY."""
    global _BUILTIN_LOADED
    if not _BUILTIN_LOADED:
        with _BUILTIN_LOCK:
            if not _BUILTIN_LOADED:
                import csmom_tpu.registry.builtin  # noqa: F401

                _BUILTIN_LOADED = True
    return REGISTRY


def register_engine(spec: EngineSpec | None = None, *, replace: bool = False,
                    **fields) -> EngineSpec:
    """Register one engine (a built ``EngineSpec`` or its fields).

    The module-level entry point user code and tests use — a toy engine
    registered here immediately has all five surfaces: it appears in
    the serve-profile manifest, warms, serves, joins the loadgen mix,
    and carries the sharded hook, with no other file edited.
    """
    if spec is None:
        spec = EngineSpec(**fields)
    ensure_builtin()
    return REGISTRY.register(spec, replace=replace)
