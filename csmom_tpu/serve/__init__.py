"""csmom_tpu.serve — the online workload: a micro-batching signal service.

Every entry point before this package was a one-shot batch CLI; this is
the request path the ROADMAP's "serve heavy traffic" north star needs,
built the way inference servers batch (continuous/micro-batching in the
Orca spirit, Yu et al. OSDI '22) and composed from subsystems earlier
rounds landed:

- :mod:`~csmom_tpu.serve.slo` — named SLO classes (interactive /
  standard / bulk; the r10 ``batch`` name aliases): per-class deadline
  budgets, token-bucket admission quotas, and queue-share bounds, so a
  bulk tenant provably cannot starve interactive scoring.
- :mod:`~csmom_tpu.serve.queue` — bounded admission queue: per-request
  monotonic deadlines, SLO-class-ranked collection, and BACKPRESSURE — a
  full queue rejects with a retry-after hint instead of buffering
  unboundedly.  Every request presented to the service terminates in
  exactly one of ``served`` / ``rejected`` / ``expired`` (the accounting
  invariant the chaos scenarios assert: served + rejected + expired ==
  admitted — globally AND per class).
- :mod:`~csmom_tpu.serve.cache` — version-keyed idempotent result cache
  (content fingerprint + signal params + ``panel_version``) with
  in-flight coalescing: identical concurrent requests share one
  dispatch, ``panel_version`` bumps invalidate, stale hits are zero BY
  SCHEMA.
- :mod:`~csmom_tpu.serve.batcher` — adaptive micro-batch coalescer
  (deadline-aware continuous batching, Orca-style): fires early when a
  queued deadline is at risk, refills with a zero window when the
  engine frees under backlog, waits the coalescing window only when
  idle — then pads the gathered same-endpoint requests up to the
  nearest :mod:`~csmom_tpu.serve.buckets` shape bucket, so every
  dispatch hits a shape the engine already warmed — zero in-window fresh
  compiles by construction, verified via ``profiling.compile_stats``.
- :mod:`~csmom_tpu.serve.engine` — the scoring backends: ``JaxEngine``
  (vmapped momentum / turnover / mini-backtest kernels, one dispatch per
  micro-batch; shapes enumerable by the ``compile/manifest.py`` ``serve``
  profile so ``csmom warmup --profiles serve`` AOT-persists them) and
  ``StubEngine`` (pure numpy, jax-free — what the fast rehearse tier and
  plumbing tests drive).
- :mod:`~csmom_tpu.serve.service` — the worker loop: admission →
  coalesce → dispatch, chaos checkpoints at each stage, queue-depth /
  batch-size / latency metrics into :mod:`csmom_tpu.obs`, requests whose
  deadline expired while queued cancelled before dispatch, and a worker
  crash mid-batch terminating its in-flight requests (rejected, with the
  crash as the reason) while the queue stays drainable.
- :mod:`~csmom_tpu.serve.loadgen` — seeded OPEN-LOOP load generator
  (arrivals fire on schedule whether or not the service keeps up — the
  honest way to find the saturation knee) emitting a schema-valid
  ``SERVE_<run>.json`` artifact: throughput, batch-size distribution,
  p50/p95/p99 queue + service latency, request accounting, and the
  in-window compile count.  :mod:`csmom_tpu.chaos.invariants` validates
  it (kind ``serve``) and :mod:`csmom_tpu.obs.ledger` ingests it, so
  serve latency/throughput join the cross-run regression gate.

Everything is in-process and thread-based (no network dependency), so
the full admission→coalesce→dispatch pipeline runs in tier-1 on CPU.
Clock discipline: all timing goes through
:func:`csmom_tpu.utils.deadline.mono_now_s` (monotonic, skew-proof).
"""

from csmom_tpu.registry import serve_endpoints
from csmom_tpu.serve.buckets import BucketSpec, bucket_spec

__all__ = ["BucketSpec", "bucket_spec", "serve_endpoints"]
