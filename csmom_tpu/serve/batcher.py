"""Adaptive micro-batch coalescer: deadline-aware continuous batching.

The batcher turns the queue's per-request panels into the CLOSED set of
shapes the engine warmed (:mod:`csmom_tpu.serve.buckets`).  r10's
version waited a FIXED max-latency window before every dispatch; this
one decides adaptively (the continuous-batching refinement of Orca
[Yu et al., OSDI 2022 — PAPERS.md [4]], adapted to padded shape
buckets):

- **Fire early when a deadline is at risk**: before every wait the
  queue reports the smallest remaining deadline budget among gatherable
  requests; when it dips under the risk margin — an EMA of recent batch
  service walls times a safety factor, plus a floor — the batch fires
  NOW, because waiting out the window would expire the request.  The
  margin adapts to the engine actually being driven (a TPU batch and a
  CPU batch learn different margins from the same code).
- **Refill the instant the engine frees**: when the previous dispatch
  returns and work is already queued, the next micro-batch collects
  with a ZERO window ("refill" fire reason) — under sustained load the
  coalescing window adds no latency and batches grow toward the bucket
  grid's ceiling on their own, because everything that arrived during
  the previous engine call is taken at once.  This is Orca's
  iteration-level scheduling mapped onto our iteration unit: one padded
  bucket dispatch.
- **Coalesce only when idle**: a request arriving at an idle service
  waits at most ``max_wait_s`` for co-batchable company (r10's window
  behavior — the right trade when there is no backlog to refill from).

Every dispatch still pads onto the warmed bucket grid:

- each request's asset axis up to the smallest asset bucket that holds
  it (padded lanes carry a False mask, so kernels ignore them exactly
  like delisted names), and
- the batch axis up to the smallest batch bucket (padding rows are
  all-masked dummies),

so every dispatch is one of ``len(batch_buckets) x len(asset_buckets)``
shapes per endpoint — the zero-in-window-compiles property is a
consequence of this padding, not of luck about what clients send, and
adaptivity changes WHEN a batch fires, never what SHAPES exist.

Why pad instead of compiling per request shape: a fresh XLA compile is
seconds (CPU) to ~30 s (tunneled TPU) of request-path latency, paid by
the first caller of every new universe size and again after every
restart; padding costs masked FLOPs bounded by the bucket step (< 4x
worst case, measured per run as ``pad_fraction`` in the SERVE artifact).
For a service the trade is not close — see ARCHITECTURE "Serving".

The per-batch fire reasons (``full`` / ``deadline_risk`` / ``window`` /
``refill``) are counted and land in the SERVE artifact's ``batches``
block, so the dispatch policy's actual behavior under a given load is
evidence, not intent.

Numpy-only (the jax side lives in :mod:`csmom_tpu.serve.engine`), so the
stub engine path and the fast rehearse tier stay jax-free.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from csmom_tpu.serve.buckets import BucketSpec
from csmom_tpu.serve.queue import AdmissionQueue

__all__ = ["Batcher", "Microbatch"]

# deadline-risk margin: fire early when a queued deadline's remaining
# budget <= SAFETY * (batch service EMA) + FLOOR.  SAFETY covers pad/
# fan-out overhead around the engine call; FLOOR covers the cold start
# before any batch has been measured.
RISK_SAFETY = 2.0
RISK_FLOOR_S = 0.002


@dataclasses.dataclass
class Microbatch:
    """One coalesced, padded dispatch unit."""

    kind: str
    requests: list               # live (non-expired) requests, batch order
    batch_bucket: int            # B: padded batch rows
    asset_bucket: int            # A: padded asset lanes
    values: np.ndarray           # f32[B, A, M]
    mask: np.ndarray             # bool[B, A, M]
    fire_reason: str = "window"  # why collect fired (see queue.collect)

    @property
    def pad_fraction(self) -> float:
        """Fraction of dispatched (batch, asset) lanes that are padding —
        the honesty metric for the bucket grid."""
        used = sum(r.n_assets for r in self.requests)
        total = self.batch_bucket * self.asset_bucket
        return round(1.0 - used / total, 4) if total else 0.0


class Batcher:
    """Coalesce queued requests into padded bucket-shaped micro-batches,
    deciding WHEN to fire adaptively (deadline risk, refill, window)."""

    def __init__(self, spec: BucketSpec, max_wait_s: float = 0.01):
        self.spec = spec
        self.max_wait_s = max_wait_s
        self._lock = threading.Lock()
        self._service_ema_s: float | None = None
        self.fire_reasons: dict = {}

    def note_service_wall(self, wall_s: float) -> None:
        """Feed one batch's dispatch wall into the risk-margin EMA (the
        service calls this after every engine call, crash or not)."""
        with self._lock:
            ema = self._service_ema_s
            self._service_ema_s = (wall_s if ema is None
                                   else 0.8 * ema + 0.2 * wall_s)

    def risk_margin_s(self) -> float:
        """How much remaining deadline budget a queued request needs for
        waiting to still be safe: below this, fire immediately."""
        with self._lock:
            ema = self._service_ema_s or 0.0
        return RISK_SAFETY * ema + RISK_FLOOR_S

    def next_batch(self, queue: AdmissionQueue,
                   stop: threading.Event) -> Microbatch | None:
        """Block for the next micro-batch; None when ``stop`` is set (or
        every gathered request had already expired, or padding failed).

        Continuous-batching refill: when work is already queued at entry
        (the engine just freed with a backlog), collect runs with a zero
        window and fires immediately with everything gatherable — the
        idle-arrival coalescing window only applies when the queue was
        empty.

        Padding failure is CONTAINED here, not propagated: once requests
        have been taken off the queue, an escaping exception would kill
        the worker thread with those requests never reaching a terminal
        state — exactly the silent drop the accounting invariant exists
        to forbid.  A batch that cannot be padded terminates rejected
        (with the reason) and the worker lives on.
        """
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics

        window_s = 0.0 if queue.depth() > 0 else self.max_wait_s
        reqs, reason = queue.collect(self.spec.max_batch, window_s, stop,
                                     risk_s=self.risk_margin_s())
        if not reqs:
            return None
        with self._lock:
            self.fire_reasons[reason] = self.fire_reasons.get(reason, 0) + 1
        checkpoint("serve.coalesce", kind=reqs[0].kind, n=len(reqs),
                   fire=reason)
        for r in reqs:
            # stage boundary: taken off the queue -> batch formed (the
            # coalesce bookkeeping); padding time gets its own clock next
            if r.trace is not None:
                r.trace.mark("coalesce").set(fire_reason=reason,
                                             batch_n=len(reqs))
        try:
            mb = self.pad(reqs)
            mb.fire_reason = reason
            for r in reqs:
                if r.trace is not None:
                    r.trace.mark("pad").set(
                        bucket=f"{mb.batch_bucket}x{mb.asset_bucket}")
            return mb
        except Exception as e:
            metrics.counter("serve.pad_failures").inc()
            reason_s = f"could not pad batch ({type(e).__name__}: {e})"[:200]
            for r in reqs:
                queue.finish_rejected(r, reason_s)
            return None

    def fire_reason_counts(self) -> dict:
        with self._lock:
            return dict(sorted(self.fire_reasons.items()))

    def pad(self, reqs: list) -> Microbatch:
        """Pad ``reqs`` (same endpoint, each ``values/mask`` = [A_i, M])
        into one bucket-shaped array pair."""
        kind = reqs[0].kind
        B = self.spec.batch_bucket_for(len(reqs))
        A = self.spec.asset_bucket_for(max(r.n_assets for r in reqs))
        if A is None:  # service.submit rejects oversize at the door
            raise ValueError(
                f"request exceeds the largest asset bucket "
                f"{self.spec.max_assets}")
        M = self.spec.months
        dtype = np.dtype(self.spec.dtype)
        values = np.zeros((B, A, M), dtype=dtype)
        mask = np.zeros((B, A, M), dtype=bool)
        for b, r in enumerate(reqs):
            v = np.asarray(r.values, dtype=dtype)
            m = np.asarray(r.mask, dtype=bool)
            if v.shape != (r.n_assets, M):
                raise ValueError(
                    f"request {r.req_id}: values shape {v.shape} does not "
                    f"match (n_assets={r.n_assets}, months={M})")
            if m.shape != v.shape:
                raise ValueError(
                    f"request {r.req_id}: mask shape {m.shape} does not "
                    f"match the values panel {v.shape}")
            values[b, :r.n_assets] = v
            mask[b, :r.n_assets] = m
        return Microbatch(kind=kind, requests=list(reqs), batch_bucket=B,
                          asset_bucket=A, values=values, mask=mask)
