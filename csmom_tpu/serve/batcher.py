"""Micro-batch coalescer: gathered requests -> one padded bucket dispatch.

The batcher turns the queue's per-request panels into the CLOSED set of
shapes the engine warmed (:mod:`csmom_tpu.serve.buckets`): it waits up
to the coalescing window for same-endpoint company, then pads

- each request's asset axis up to the smallest asset bucket that holds
  it (padded lanes carry a False mask, so kernels ignore them exactly
  like delisted names), and
- the batch axis up to the smallest batch bucket (padding rows are
  all-masked dummies),

so every dispatch is one of ``len(batch_buckets) x len(asset_buckets)``
shapes per endpoint — the zero-in-window-compiles property is a
consequence of this padding, not of luck about what clients send.

Why pad instead of compiling per request shape: a fresh XLA compile is
seconds (CPU) to ~30 s (tunneled TPU) of request-path latency, paid by
the first caller of every new universe size and again after every
restart; padding costs masked FLOPs bounded by the bucket step (< 4x
worst case, measured per run as ``pad_fraction`` in the SERVE artifact).
For a service the trade is not close — see ARCHITECTURE "Serving".

Numpy-only (the jax side lives in :mod:`csmom_tpu.serve.engine`), so the
stub engine path and the fast rehearse tier stay jax-free.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from csmom_tpu.serve.buckets import BucketSpec
from csmom_tpu.serve.queue import AdmissionQueue

__all__ = ["Batcher", "Microbatch"]


@dataclasses.dataclass
class Microbatch:
    """One coalesced, padded dispatch unit."""

    kind: str
    requests: list               # live (non-expired) requests, batch order
    batch_bucket: int            # B: padded batch rows
    asset_bucket: int            # A: padded asset lanes
    values: np.ndarray           # f32[B, A, M]
    mask: np.ndarray             # bool[B, A, M]

    @property
    def pad_fraction(self) -> float:
        """Fraction of dispatched (batch, asset) lanes that are padding —
        the honesty metric for the bucket grid."""
        used = sum(r.n_assets for r in self.requests)
        total = self.batch_bucket * self.asset_bucket
        return round(1.0 - used / total, 4) if total else 0.0


class Batcher:
    """Coalesce queued requests into padded bucket-shaped micro-batches."""

    def __init__(self, spec: BucketSpec, max_wait_s: float = 0.01):
        self.spec = spec
        self.max_wait_s = max_wait_s

    def next_batch(self, queue: AdmissionQueue,
                   stop: threading.Event) -> Microbatch | None:
        """Block for the next micro-batch; None when ``stop`` is set (or
        every gathered request had already expired, or padding failed).

        Padding failure is CONTAINED here, not propagated: once requests
        have been taken off the queue, an escaping exception would kill
        the worker thread with those requests never reaching a terminal
        state — exactly the silent drop the accounting invariant exists
        to forbid.  A batch that cannot be padded terminates rejected
        (with the reason) and the worker lives on.
        """
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics

        reqs = queue.collect(self.spec.max_batch, self.max_wait_s, stop)
        if not reqs:
            return None
        checkpoint("serve.coalesce", kind=reqs[0].kind, n=len(reqs))
        try:
            return self.pad(reqs)
        except Exception as e:
            metrics.counter("serve.pad_failures").inc()
            reason = f"could not pad batch ({type(e).__name__}: {e})"[:200]
            for r in reqs:
                queue.finish_rejected(r, reason)
            return None

    def pad(self, reqs: list) -> Microbatch:
        """Pad ``reqs`` (same endpoint, each ``values/mask`` = [A_i, M])
        into one bucket-shaped array pair."""
        kind = reqs[0].kind
        B = self.spec.batch_bucket_for(len(reqs))
        A = self.spec.asset_bucket_for(max(r.n_assets for r in reqs))
        if A is None:  # service.submit rejects oversize at the door
            raise ValueError(
                f"request exceeds the largest asset bucket "
                f"{self.spec.max_assets}")
        M = self.spec.months
        dtype = np.dtype(self.spec.dtype)
        values = np.zeros((B, A, M), dtype=dtype)
        mask = np.zeros((B, A, M), dtype=bool)
        for b, r in enumerate(reqs):
            v = np.asarray(r.values, dtype=dtype)
            m = np.asarray(r.mask, dtype=bool)
            if v.shape != (r.n_assets, M):
                raise ValueError(
                    f"request {r.req_id}: values shape {v.shape} does not "
                    f"match (n_assets={r.n_assets}, months={M})")
            if m.shape != v.shape:
                raise ValueError(
                    f"request {r.req_id}: mask shape {m.shape} does not "
                    f"match the values panel {v.shape}")
            values[b, :r.n_assets] = v
            mask[b, :r.n_assets] = m
        return Microbatch(kind=kind, requests=list(reqs), batch_bucket=B,
                          asset_bucket=A, values=values, mask=mask)
