"""Serve shape buckets: the closed set of shapes a dispatch may take.

A request arrives with its own universe size; XLA compiles per shape.
Serving per-request shapes would therefore compile on the request path —
~30 s/shape on the tunneled TPU backend, seconds on CPU, either way a
latency cliff the first caller of every new size falls off.  The serve
layer instead pads every micro-batch up to the nearest entry of a SMALL
fixed grid of (batch, assets) buckets at one canonical month count, so
the set of dispatchable shapes is closed and enumerable: the
``compile/manifest.py`` ``serve`` profile lists exactly these shapes,
``csmom warmup --profiles serve`` AOT-persists them, and the service
warms them again (by execution) at startup — after which zero fresh
compiles can occur in the serving window *by construction* (verified per
run via ``profiling.compile_stats`` and recorded in the SERVE artifact).

The cost is padded lanes (masked out, so results are exact); the
``pad_fraction`` field of every SERVE artifact keeps that overhead
honest.  Bucket sizes are powers-of-two-ish steps so the worst-case pad
waste is bounded (< 4x on the asset axis, < 2x between batch steps).

This module is stdlib-only: the queue/batcher/service plumbing and the
fast rehearse tier import bucket geometry without touching jax.  The
ENDPOINT set is deliberately NOT here anymore (ISSUE 9): endpoints are
registered engines — :func:`csmom_tpu.registry.serve_endpoints` is the
one enumeration, and this module owns only shape geometry.
"""

from __future__ import annotations

import bisect
import dataclasses

__all__ = ["BucketSpec", "PROFILES", "bucket_spec"]


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """One closed shape grid: (batch buckets) x (asset buckets) x months."""

    name: str
    months: int                 # canonical history length M (time axis)
    asset_buckets: tuple        # ascending A buckets requests pad up to
    batch_buckets: tuple        # ascending B buckets micro-batches pad up to
    dtype: str = "float32"      # the serve compute dtype (TPU-native)

    def asset_bucket_for(self, n_assets: int) -> int | None:
        """Smallest asset bucket holding ``n_assets``; None = too large
        (the service rejects at admission — an unserveable shape must
        fail at the door, not compile on the dispatch path)."""
        if n_assets <= 0:
            return None
        i = bisect.bisect_left(self.asset_buckets, n_assets)
        return self.asset_buckets[i] if i < len(self.asset_buckets) else None

    def batch_bucket_for(self, n_requests: int) -> int:
        """Smallest batch bucket holding ``n_requests`` (the batcher never
        gathers more than ``max_batch`` requests, so this always fits)."""
        i = bisect.bisect_left(self.batch_buckets, n_requests)
        return self.batch_buckets[min(i, len(self.batch_buckets) - 1)]

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    @property
    def max_assets(self) -> int:
        return self.asset_buckets[-1]

    def shapes(self):
        """Every dispatchable (B, A, months) — the closed world the serve
        manifest profile enumerates and warmup compiles."""
        return [(b, a, self.months)
                for b in self.batch_buckets for a in self.asset_buckets]


PROFILES = {
    # the production grid: five years of months, universes to 128 names,
    # batches to 8 requests — 6 shapes per endpoint
    "serve": BucketSpec(
        name="serve", months=60, asset_buckets=(32, 128),
        batch_buckets=(1, 4, 8),
    ),
    # the tier-1/smoke grid: tiny shapes, every code path — 2 shapes per
    # endpoint, compiles in seconds on CPU
    "serve-smoke": BucketSpec(
        name="serve-smoke", months=24, asset_buckets=(8,),
        batch_buckets=(1, 4),
    ),
}


def bucket_spec(profile: str) -> BucketSpec:
    try:
        return PROFILES[profile]
    except KeyError:
        raise ValueError(
            f"unknown serve bucket profile {profile!r}: use one of "
            f"{sorted(PROFILES)}"
        ) from None
