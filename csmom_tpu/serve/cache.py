"""Version-keyed scoring result cache + in-flight request coalescing.

Scoring is idempotent: the same panel, the same signal params, and the
same engine produce the same result, so recomputing an identical request
burns device time live traffic needs.  This module adds the two layers
that exploit that, both bounded and both honest about staleness:

- :class:`ResultCache` — an LRU keyed by
  ``(endpoint, signal params, months, n_assets, panel fingerprint,
  panel_version)``.  The fingerprint is a content hash of the request's
  values+mask, so two byte-identical panels hit regardless of who sent
  them; ``panel_version`` (the ``stream/`` ingestion counter, r12) rides
  IN the key AND in a separate **version floor**: when ingestion bumps
  the panel version, :meth:`ResultCache.set_version_floor` drops every
  entry computed from an older panel and the get path refuses any entry
  below the floor even if one somehow survives (``stale_blocked``).
  ``stale_hits`` — a stale entry actually RETURNED — is structurally 0
  and the SERVE artifact schema enforces it stays 0, the same
  claimed-not-hoped pattern as ``expired_dispatched``.
- :class:`InflightCoalescer` — identical CONCURRENT requests share one
  dispatch: the first becomes the leader (queued and dispatched
  normally), later identical submissions attach as followers and are
  resolved from the leader's terminal state — each waiter gets the
  result exactly once, and the accounting books count every follower
  (``served_coalesced``) so coalescing never hides a request.

Memory is bounded two ways: ``max_entries`` and ``max_bytes`` (result
payload bytes, measured not guessed); eviction is LRU and counted.

Chaos: the ``serve.cache`` checkpoint fires on every lookup; the
``cache_poison`` action (caller-interpreted, like the stream tick
faults) plants an entry under the LOOKED-UP key whose stamped version
lies below the floor — rehearsing that the get-path floor check, not
the key shape, is what keeps poisoned results from being served.

Stdlib + numpy only, thread-safe, no clock reads at all (LRU order is
recency, not time — the time-discipline lint pins this module).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from collections import OrderedDict

import numpy as np

__all__ = ["CacheKey", "InflightCoalescer", "ResultCache",
           "panel_fingerprint"]


def panel_fingerprint(values: np.ndarray, mask: np.ndarray) -> str:
    """Content hash of one request panel (shape + dtype + bytes of both
    arrays): byte-identical panels collide, nothing else does."""
    h = hashlib.blake2b(digest_size=12)
    v = np.ascontiguousarray(values)
    m = np.ascontiguousarray(mask)
    h.update(repr((v.shape, str(v.dtype), m.shape, str(m.dtype))).encode())
    h.update(v.tobytes())
    h.update(m.tobytes())
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class CacheKey:
    """The idempotency key: what must match for a result to be reusable."""

    kind: str                    # endpoint
    params: tuple                # (engine, lookback, skip, n_bins, mode)
    months: int
    n_assets: int
    fingerprint: str             # content hash of values+mask
    panel_version: int | None    # stream ingestion version (None = batch)


@dataclasses.dataclass
class _Entry:
    result: object
    version: int | None
    nbytes: int


def _result_nbytes(result) -> int:
    """Measured payload size of one cached result."""
    if isinstance(result, np.ndarray):
        return int(result.nbytes)
    if isinstance(result, dict):
        return 64 * max(1, len(result))
    return 64


class ResultCache:
    """Bounded LRU of scoring results with a panel-version floor."""

    def __init__(self, max_entries: int = 512, max_bytes: int = 32 << 20):
        if max_entries < 1 or max_bytes < 1:
            raise ValueError("max_entries/max_bytes must be >= 1")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self.version_floor = 0
        # stats (the SERVE artifact's cache book)
        self.hits = 0
        self.misses = 0
        self.stale_blocked = 0   # stale entry found by GET and REFUSED
        self.stale_hits = 0      # stale entry RETURNED — structurally 0,
                                 # counted so the artifact claims it
        self.stale_put_refused = 0  # dispatch raced an invalidation: its
                                    # result arrived already-stale and
                                    # was refused insertion
        self.inserts = 0
        self.evictions = 0
        self.invalidated = 0

    # --------------------------------------------------------------- get --

    def get(self, key: CacheKey):
        """``(hit, result)``; a hit refreshes LRU order.  An entry whose
        stamped version sits below the floor is never returned — it is
        evicted and counted ``stale_blocked``."""
        from csmom_tpu.chaos.inject import checkpoint

        fired = checkpoint("serve.cache", kind=key.kind)
        with self._lock:
            if fired == "cache_poison":
                # plant a poisoned entry under this exact key, stamped
                # below the floor: only the get-path version check below
                # stands between it and a caller
                self._insert_locked(key, _Entry(
                    result="POISONED-STALE-RESULT",
                    version=self.version_floor - 1, nbytes=64))
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return False, None
            if e.version is not None and e.version < self.version_floor:
                # the floor gate: a stale entry is refused, never served
                self._remove_locked(key)
                self.stale_blocked += 1
                return False, None
            self._entries.move_to_end(key)
            self.hits += 1
            return True, e.result

    # --------------------------------------------------------------- put --

    def put(self, key: CacheKey, result) -> bool:
        """Insert (idempotent per key); refuses results already below the
        version floor — a dispatch that raced an invalidation must not
        resurrect stale data."""
        if isinstance(result, dict):
            # the cache keeps its OWN copy of mutable dict payloads, so
            # a caller editing its response cannot poison later hits
            # (ndarray payloads arrive frozen by the dispatch path)
            result = dict(result)
        with self._lock:
            if (key.panel_version is not None
                    and key.panel_version < self.version_floor):
                self.stale_put_refused += 1
                return False
            self._insert_locked(key, _Entry(
                result=result, version=key.panel_version,
                nbytes=_result_nbytes(result)))
            self.inserts += 1
            return True

    def _insert_locked(self, key: CacheKey, entry: _Entry) -> None:
        if key in self._entries:
            self._remove_locked(key)
        self._entries[key] = entry
        self._bytes += entry.nbytes
        while (len(self._entries) > self.max_entries
               or self._bytes > self.max_bytes):
            if len(self._entries) == 1 and self._bytes <= self.max_bytes:
                break  # a single oversize-entry cache still holds one
            oldest = next(iter(self._entries))
            if oldest == key and len(self._entries) == 1:
                break
            self._remove_locked(oldest)
            self.evictions += 1

    def _remove_locked(self, key: CacheKey) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= e.nbytes

    # -------------------------------------------------------- invalidate --

    def set_version_floor(self, floor: int) -> int:
        """Raise the version floor (monotone; a lower floor is ignored)
        and drop every entry stamped below it.  Returns how many entries
        were invalidated.  This is the ``panel_version``-bump hook the
        stream ingestion side calls (ROADMAP item 4's primitive)."""
        with self._lock:
            if floor <= self.version_floor:
                return 0
            self.version_floor = int(floor)
            stale = [k for k, e in self._entries.items()
                     if e.version is not None and e.version < floor]
            for k in stale:
                self._remove_locked(k)
            self.invalidated += len(stale)
            return len(stale)

    # -------------------------------------------------------------- stats --

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses + self.stale_blocked
            return {
                "hits": self.hits,
                "misses": self.misses,
                "stale_blocked": self.stale_blocked,
                "stale_hits": self.stale_hits,
                "stale_put_refused": self.stale_put_refused,
                "lookups": lookups,
                "hit_rate": (round(self.hits / lookups, 4)
                             if lookups else 0.0),
                "inserts": self.inserts,
                "evictions": self.evictions,
                "invalidated": self.invalidated,
                "entries": len(self._entries),
                "size_bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "version_floor": self.version_floor,
            }


class InflightCoalescer:
    """Identical concurrent requests share one dispatch.

    The FIRST submission of a key becomes the leader and proceeds
    through the queue normally; later submissions of the same key attach
    as followers on the leader's request object (the queue resolves them
    in the leader's exactly-once terminal transition, so each waiter
    gets its terminal state exactly once).  The map holds only live
    leaders: the service unregisters a key when its leader goes
    terminal, and ``lead_or_follow`` refuses to attach to a leader that
    is already terminal (the caller then consults the cache, which the
    leader's completion just filled).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._leaders: dict = {}

    def lead_or_follow(self, key: CacheKey, req, attach_fn) -> str:
        """Register ``req`` as the key's leader, or attach it to the
        current leader via ``attach_fn(leader, req) -> bool``.  Returns
        ``"leader"`` | ``"follower"`` | ``"retry"``.  ``"retry"`` means
        the leader reached a terminal state between the map lookup and
        the attach: the dead slot is freed and the caller must RE-CHECK
        the cache — a served leader's completion just filled it, so
        taking over the slot blindly would re-dispatch work whose result
        already exists."""
        with self._lock:
            leader = self._leaders.get(key)
            if leader is None:
                self._leaders[key] = req
                return "leader"
            if attach_fn(leader, req):
                return "follower"
            if self._leaders.get(key) is leader:
                del self._leaders[key]
            return "retry"

    def unregister(self, key: CacheKey, req) -> None:
        """Drop the key's leader slot iff ``req`` still owns it."""
        with self._lock:
            if self._leaders.get(key) is req:
                del self._leaders[key]

    def inflight(self) -> int:
        with self._lock:
            return len(self._leaders)
