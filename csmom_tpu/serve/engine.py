"""Serve scoring engines: vmapped jax kernels + a jax-free stub.

The per-endpoint scorers are NOT defined here (ISSUE 9): every endpoint
is a registered engine (:mod:`csmom_tpu.registry`), and this module is
the adapter that turns a registered :class:`~csmom_tpu.registry.core.
ServeSurface` into the two live backends:

``JaxEngine`` is the real thing: one jitted, vmapped entry per endpoint
(``serve_entry_fn``), shared process-wide through ``lru_cache`` exactly
like :mod:`csmom_tpu.compile.entries` — the same callable the
``compile/manifest.py`` ``serve`` profile lowers (via the registry's
serve feeder), so an AOT ``csmom warmup --profiles serve`` and a live
service compile byte-identical HLO and the serialized-executable cache
connects them.  Each micro-batch is ONE dispatch returning a
fixed-shape array; the engine never sees a shape outside the bucket
grid.  ``serve_entry_fn_donated`` is the registry's surface (b): the
same scorer with input buffers donated — for pipelines that own their
batch buffers (the service's request path does not: cached results
outlive the dispatch, so it keeps the plain variant).

Freshness accounting: ``warm()`` executes every (endpoint, bucket) shape
once and snapshots ``profiling.compile_stats``; ``fresh_compiles()`` is
the ``backend_compiles`` delta since that snapshot — an EXACT in-process
count of computations built during the serving window, which the SERVE
artifact records as ``in_window_fresh_compiles`` (0 by construction when
every dispatch stayed on the bucket grid).

``StubEngine`` scores with plain numpy (deterministic, jax-free) through
the registered stub factories: the queue/batcher/chaos plumbing is
engine-agnostic, so the fast rehearse tier and the plumbing tests drive
the stub and stay off jax entirely — the same split the chaos harness
makes between ``minibench`` and the real ``bench.py``.

jax imports stay inside the factories so importing this module costs
nothing jax-side.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from csmom_tpu.registry import serve_endpoints, serve_surface
from csmom_tpu.serve.buckets import BucketSpec

__all__ = ["JaxEngine", "MeshJaxEngine", "StubEngine", "make_engine",
           "serve_entry_fn", "serve_entry_fn_donated", "unpack_result"]


def _surface_or_raise(kind: str):
    try:
        return serve_surface(kind)
    except KeyError:
        raise ValueError(
            f"unknown endpoint {kind!r}: registered endpoints are "
            f"{serve_endpoints()}") from None


@lru_cache(maxsize=64)
def _jit_entry(surface, lookback: int, skip: int, n_bins: int, mode: str,
               donated: bool):
    """Process-shared jit cache, keyed on the SURFACE OBJECT (not the
    endpoint name): re-registering a name with a new surface must build
    a fresh scorer, never serve the old compiled one — while every
    caller resolving the same registered surface (the service, the
    manifest feeder, warmup) still shares one callable and lowers
    byte-identical HLO."""
    import jax

    one = surface.batch_fn(dict(lookback=lookback, skip=skip,
                                n_bins=n_bins, mode=mode))
    return jax.jit(jax.vmap(one),
                   donate_argnums=(0, 1) if donated else ())


def serve_entry_fn(kind: str, lookback: int, skip: int, n_bins: int,
                   mode: str):
    """The jitted batch scorer for one registered endpoint
    (process-shared).

    Signature (all endpoints): ``fn(values f[B, A, M], mask bool[B, A, M])``
    — one array pair in, one fixed-shape array out, so a micro-batch is
    a single dispatch.  Per-asset endpoints return ``f[B, A]`` (NaN
    where invalid/padded); summary endpoints (``backtest``) return
    ``f[B, len(summary_fields)]``.
    """
    return _jit_entry(_surface_or_raise(kind), lookback, skip, n_bins,
                      mode, False)


def serve_entry_fn_donated(kind: str, lookback: int, skip: int,
                           n_bins: int, mode: str):
    """Surface (b): the same scorer with the batch buffers donated —
    XLA may reuse the input HBM block for the output.  Callers must own
    (and surrender) their arrays; the service's request path keeps the
    plain variant because results and cached panels outlive a dispatch.
    """
    return _jit_entry(_surface_or_raise(kind), lookback, skip, n_bins,
                      mode, True)


def unpack_result(kind: str, out: np.ndarray, row: int, n_assets: int):
    """One request's result from a batch output, per the registered
    output spec: a read-only per-asset vector, or the summary dict."""
    surface = _surface_or_raise(kind)
    if surface.output == "summary":
        return {f: float(out[row, i])
                for i, f in enumerate(surface.summary_fields)}
    res = np.array(out[row, :n_assets])
    # ONE object may reach the cache, the leader, and every coalesced
    # follower: freeze it so no caller can mutate what another (or a
    # later cache hit) will read
    res.setflags(write=False)
    return res


class JaxEngine:
    """The compiled scoring backend (one dispatch per micro-batch)."""

    name = "jax"

    def __init__(self, lookback: int = 12, skip: int = 1, n_bins: int = 10,
                 mode: str = "rank"):
        self.lookback = lookback
        self.skip = skip
        self.n_bins = n_bins
        self.mode = mode
        self._stats0 = None

    def _fn(self, kind: str):
        return serve_entry_fn(kind, self.lookback, self.skip, self.n_bins,
                              self.mode)

    def warm(self, spec: BucketSpec) -> dict:
        """Execute every (endpoint, bucket) shape once, then snapshot the
        compile counters — everything after this snapshot is in-window."""
        import jax

        from csmom_tpu.obs import span
        from csmom_tpu.utils.profiling import compile_stats

        kinds = serve_endpoints()
        n = 0
        with span("serve.warmup", phase="warmup", spec=spec.name):
            for kind in kinds:
                fn = self._fn(kind)
                for B, A, M in spec.shapes():
                    v = np.zeros((B, A, M), np.dtype(spec.dtype))
                    m = np.zeros((B, A, M), bool)
                    jax.block_until_ready(fn(v, m))
                    n += 1
        self._stats0 = compile_stats()
        return {"n_shapes_warmed": n, "endpoints": list(kinds)}

    def score(self, kind: str, values: np.ndarray,
              mask: np.ndarray) -> np.ndarray:
        return np.asarray(self._fn(kind)(values, mask))

    def fresh_compiles(self):
        """Distinct computations backend-compiled since warm() — the
        in-window fresh-compile count (0 = every dispatch was warm)."""
        from csmom_tpu.utils.profiling import compile_stats

        if self._stats0 is None:
            return ("not measurable: engine was never warmed "
                    "(call warm() before serving)")
        return compile_stats().delta(self._stats0).backend_compiles


class MeshJaxEngine(JaxEngine):
    """The compiled scoring backend on a DEVICE MESH (ISSUE 10).

    Same contract as :class:`JaxEngine` — one dispatch per micro-batch,
    warm-before-serve, exact fresh-compile accounting — but every
    entry is the registry's sharded variant
    (:func:`csmom_tpu.mesh.variants.sharded_serve_entry_fn`): batch
    rows split across devices, or the asset axis for the per-asset-
    independent signals, per the partition-rule table.  Outputs are
    bitwise-equal to the single-device engine (pinned by
    ``tests/test_mesh.py``), so switching engines never changes a
    served number.

    ``devices=None`` resolves the worker's pinned slice
    (``CSMOM_MESH_DEVICE_SLICE``) or every visible device.  The warmed
    shape world is keyed by the device count — the ``serve-mesh``
    manifest profile enumerates it with ``.d<n>``-suffixed names.
    """

    name = "jax-mesh"

    def __init__(self, lookback: int = 12, skip: int = 1, n_bins: int = 10,
                 mode: str = "rank", devices=None):
        super().__init__(lookback=lookback, skip=skip, n_bins=n_bins,
                         mode=mode)
        self._devices = tuple(devices) if devices is not None else None

    def _fn(self, kind: str):
        # resolved per call, like JaxEngine._fn: the entry is a cheap
        # wrapper (the compiled programs live in the surface-keyed
        # _sharded_serve_jit cache), and re-resolving is what lets a
        # re-registered endpoint serve its NEW scorer here too
        from csmom_tpu.mesh.variants import sharded_serve_entry_fn

        return sharded_serve_entry_fn(
            kind, self.lookback, self.skip, self.n_bins, self.mode,
            devices=self._devices)

    def dispatch_shards(self, kind: str, batch_bucket: int,
                        asset_bucket: int) -> tuple:
        """``(devices, shards)`` for one bucket dispatch — the trace
        layer's per-dispatch mesh attribution (obs.trace).  XLA executes
        a sharded dispatch as ONE program, so the shard count is an
        attribute of the dispatch stage, not a separable wall; recording
        it per trace is what lets the decomposition CLI say which tails
        rode a partial split (a bucket axis that only divides 4 ways on
        8 devices)."""
        entry = self._fn(kind)
        return entry.n_devices, entry.shards_for_shape(batch_bucket,
                                                       asset_bucket)

    def mesh_info(self, spec=None) -> dict:
        """The topology evidence the SERVE artifact records: device
        count + each endpoint's axis placement and per-bucket shard
        counts (the d<n> world the warmup profile enumerated)."""
        from csmom_tpu.serve.buckets import bucket_spec

        spec = spec or bucket_spec("serve")
        info: dict = {"endpoints": {}}
        for kind in serve_endpoints():
            entry = self._fn(kind)
            info["devices"] = entry.n_devices
            info["endpoints"][kind] = {
                "axis": entry.axis,
                "shards": {f"b{B}@{A}": entry.shards_for_shape(B, A)
                           for B, A, _ in spec.shapes()},
            }
        return info

    def warm(self, spec) -> dict:
        # the scaling probe's single-device reference entry must compile
        # BEFORE the freshness snapshot super().warm takes, or the probe
        # itself would read as an in-window fresh compile
        import jax

        kind = self._probe_kind()
        B, A = spec.batch_buckets[-1], spec.asset_buckets[-1]
        v = np.zeros((B, A, spec.months), np.dtype(spec.dtype))
        m = np.zeros((B, A, spec.months), bool)
        jax.block_until_ready(
            serve_entry_fn(kind, self.lookback, self.skip, self.n_bins,
                           self.mode)(v, m))
        report = super().warm(spec)
        report["mesh"] = self.mesh_info(spec)
        return report

    @staticmethod
    def _probe_kind() -> str:
        return serve_endpoints()[0]

    def scaling_probe(self, spec, reps: int = 5) -> dict:
        """Single-device vs sharded dispatch wall at the largest bucket
        — the ``mesh_scaling_efficiency`` info row's measurement.  Both
        entries were warmed (see :meth:`warm`), so this never compiles
        inside the window; CPU host-platform devices share cores, so
        the number is honest about what THIS host delivers, not an ICI
        projection."""
        import jax

        from csmom_tpu.utils.deadline import mono_now_s

        kind = self._probe_kind()
        B, A = spec.batch_buckets[-1], spec.asset_buckets[-1]
        rng = np.random.default_rng(0)
        v = (100.0 * np.exp(np.cumsum(
            rng.normal(0, 0.03, (B, A, spec.months)), axis=2))
        ).astype(np.dtype(spec.dtype))
        m = np.ones((B, A, spec.months), bool)
        single = serve_entry_fn(kind, self.lookback, self.skip,
                                self.n_bins, self.mode)
        sharded = self._fn(kind)

        def best(fn):
            walls = []
            for _ in range(reps):
                t0 = mono_now_s()
                jax.block_until_ready(fn(v, m))
                walls.append(mono_now_s() - t0)
            return min(walls)

        t_single, t_sharded = best(single), best(sharded)
        # efficiency charges the shards the probe shape actually split
        # into — a bucket axis that only divides 4 ways on 8 devices
        # delivered a 4-way split, not an 8-way one
        shards = sharded.shards_for_shape(B, A)
        speedup = t_single / t_sharded if t_sharded > 0 else float("inf")
        return {
            "probe_endpoint": kind,
            "probe_shape": [B, A, spec.months],
            "single_device_dispatch_ms": round(1e3 * t_single, 3),
            "sharded_dispatch_ms": round(1e3 * t_sharded, 3),
            "devices": sharded.n_devices,
            "shards": shards,
            "speedup": round(speedup, 4),
            "scaling_efficiency": (round(speedup / shards, 4)
                                   if shards else None),
        }


class StubEngine:
    """Deterministic numpy scorer — the plumbing-test / rehearse engine.

    Shapes and NaN semantics mirror the jax engine via the registered
    stub factories; the numbers are a simplified model, which is fine:
    every consumer of the stub is testing the queue/batcher/chaos path,
    not signal values.
    """

    name = "stub"

    def __init__(self, lookback: int = 12, skip: int = 1, n_bins: int = 10,
                 mode: str = "rank"):
        self.lookback = lookback
        self.skip = skip
        self.n_bins = n_bins
        self.mode = mode
        self._fns: dict = {}  # per-engine-instance scorer cache

    def warm(self, spec: BucketSpec) -> dict:
        return {"n_shapes_warmed": 0,
                "endpoints": list(serve_endpoints()),
                "note": "stub engine: nothing to compile"}

    def score(self, kind: str, values: np.ndarray,
              mask: np.ndarray) -> np.ndarray:
        # build each stub scorer once per engine instance, not per
        # dispatch: the rehearse tier pushes thousands of micro-batches
        # through here and the factory closure is pure in (kind, params)
        fn = self._fns.get(kind)
        if fn is None:
            surface = _surface_or_raise(kind)
            fn = self._fns[kind] = surface.stub_fn(
                dict(lookback=self.lookback, skip=self.skip,
                     n_bins=self.n_bins, mode=self.mode))
        return fn(values, mask)

    def fresh_compiles(self) -> int:
        return 0  # nothing ever compiles: trivially warm


def make_engine(name: str, **kwargs):
    if name == "jax":
        return JaxEngine(**kwargs)
    if name == "jax-mesh":
        return MeshJaxEngine(**kwargs)
    if name == "stub":
        return StubEngine(**kwargs)
    raise ValueError(
        f"unknown engine {name!r}: use 'jax', 'jax-mesh', or 'stub'")
