"""Serve scoring engines: vmapped jax kernels + a jax-free stub.

``JaxEngine`` is the real thing: one jitted, vmapped entry per endpoint
(momentum / turnover / mini-backtest), shared process-wide through
``lru_cache`` exactly like :mod:`csmom_tpu.compile.entries` — the same
callable the ``compile/manifest.py`` ``serve`` profile lowers, so an AOT
``csmom warmup --profiles serve`` and a live service compile
byte-identical HLO and the serialized-executable cache connects them.
Each micro-batch is ONE dispatch returning a fixed-shape array; the
engine never sees a shape outside the bucket grid.

Freshness accounting: ``warm()`` executes every (endpoint, bucket) shape
once and snapshots ``profiling.compile_stats``; ``fresh_compiles()`` is
the ``backend_compiles`` delta since that snapshot — an EXACT in-process
count of computations built during the serving window, which the SERVE
artifact records as ``in_window_fresh_compiles`` (0 by construction when
every dispatch stayed on the bucket grid).

``StubEngine`` scores with plain numpy (deterministic, jax-free): the
queue/batcher/chaos plumbing is engine-agnostic, so the fast rehearse
tier and the plumbing tests drive the stub and stay off jax entirely —
the same split the chaos harness makes between ``minibench`` and the
real ``bench.py``.

jax imports stay inside ``JaxEngine`` so importing this module costs
nothing jax-side.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from csmom_tpu.serve.buckets import ENDPOINTS, BucketSpec

__all__ = ["ENDPOINTS", "JaxEngine", "StubEngine", "make_engine",
           "serve_entry_fn"]

# days constant the turnover stub shares with signals.turnover's ADV proxy
_TRADING_DAYS_PER_MONTH = 21.0


def _nanmean(a: np.ndarray, axis: int) -> np.ndarray:
    """All-NaN-slice-safe nanmean (np.nanmean warns on empty slices; a
    padded stub batch is full of them by design)."""
    ok = np.isfinite(a)
    c = ok.sum(axis=axis)
    s = np.where(ok, a, 0.0).sum(axis=axis)
    return np.where(c > 0, s / np.maximum(c, 1), np.nan)


@lru_cache(maxsize=32)
def serve_entry_fn(kind: str, lookback: int, skip: int, n_bins: int,
                   mode: str):
    """The jitted batch scorer for one endpoint (process-shared).

    Signature (all endpoints): ``fn(values f[B, A, M], mask bool[B, A, M])``
    — one array pair in, one fixed-shape array out, so a micro-batch is a
    single dispatch:

    - ``momentum``: ``f[B, A]`` — the (J, skip) compounded momentum at
      the last formation date, NaN where invalid/padded.
    - ``turnover``: ``f[B, A]`` — trailing-``lookback`` average turnover
      proxy (values = monthly share volume; the offline shares-unknown
      proxy, like ``csmom doublesort`` without ``--fetch-shares``).
    - ``backtest``: ``f[B, 2]`` — (mean_spread, ann_sharpe) of the full
      monthly decile backtest per request panel.
    """
    if kind not in ENDPOINTS:
        raise ValueError(f"unknown endpoint {kind!r}: use one of {ENDPOINTS}")
    import jax
    import jax.numpy as jnp

    if kind == "momentum":
        from csmom_tpu.signals.momentum import momentum

        def one(values, mask):
            mom, ok = momentum(values, mask, lookback=lookback, skip=skip)
            return jnp.where(ok[:, -1], mom[:, -1], jnp.nan)

    elif kind == "turnover":
        from csmom_tpu.signals.turnover import turnover_features

        def one(values, mask):
            shares = jnp.ones((values.shape[0],), values.dtype)
            turn, ok = turnover_features(
                values, mask, shares, lookback=lookback)["turn_avg"]
            return jnp.where(ok[:, -1], turn[:, -1], jnp.nan)

    else:  # backtest
        from csmom_tpu.backtest.monthly import monthly_spread_backtest

        def one(values, mask):
            res = monthly_spread_backtest(
                values, mask, lookback=lookback, skip=skip, n_bins=n_bins,
                mode=mode)
            return jnp.stack([res.mean_spread, res.ann_sharpe])

    return jax.jit(jax.vmap(one))


class JaxEngine:
    """The compiled scoring backend (one dispatch per micro-batch)."""

    name = "jax"

    def __init__(self, lookback: int = 12, skip: int = 1, n_bins: int = 10,
                 mode: str = "rank"):
        self.lookback = lookback
        self.skip = skip
        self.n_bins = n_bins
        self.mode = mode
        self._stats0 = None

    def _fn(self, kind: str):
        return serve_entry_fn(kind, self.lookback, self.skip, self.n_bins,
                              self.mode)

    def warm(self, spec: BucketSpec) -> dict:
        """Execute every (endpoint, bucket) shape once, then snapshot the
        compile counters — everything after this snapshot is in-window."""
        import jax

        from csmom_tpu.obs import span
        from csmom_tpu.utils.profiling import compile_stats

        n = 0
        with span("serve.warmup", phase="warmup", spec=spec.name):
            for kind in ENDPOINTS:
                fn = self._fn(kind)
                for B, A, M in spec.shapes():
                    v = np.zeros((B, A, M), np.dtype(spec.dtype))
                    m = np.zeros((B, A, M), bool)
                    jax.block_until_ready(fn(v, m))
                    n += 1
        self._stats0 = compile_stats()
        return {"n_shapes_warmed": n, "endpoints": list(ENDPOINTS)}

    def score(self, kind: str, values: np.ndarray,
              mask: np.ndarray) -> np.ndarray:
        return np.asarray(self._fn(kind)(values, mask))

    def fresh_compiles(self):
        """Distinct computations backend-compiled since warm() — the
        in-window fresh-compile count (0 = every dispatch was warm)."""
        from csmom_tpu.utils.profiling import compile_stats

        if self._stats0 is None:
            return ("not measurable: engine was never warmed "
                    "(call warm() before serving)")
        return compile_stats().delta(self._stats0).backend_compiles


class StubEngine:
    """Deterministic numpy scorer — the plumbing-test / rehearse engine.

    Shapes and NaN semantics mirror the jax engine; the numbers are a
    simplified model (no pad-parity forward fill), which is fine: every
    consumer of the stub is testing the queue/batcher/chaos path, not
    signal values.
    """

    name = "stub"

    def __init__(self, lookback: int = 12, skip: int = 1, n_bins: int = 10,
                 mode: str = "rank"):
        self.lookback = lookback
        self.skip = skip

    def warm(self, spec: BucketSpec) -> dict:
        return {"n_shapes_warmed": 0,
                "note": "stub engine: nothing to compile"}

    def score(self, kind: str, values: np.ndarray,
              mask: np.ndarray) -> np.ndarray:
        v = np.where(mask, values, np.nan)
        if kind == "momentum":
            end = v[:, :, -1 - self.skip]
            start = v[:, :, -1 - self.skip - self.lookback]
            with np.errstate(divide="ignore", invalid="ignore"):
                return end / start - 1.0
        if kind == "turnover":
            return (_nanmean(v[:, :, -self.lookback:], -1)
                    / _TRADING_DAYS_PER_MONTH)
        if kind == "backtest":
            with np.errstate(divide="ignore", invalid="ignore"):
                ret = v[:, :, 1:] / v[:, :, :-1] - 1.0
            mean = _nanmean(_nanmean(ret, 1), -1)
            return np.stack([np.nan_to_num(mean),
                             np.zeros_like(mean)], axis=-1)
        raise ValueError(f"unknown endpoint {kind!r}")

    def fresh_compiles(self) -> int:
        return 0  # nothing ever compiles: trivially warm


def make_engine(name: str, **kwargs):
    if name == "jax":
        return JaxEngine(**kwargs)
    if name == "stub":
        return StubEngine(**kwargs)
    raise ValueError(f"unknown engine {name!r}: use 'jax' or 'stub'")
