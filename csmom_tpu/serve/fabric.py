"""The horizontal serving fabric: three supervised process tiers.

r11–r17 ran ONE router inside the loadgen process talking unix sockets
to workers on the same host.  This module is the distribution round
(ROADMAP item 2): the router becomes its own supervised, REPLICATED
process tier, every hop can speak TCP, and the tiers share one
admission view through a published routes file::

    loadgen / client tier          FabricClient (this module)
        |  tcp/unix, framed proto      |  round-robin + failover
    router tier (>= 2 replicas)    python -m csmom_tpu.serve.router
        |  consistent-hash on the      |  hedged retries, fair gate
        |  result-cache key            |
    worker tier (N processes)      python -m csmom_tpu.serve.worker

Pieces:

- **Routes file** (:func:`write_routes` / :class:`RoutesView`): the
  shared admission view.  The worker supervisor's state — which workers
  are READY, at which addresses, plus the backoff-derived retry-after
  hint for a fully-parked fleet — is published as one atomically-
  replaced JSON file; every router replica mtime-polls it per pick, so
  all replicas route from the SAME view without any replica-to-replica
  protocol.  (Cross-host fabrics put the file on a shared mount or sync
  it; the transport for the view is deliberately boring.)
- **RoutesPublisher**: the thread that watches a
  :class:`~csmom_tpu.serve.supervisor.PoolSupervisor` and republishes
  the routes file when the fleet changes — a worker death propagates to
  every replica within one publish interval.
- **RouterSupervisor**: the worker supervisor's machinery (spawn,
  demonstrated-ready probe, exponential-backoff restart, crash-loop
  parking, rolling restart) pointed at router-replica processes — the
  two hooks :meth:`~csmom_tpu.serve.supervisor.PoolSupervisor
  ._slot_argv` and ``_slot_address`` are the entire difference.
- **FabricClient**: the client tier.  Submits over the wire to whichever
  replica is ready, fails over on a reset/killed replica (a router
  SIGKILL mid-burst costs its in-flight requests one retry against a
  surviving replica, never a lost request), keeps CLOSED client-side
  books (served + rejected + expired == admitted — the fabric's
  outermost ledger, the one a dead replica cannot take with it), and
  stitches three-tier traces from the reply halves.

Clock discipline: ``mono_now_s`` only (the serve tier contract).
Stdlib + numpy only — no jax in any fabric-control process.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import sys
import threading

import numpy as np

from csmom_tpu.serve import proto
from csmom_tpu.serve.router import (
    TERMINAL_STATES,
    _TERMINAL_GRACE_S as _ROUTER_TERMINAL_GRACE_S,
    no_deadline_score_give_up_s,
)
from csmom_tpu.serve.supervisor import PoolConfig, PoolSupervisor, \
    WorkerHandle
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["FabricClient", "FabricClientConfig", "FabricRequest",
           "RouterSupervisor", "RoutesPublisher", "RoutesView",
           "build_fabric", "kill_mid_burst", "stop_fabric",
           "write_routes"]

ROUTES_SCHEMA_VERSION = 1


# ---------------------------------------------------------------- routes ---

def write_routes(path: str, workers: list, retry_after_s: float | None,
                 cache_version: str | None = None) -> None:
    """Atomically publish the admission view: ``workers`` is a list of
    ``(worker_id, address)`` pairs (or dicts with those keys)."""
    rows = []
    for w in workers:
        if isinstance(w, dict):
            rows.append({"worker_id": w["worker_id"],
                         "addr": w["addr"]})
        else:
            rows.append({"worker_id": w[0], "addr": w[1]})
    obj = {
        "schema_version": ROUTES_SCHEMA_VERSION,
        "workers": rows,
        "retry_after_s": retry_after_s,
        "cache_version": cache_version,
    }
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


class _RouteWorker:
    """One routable worker row (duck-typed like a supervisor handle)."""

    __slots__ = ("worker_id", "socket_path")

    def __init__(self, worker_id: str, addr: str):
        self.worker_id = worker_id
        self.socket_path = addr


class RoutesView:
    """An mtime-cached reader of the published routes file.

    Every router pick calls :meth:`workers`; the file is re-parsed only
    when its mtime moved, so the per-pick cost is one ``stat``.  A
    missing or unparseable file reads as an EMPTY worker set with the
    reason carried — the router's no-worker rejection then says why.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._stat_sig: tuple | None = None
        self._workers: list = []
        self._retry_after: float | None = None
        self._cache_version: str | None = None
        self._reason: str | None = "routes file never read"

    def _refresh_locked(self) -> None:
        try:
            st = os.stat(self.path)
        except OSError as e:
            # a broken file invalidates the WHOLE view: a retry-after
            # hint or cache version surviving from the last good parse
            # would stamp outdated state onto every rejection
            self._workers = []
            self._retry_after = None
            self._cache_version = None
            self._reason = f"routes file unreadable: {e}"
            self._stat_sig = None
            return
        # mtime alone misses two publishes inside one filesystem tick;
        # the publisher lands every view via os.replace (a NEW inode),
        # so the inode is the signature that cannot lie
        sig = (st.st_mtime_ns, st.st_ino, st.st_size)
        if sig == self._stat_sig:
            return
        try:
            with open(self.path, encoding="utf-8") as f:
                obj = json.load(f)
            rows = obj.get("workers") or []
            self._workers = [_RouteWorker(str(r["worker_id"]),
                                          str(r["addr"]))
                             for r in rows]
            ra = obj.get("retry_after_s")
            self._retry_after = float(ra) if ra is not None else None
            self._cache_version = obj.get("cache_version")
            self._reason = None
            self._stat_sig = sig
        except (OSError, ValueError, KeyError, TypeError) as e:
            # a torn/garbage routes file must not crash the replica —
            # it degrades to "no workers" with the parse as the reason
            self._workers = []
            self._retry_after = None
            self._cache_version = None
            self._reason = f"routes file unparseable: {e}"
            self._stat_sig = None

    def workers(self) -> list:
        with self._lock:
            self._refresh_locked()
            return list(self._workers)

    def retry_after_s(self) -> float | None:
        with self._lock:
            self._refresh_locked()
            return self._retry_after

    def cache_version(self) -> str | None:
        with self._lock:
            self._refresh_locked()
            return self._cache_version

    def status(self) -> tuple:
        """``(ok, reason)`` — ok iff the routes file parses (an empty
        worker set is still a valid view: the fleet may be mid-restart,
        and the router's retry-after degradation handles it)."""
        with self._lock:
            self._refresh_locked()
            return self._reason is None, self._reason


class RoutesPublisher:
    """Watch a worker supervisor; republish the routes file on change.

    The published view is derived state (ready handles + backoff hint),
    so the publisher is a dumb loop: snapshot, compare, write-if-
    changed.  The retry-after hint is published only while NO worker is
    ready (it counts down continuously; publishing it while the fleet
    is healthy would churn the file every interval for nothing).
    """

    def __init__(self, supervisor: PoolSupervisor, path: str,
                 interval_s: float = 0.1):
        self.supervisor = supervisor
        self.path = path
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last: str | None = None
        self.publishes = 0

    def publish_once(self) -> bool:
        """One snapshot → write-if-changed; returns True when written."""
        ready = self.supervisor.ready_workers()
        hint = None if ready else self.supervisor.retry_after_s()
        snapshot = json.dumps({
            "workers": sorted((h.worker_id, h.socket_path) for h in ready),
            "retry_after_s": hint,
        }, sort_keys=True)
        if snapshot == self._last:
            return False
        write_routes(self.path,
                     [(h.worker_id, h.socket_path) for h in ready],
                     hint, self.supervisor.expect_cache_version)
        self._last = snapshot
        self.publishes += 1
        return True

    def start(self) -> "RoutesPublisher":
        self.publish_once()
        self._thread = threading.Thread(target=self._loop,
                                        name="csmom-routes-publisher",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.publish_once()
            except OSError:
                pass  # a transient write failure retries next interval
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


# ------------------------------------------------------ router supervisor ---

class RouterSupervisor(PoolSupervisor):
    """The supervisor machinery pointed at router-replica processes.

    Everything structural — spawn + demonstrated-ready probing (the
    replica's ``ready`` op answers once its routes file parses),
    exponential-backoff restarts with crash-loop parking, rolling
    restarts that swap the routable handle only after the replacement
    answered ready — is inherited from :class:`PoolSupervisor`; only
    WHAT runs in a slot and WHERE it listens differ.
    """

    slot_prefix = "r"

    def __init__(self, config: PoolConfig, run_dir: str, routes_path: str,
                 deadline_ms: float = 500.0, hedge_fraction: float = 0.35,
                 max_attempts: int = 3, fair_slots: int = 16,
                 affinity: bool = True, trace: bool = False):
        super().__init__(config, run_dir)
        self.routes_path = routes_path
        self.deadline_ms = deadline_ms
        self.hedge_fraction = hedge_fraction
        self.max_attempts = max_attempts
        self.fair_slots = fair_slots
        self.affinity = affinity
        self.trace = trace

    def _slot_argv(self, h: WorkerHandle) -> list:
        argv = [sys.executable, "-m", "csmom_tpu.serve.router",
                "--listen", h.socket_path,
                "--routes", self.routes_path,
                "--router-id", h.worker_id,
                "--profile", self.config.profile,
                "--deadline-ms", str(self.deadline_ms),
                "--hedge-fraction", str(self.hedge_fraction),
                "--max-attempts", str(self.max_attempts),
                "--fair-slots", str(self.fair_slots),
                "--expect-cache-version", self.expect_cache_version]
        if not self.affinity:
            argv.append("--no-affinity")
        if self.trace:
            argv.append("--trace")
        return argv

    def router_stats(self) -> list:
        """Per-replica stats (books, fair gate, trace snapshot when the
        replica armed tracing); a dead/parked replica contributes its
        handle state and reason — lost books are REPORTED, the client
        tier's accounting is the fabric's closed ledger."""
        out = []
        for h in self.handles:
            rec = {"router_id": h.worker_id, "state": h.state,
                   "generation": h.generation, "restarts": h.restarts,
                   "addr": h.socket_path}
            if h.state == "ready":
                try:
                    obj, _ = proto.request_once(h.socket_path, {"op": "stats"},
                                           timeout_s=5.0)
                    rec.update({
                        "accounting": obj.get("accounting"),
                        "classes": obj.get("classes"),
                        "availability": obj.get("availability"),
                        "fair_gate": obj.get("fair_gate"),
                        "channels": obj.get("channels"),
                        "invariant_violations":
                            obj.get("invariant_violations"),
                        "trace": obj.get("trace"),
                    })
                except (OSError, proto.ProtocolError) as e:
                    rec["stats_error"] = f"{type(e).__name__}: {e}"[:120]
            elif h.reason:
                rec["reason"] = h.reason[:300]
            out.append(rec)
        return out


# ------------------------------------------------------ bring-up/teardown ---

def build_fabric(wcfg: PoolConfig, rcfg: PoolConfig, run_dir: str, *,
                 deadline_ms: float, hedge_fraction: float = 0.35,
                 trace: bool = False, publisher_interval_s: float = 0.05,
                 client_deadline_s: float | None = None,
                 configure_router=None, fleet_config=None):
    """The three-tier bring-up, in the one order that works: worker
    supervisor first (the fleet the view describes), routes publisher
    (the admission view every replica reads), router supervisor (the
    replicas dial workers through the view), fabric client last.

    ``rcfg.expect_cache_version`` is threaded from the LIVE worker
    supervisor (the caller cannot know it before the workers exist).
    ``configure_router(rsup)`` runs after construction but BEFORE the
    replicas spawn — the hook tier-scoped chaos arming needs (the
    replicas are the processes that dial workers; the caller's own
    dials must not fire the fault).  A failed router start stops the
    already-running tiers before the error propagates.  Tear down with
    :func:`stop_fabric` — both CLI drivers and the rehearse runner
    share this sequencing so a fix to one cannot silently miss the
    others.  ``fleet_config`` (a :class:`~csmom_tpu.serve.fleet.
    FleetConfig`) arms the elastic tier: hot spares + autoscaler attach
    to the worker supervisor as ``wsup.fleet`` AFTER the routes
    publisher exists (a promotion is a routes publish away) and stop
    first on teardown via ``wsup.stop()``.  Returns
    ``(wsup, publisher, rsup, client)``.
    """
    wsup = PoolSupervisor(wcfg, os.path.join(run_dir, "workers"))
    os.makedirs(wsup.run_dir, exist_ok=True)
    wsup.start()
    # from here EVERY failure must stop the tiers already running — the
    # caller's locals are unassigned until we return, so a leak here is
    # a leak for the rest of the process
    publisher = rsup = None
    try:
        routes_path = os.path.join(run_dir, "routes.json")
        publisher = RoutesPublisher(wsup, routes_path,
                                    interval_s=publisher_interval_s).start()
        if fleet_config is not None and (
                fleet_config.spares > 0 or fleet_config.autoscale
                or fleet_config.prefork):
            from csmom_tpu.obs import fleet as obs_fleet
            from csmom_tpu.serve.fleet import FleetController

            FleetController(
                wsup, fleet_config, publisher=publisher,
                aggregator=obs_fleet.current_aggregator()).start()
        rcfg = dataclasses.replace(
            rcfg, expect_cache_version=wsup.expect_cache_version)
        rsup = RouterSupervisor(rcfg, os.path.join(run_dir, "routers"),
                                routes_path, deadline_ms=deadline_ms,
                                hedge_fraction=hedge_fraction, trace=trace)
        os.makedirs(rsup.run_dir, exist_ok=True)
        if configure_router is not None:
            configure_router(rsup)
        rsup.start()
        client = FabricClient(rsup.ready_workers, FabricClientConfig(
            default_deadline_s=client_deadline_s))
    except Exception:
        stop_fabric(publisher, rsup, wsup)
        raise
    return wsup, publisher, rsup, client


def stop_fabric(publisher, rsup, wsup) -> None:
    """Ordered teardown — every exit path must stop BOTH process tiers
    and the publisher: the elastic tier first (no promotion or scaling
    may race the teardown), then the publisher (stops must not churn
    the view), the router replicas, and the workers.  ``None`` slots
    are skipped; every tier stops even when an earlier stop raises."""
    fleet = getattr(wsup, "fleet", None)
    try:
        if fleet is not None:
            fleet.stop()
    finally:
        _stop_fabric_rest(publisher, rsup, wsup)


def _stop_fabric_rest(publisher, rsup, wsup) -> None:
    try:
        if publisher is not None:
            publisher.stop()
    finally:
        try:
            if rsup is not None:
                rsup.stop()
        finally:
            if wsup is not None:
                wsup.stop()


# --------------------------------------------------------- mid-burst kills ---

def kill_mid_burst(kills, settle_timeout_s: float = 60.0,
                   announce=None, poll_interval_s: float = 0.05) -> bool:
    """The rehearsed mid-burst kill ``concurrent`` hook (ISSUE 14):
    SIGKILL the first handle of each scheduled supervisor at its offset
    into the run, then poll every affected tier until the victim's
    replacement demonstrates ready (generation >= 1) —
    ``run_fabric_loadgen`` builds books only from a SETTLED fleet.

    ``kills`` rows are ``(after_s, supervisor, tier_label)``; rows with
    a falsy offset are dropped.  Sorted on the offset ALONE: tied
    offsets must not fall through to comparing supervisors (unorderable
    — a TypeError here would surface only after the whole load burst).
    ``announce`` is an optional ``callable(tier, victim_id, after_s)``
    for CLI chatter.  Returns True when every tier settled inside
    ``settle_timeout_s``.
    """
    kills = sorted(((after, sup, tier) for after, sup, tier in kills
                    if after), key=lambda k: k[0])
    pause = threading.Event()
    victims = []   # (sup, slot, generation at kill) — the slots to watch
    t0 = mono_now_s()
    for after_s, sup, tier in kills:
        delay = after_s - (mono_now_s() - t0)
        if delay > 0:
            pause.wait(delay)
        victim = sup.handles[0]
        victims.append((sup, 0, victim.generation))
        if announce is not None:
            announce(tier, victim.worker_id, after_s)
        sup.kill_worker(victim.worker_id)
    give_up = mono_now_s() + settle_timeout_s
    while mono_now_s() < give_up:
        # the VICTIM'S slot must advance past the killed generation and
        # demonstrate ready — any other handle already at generation >= 1
        # (an earlier warmup flake) must not count as settled
        if all(sup.handles[slot].generation > gen0
               and sup.handles[slot].state == "ready"
               for sup, slot, gen0 in victims):
            return True
        pause.wait(poll_interval_s)
    return False


# ----------------------------------------------------------------- client ---

@dataclasses.dataclass(frozen=True)
class FabricClientConfig:
    """Client-tier dispatch knobs."""

    default_deadline_s: float | None = 0.5
    connect_timeout_s: float = 2.0
    # how many DISTINCT router replicas one request may try before the
    # client settles it (a reset replica triggers an immediate failover)
    max_router_attempts: int = 3


_FABRIC_IDS = itertools.count(1)

# the terminal vocabulary and give-up budgets are the ROUTER's — one
# definition, imported, so the cross-tier "give up outermost-last"
# chain cannot be broken by editing a hand-rolled copy on one side
# (router.py's only fabric import is lazy, so no cycle)
_CLIENT_TERMINAL = TERMINAL_STATES
_TERMINAL_GRACE_S = _ROUTER_TERMINAL_GRACE_S


@dataclasses.dataclass
class FabricRequest:
    """One request's life-cycle record, client tier."""

    kind: str
    n_assets: int
    priority: str = "interactive"
    deadline_s: float | None = None      # ABSOLUTE monotonic
    panel_version: int | None = None
    req_id: int = dataclasses.field(
        default_factory=lambda: next(_FABRIC_IDS))
    state: str = "routing"
    result: object = None
    error: str | None = None
    router_id: str | None = None         # which replica answered
    worker_id: str | None = None         # which worker served it
    cache_hit: bool = False
    hedged: bool = False
    attempts: int = 0                    # router attempts (client tier)
    retry_after_s: float | None = None
    t_submit_s: float = 0.0
    t_done_s: float | None = None
    trace: object = dataclasses.field(default=None, repr=False,
                                      compare=False)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    @property
    def total_s(self) -> float | None:
        return (None if self.t_done_s is None
                else max(0.0, self.t_done_s - self.t_submit_s))

    def remaining_s(self, now_s: float) -> float | None:
        return (None if self.deadline_s is None
                else self.deadline_s - now_s)


class FabricClient:
    """The fabric's outermost tier: submit to router replicas, fail
    over on replica death, keep closed client-side books.

    The client is deliberately thin: no hedging (that is the routers'
    job, one tier down, where the worker menu lives), no queue — one
    thread per in-flight request doing one wire round trip per router
    attempt.  Replica choice is round robin over the READY set per
    attempt; a conn-reset/killed replica is excluded for the request's
    remaining attempts, so the failover converges on survivors.
    """

    def __init__(self, routers_fn, config: FabricClientConfig | None = None):
        """``routers_fn() -> list`` of handles with ``.worker_id`` and
        ``.socket_path`` — the router supervisor's READY set."""
        self.config = config or FabricClientConfig()
        self._routers_fn = routers_fn
        self._rr = itertools.count()
        self._lock = threading.Lock()
        # the persistent multiplexed transport (ISSUE 15): long-lived
        # channels to the router replicas — requests interleave on one
        # TCP_NODELAY stream per replica instead of dialing per submit
        self.channels = proto.ChannelPool(
            connect_timeout_s=self.config.connect_timeout_s)
        # shared score-header renderer — one implementation with the
        # router tier (proto.ScoreHeaderCache), no hand-synced copy
        self._headers = proto.ScoreHeaderCache()
        self.admitted = 0
        self.served = 0
        self.rejected = 0
        self.expired = 0
        self.rejected_infra = 0
        self.served_cache_hits = 0
        self.served_hedged = 0
        self.router_conn_failures = 0
        self.failovers = 0

    # --------------------------------------------------------------- admit

    def close(self) -> None:
        """Close the client's channels (teardown hygiene; safe while
        requests are settling — they reason-close into failover)."""
        self.channels.close()

    def submit(self, kind: str, values, mask,
               priority: str = "interactive",
               deadline_s: float | None = None,
               panel_version: int | None = None) -> FabricRequest:
        from csmom_tpu.obs import fleet as obs_fleet
        from csmom_tpu.obs import trace as obs_trace

        values = np.asarray(values)
        mask = np.asarray(mask, dtype=bool)
        n_assets = int(values.shape[0]) if values.ndim == 2 else 0
        rel = (self.config.default_deadline_s if deadline_s is None
               else deadline_s)
        now = mono_now_s()
        req = FabricRequest(
            kind=kind, n_assets=n_assets, priority=priority,
            deadline_s=None if rel is None else now + rel,
            panel_version=panel_version, t_submit_s=now,
            trace=obs_trace.begin(kind, priority,
                                  panel_version=panel_version))
        with self._lock:
            self.admitted += 1
        # fleet demand telemetry (no-op disarmed): the client tier is
        # open-loop, so offered == admitted here — the FLEET artifact's
        # demand book reconciles with accounting() BY SCHEMA
        obs_fleet.demand("offered", priority)
        obs_fleet.demand("admitted", priority)
        t = threading.Thread(
            target=self._drive, args=(req, values, mask),
            name=f"csmom-fabric-req-{req.req_id}", daemon=True)
        t.start()
        return req

    def _pick_router(self, exclude: set):
        routers = [r for r in self._routers_fn()
                   if r.worker_id not in exclude]
        if not routers:
            return None
        return routers[next(self._rr) % len(routers)]

    def _drive(self, req: FabricRequest, values, mask) -> None:
        tried: set = set()
        failures: list = []
        for attempt in range(self.config.max_router_attempts):
            now = mono_now_s()
            rem = req.remaining_s(now)
            if rem is not None and rem <= 0:
                self._terminate(req, "expired",
                                error="deadline expired before any router "
                                      "replica answered"
                                      + (f" (after: {'; '.join(failures)})"
                                         if failures else ""))
                return
            router = self._pick_router(tried)
            if router is None and tried:
                # every replica tried: widen back to the full ready set
                # (a replica that rejected honestly may still serve a
                # retry; a killed one is simply gone from the menu)
                tried = set()
                router = self._pick_router(tried)
            if router is None:
                self._terminate(req, "rejected", infra=True,
                                error="no ready router replica"
                                      + (f" ({'; '.join(failures[-2:])})"
                                         if failures else ""))
                return
            tried.add(router.worker_id)
            req.attempts += 1
            if attempt > 0:
                with self._lock:
                    self.failovers += 1
            header = self._headers.render(req.kind, req.priority,
                                          req.panel_version, req.req_id,
                                          rem, trace_ctx=req.trace)
            # a deadline-less attempt must outwait the ROUTER's own
            # terminal give-up (gate + dispatch + grace) — derived from
            # the same function _score uses, so the chain keeps giving
            # up outermost-last
            wait_budget = (rem if rem is not None
                           else no_deadline_score_give_up_s(
                               self.config.connect_timeout_s))
            timeout = (self.config.connect_timeout_s + wait_budget
                       + _TERMINAL_GRACE_S)
            t0 = mono_now_s()
            marks: dict = {}
            try:
                obj, arrays = self.channels.request(
                    router.socket_path, header,
                    arrays={"values": values, "mask": mask},
                    timeout_s=timeout, marks=marks)
            except (OSError, proto.ProtocolError) as e:
                # the replica died/reset mid-request (the rehearsed
                # router SIGKILL): its half of the trace is an orphan,
                # closed here with the reason; the request fails over
                with self._lock:
                    self.router_conn_failures += 1
                reason = (f"router connection failed "
                          f"({type(e).__name__}: {e})")[:160]
                if req.trace is not None:
                    req.trace.note_orphan(router.worker_id, reason)
                failures.append(f"{router.worker_id}: {reason}")
                continue
            t1 = mono_now_s()
            if self._settle_reply(req, router, obj, arrays, t0, t1,
                                  failures, marks=marks):
                return
        self._terminate(
            req, "rejected", infra=True,
            error=f"all {req.attempts} router attempt(s) failed: "
                  f"{'; '.join(failures[-3:])}"[:300])

    def _settle_reply(self, req: FabricRequest, router, obj: dict,
                      arrays: dict, t0: float, t1: float,
                      failures: list, marks: dict | None = None) -> bool:
        """Fold one router reply into the request; False = not settled
        (a draining replica's refusal fails over instead)."""
        marks = marks or {}
        window = (t0, t1, obj.get("router_id") or router.worker_id,
                  marks.get("t_acquired_s"), marks.get("t_sent_s"))
        state = obj.get("state")
        req.router_id = obj.get("router_id") or router.worker_id
        req.worker_id = obj.get("worker_id")
        ra = obj.get("retry_after_s")
        req.retry_after_s = float(ra) if ra is not None else None
        if state == "served":
            result = (obj.get("result_obj") if "result_obj" in obj
                      else arrays.get("result"))
            if result is not None and not isinstance(result, dict):
                result = np.asarray(result)[:req.n_assets]
            self._terminate(req, "served", result=result,
                            cache_hit=bool(obj.get("cache_hit")),
                            hedged=bool(obj.get("hedged")),
                            trace_half=obj.get("trace_half"),
                            attempt_window=window)
            return True
        err = str(obj.get("error") or "")
        if "router draining" in err:
            # a drain-stopping replica (rolling restart) is a routing
            # miss, not the request's fate — try a surviving replica.
            # Matched on the replica's OWN drain text only: the door's
            # no-ready-worker rejection also mentions "draining" and
            # must settle below, not fan the outage across every replica
            failures.append(f"{req.router_id}: {err}"[:160])
            return False
        if state not in _CLIENT_TERMINAL:
            state = "rejected"
        # an honest router answer (backpressure, expiry, unserveable) is
        # the request's fate — the replica had the full worker menu and
        # its own failover/hedging already; re-asking another replica
        # would double the load exactly when the fabric is saturated.
        # Infra classification rides the WIRE (the replica's own books
        # know why it rejected); the substring is only a fallback for
        # replies minted before the flag existed
        infra = bool(obj.get("infra")) or "no ready worker" in err
        self._terminate(req, state, error=obj.get("error"), infra=infra,
                        trace_half=obj.get("trace_half"),
                        attempt_window=window)
        return True

    # ------------------------------------------------------------ terminal

    def _terminate(self, req: FabricRequest, state: str, result=None,
                   error: str | None = None, infra: bool = False,
                   cache_hit: bool = False, hedged: bool = False,
                   trace_half: dict | None = None,
                   attempt_window: tuple | None = None) -> None:
        with self._lock:
            if req.state in _CLIENT_TERMINAL:
                return
            req.state = state
            req.result = result
            if error is not None:
                req.error = str(error)
            req.t_done_s = mono_now_s()
            if state == "served":
                self.served += 1
                if cache_hit:
                    req.cache_hit = True
                    self.served_cache_hits += 1
                if hedged:
                    req.hedged = True
                    self.served_hedged += 1
            elif state == "expired":
                self.expired += 1
            else:
                self.rejected += 1
                if infra:
                    self.rejected_infra += 1
            if req.trace is not None:
                if trace_half is not None and attempt_window is not None:
                    ta0, ta1, rid = attempt_window[:3]
                    acq, sent = (attempt_window[3:5]
                                 if len(attempt_window) >= 5
                                 else (None, None))
                    req.trace.absorb_remote(trace_half, ta0, ta1,
                                            worker_id=rid,
                                            t_acquired_s=acq,
                                            t_sent_s=sent)
                req.trace.close_routed(state, req.t_done_s, reason=error)
            req._done.set()
        if state == "served":
            from csmom_tpu.obs import fleet as obs_fleet

            obs_fleet.demand("served", req.priority)

    # ---------------------------------------------------------- accounting

    def accounting(self) -> dict:
        with self._lock:
            return {
                "admitted": self.admitted,
                "served": self.served,
                "rejected": self.rejected,
                "expired": self.expired,
                "rejected_infra": self.rejected_infra,
                "served_cache_hits": self.served_cache_hits,
                "served_hedged": self.served_hedged,
                "router_conn_failures": self.router_conn_failures,
                "failovers": self.failovers,
            }

    def availability(self) -> float:
        """``1 - rejected_infra / admitted`` at the CLIENT tier: the
        fraction of admitted requests the fabric answered honestly,
        through every replica death and partition it absorbed."""
        a = self.accounting()
        if not a["admitted"]:
            return 1.0
        return round(1.0 - a["rejected_infra"] / a["admitted"], 6)

    def invariant_violations(self) -> list:
        a = self.accounting()
        out = []
        total = a["served"] + a["rejected"] + a["expired"]
        if total != a["admitted"]:
            out.append(
                f"fabric client accounting broken: served {a['served']} + "
                f"rejected {a['rejected']} + expired {a['expired']} = "
                f"{total} != admitted {a['admitted']}")
        if a["rejected_infra"] > a["rejected"]:
            out.append("rejected_infra exceeds rejected")
        if a["served_cache_hits"] > a["served"]:
            out.append("served_cache_hits exceeds served")
        return out
