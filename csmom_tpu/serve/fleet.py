"""Elastic fleet controller: hot spares, fast warm path, autoscaling.

The tier ABOVE :mod:`csmom_tpu.serve.supervisor` (ISSUE 20).  r20's
observatory priced the problem: a SIGKILLed worker costs
``fleet_kill_window_capacity_loss_frac`` 0.3333 while its replacement
re-warms — 23.1 s cold, 6.5 s even off the AOT cache — and the
per-class demand series sat unused.  Tail at Scale's answer is to pay
for capacity BEFORE the outage, not during it:

- **Hot spares** (:class:`FleetController`): N pre-spawned,
  demonstrated-ready workers parked OUT of the hash ring and the routes
  file.  On a worker death the controller's death hook promotes a spare
  into the victim's slot — swap the handle, publish routes — so the
  kill costs one failover instead of a re-warm window.  The pool
  backfills off the hot path.  Spares live in the supervisor's event
  book under ``spare_*`` names: serving consumers (capacity kill
  windows, lifecycle walls, the router's ready set) filter by event
  name, so spares are held out of the serving books BY SCHEMA, and the
  capacity account credits a parked spare as warm reserve
  (:func:`csmom_tpu.obs.fleet.capacity_account`).
- **Fast warm path** (:class:`PreforkServer`): a forkserver-style
  prefork parent (``python -m csmom_tpu.serve.fleet``) with the serve
  stack — and, for jax engines, the jax *module* — pre-imported, plus a
  page-cache prewarm pass over the serialized-executable cache so a
  forked child's AOT loads hit warm pages.  The parent NEVER
  initializes the accelerator backend (initializing XLA before fork is
  unsafe); children do that during their own warmup, off a warm import
  graph.  Spawn/poll run over one-shot lifecycle ops; the parent's
  accept loop is single-threaded so ``fork`` happens with exactly one
  thread alive.
- **Demand-driven autoscaler** (:class:`AutoscalerPolicy` +
  controller loop): a control loop reading the FleetAggregator's
  per-class demand series (``demand_recent_rps``), hysteresis-banded
  with sustain and cooldown so bursty schedules don't thrash, growing /
  shrinking the fleet within declared floors/ceilings and auto-tuning
  the r13 static SLO-class quotas (``tune_quota`` worker op →
  ``AdmissionQueue.retune_quota``).  Every decision — including the
  reasoned no-ops — lands in the closed-world ``fleet.elastic``
  artifact block with a reason.

Clock discipline: monotonic only (``analysis/rules.py`` pins this
module mono-only — promotion walls and scaling decisions must never
jump with wall-clock adjustments).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import threading

from csmom_tpu.serve import health, proto
from csmom_tpu.serve.supervisor import WorkerHandle
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["FleetConfig", "FleetController", "AutoscalerPolicy",
           "PreforkServer"]


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Everything the elastic tier needs, with declared bounds."""

    spares: int = 0                    # hot spares held in reserve
    prefork: bool = False              # spawn via the prefork parent
    autoscale: bool = False            # arm the demand control loop
    poll_interval_s: float = 0.2       # spare monitor cadence
    spare_ready_timeout_s: float = 120.0
    # -- autoscaler (hysteresis band on offered rps per ready worker) --
    autoscale_interval_s: float = 0.5
    demand_horizon_s: float = 2.0      # trailing window the rate reads
    high_rps_per_worker: float = 200.0
    low_rps_per_worker: float = 5.0
    sustain_s: float = 1.5             # band breach must persist this long
    cooldown_s: float = 5.0            # dead time after any action
    min_workers: int = 1               # declared floor (never shrink past)
    max_workers: int = 8               # declared ceiling (never grow past)
    # -- SLO-class quota auto-tune (bulk is the only quota'd class) -----
    quota_class: str = "bulk"
    quota_floor_rps: float = 8.0
    quota_ceiling_rps: float = 64.0
    quota_headroom: float = 1.25       # quota = headroom × offered rate
    quota_min_rel_change: float = 0.25  # retune only past this delta


# ------------------------------------------------------------- prefork ----

_PREFORK_DEFAULT_IMPORTS = "csmom_tpu.serve.worker"


class _PreforkChild:
    """Duck-typed ``subprocess.Popen`` stand-in for a forked worker.

    The supervisor only ever touches ``pid`` / ``poll`` / ``wait`` /
    ``terminate`` / ``kill`` / ``returncode``.  ``poll`` asks the
    prefork PARENT (``waitpid`` with cached statuses) because probing a
    zombie with ``os.kill(pid, 0)`` succeeds — the one bug that would
    make a dead child read alive forever.  If the parent itself is
    gone, the child was reparented to init (which reaps), so the signal
    probe becomes truthful and we fall back to it.
    """

    def __init__(self, pid: int, control_address: str):
        self.pid = pid
        self._address = control_address
        self.returncode: int | None = None

    def _probe_parent(self) -> dict:
        """One-shot liveness probe of the forked child via the prefork
        parent's control socket — a fresh dial per probe is the point
        (the control socket is never a request path)."""
        obj, _ = proto.request_once(
            self._address, {"op": "poll", "pid": self.pid},
            timeout_s=2.0)
        return obj

    def poll(self) -> int | None:
        if self.returncode is not None:
            return self.returncode
        try:
            # `poll` is the Popen contract name and cannot be renamed;
            # the dial lives in the probe-named helper above
            rc = self._probe_parent().get("returncode")
            if rc is not None:
                self.returncode = int(rc)
        except (OSError, proto.ProtocolError):
            # parent gone: init owns the child now, the probe is honest
            try:
                os.kill(self.pid, 0)
            except ProcessLookupError:
                self.returncode = -1  # exited; true rc reaped by init
            except PermissionError:
                pass
        return self.returncode

    def wait(self, timeout: float | None = None) -> int:
        give_up = None if timeout is None else mono_now_s() + timeout
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if give_up is not None and mono_now_s() >= give_up:
                raise subprocess.TimeoutExpired("prefork-child", timeout)
            threading.Event().wait(0.05)

    def _signal(self, sig) -> None:
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)


class PreforkServer:
    """The prefork parent process (``python -m csmom_tpu.serve.fleet``).

    Single-threaded by construction: one accept loop, lifecycle ops
    handled inline, ``fork`` with exactly one thread alive.  Ops:

    - ``ping``    → liveness + what got pre-imported / prewarmed
    - ``spawn``   → fork; child redirects stdio to the requested log,
      applies env overrides, and runs ``serve.worker.main(argv)``
    - ``poll``    → ``waitpid(WNOHANG)`` with cached exit statuses
    - ``shutdown`` → reply, close the listener, exit the loop

    The parent never initializes an accelerator backend; it imports
    modules and touches cache FILES (page-cache prewarm) only.
    """

    def __init__(self, address: str, preimport: str = "",
                 prewarm_dir: str = ""):
        self.address = address
        self.preimport = [m for m in preimport.split(",") if m]
        self.prewarm_dir = prewarm_dir
        self.imported: list = []
        self.prewarmed_bytes = 0
        self.prewarmed_files = 0
        self._children: dict = {}   # pid -> returncode | None
        self._listener = None
        self._stopping = False

    # ------------------------------------------------------------ warmup

    def warm(self) -> None:
        import importlib

        for mod in self.preimport:
            try:
                importlib.import_module(mod)
                self.imported.append(mod)
            except Exception as e:  # a missing engine dep must not kill
                self.imported.append(f"{mod}!{type(e).__name__}")
        if self.prewarm_dir and os.path.isdir(self.prewarm_dir):
            self._prewarm(self.prewarm_dir)

    def _prewarm(self, root: str, budget_bytes: int = 1 << 29) -> None:
        """Fault the serialized-executable cache into the page cache so
        every forked child's AOT load is an mmap of warm pages, not a
        cold disk read (best-effort, bounded)."""
        for dirpath, _dirs, files in os.walk(root):
            for name in files:
                if self.prewarmed_bytes >= budget_bytes:
                    return
                path = os.path.join(dirpath, name)
                try:
                    with open(path, "rb") as f:
                        while f.read(1 << 20):
                            pass
                    self.prewarmed_bytes += os.path.getsize(path)
                    self.prewarmed_files += 1
                except OSError:
                    continue

    # -------------------------------------------------------------- ops

    def _op_spawn(self, obj: dict) -> dict:
        argv = list(obj.get("argv") or [])
        log_path = obj.get("log_path")
        env = obj.get("env") or {}
        pid = os.fork()
        if pid == 0:
            # the child: shed the parent's sockets, point stdio at the
            # slot log, then BECOME the worker (never return)
            try:
                if self._listener is not None:
                    self._listener.close()
                if log_path:
                    fd = os.open(log_path,
                                 os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                                 0o644)
                    os.dup2(fd, 1)
                    os.dup2(fd, 2)
                    os.close(fd)
                os.environ.update({str(k): str(v) for k, v in env.items()})
                from csmom_tpu.serve import worker as worker_mod

                rc = worker_mod.main(argv)
            except SystemExit as e:
                rc = (e.code if isinstance(e.code, int)
                      else 0 if e.code is None else 1)
            except BaseException:
                rc = 70  # EX_SOFTWARE: the child must never unwind into
                #          the parent's stack
            os._exit(int(rc) & 0xFF)
        self._children[pid] = None
        return {"state": "ok", "pid": pid}

    def _op_poll(self, obj: dict) -> dict:
        pid = int(obj.get("pid", -1))
        rc = self._children.get(pid)
        if rc is None and pid in self._children:
            try:
                done, status = os.waitpid(pid, os.WNOHANG)
                if done == pid:
                    rc = (os.WEXITSTATUS(status) if os.WIFEXITED(status)
                          else -os.WTERMSIG(status))
                    self._children[pid] = rc
            except ChildProcessError:
                rc = -1  # not ours / already reaped: report exited
                self._children[pid] = rc
        return {"state": "ok", "returncode": rc}

    def handle(self, obj: dict) -> dict:
        op = obj.get("op")
        if op == "ping":
            return {"state": "ok", "pid": os.getpid(),
                    "imported": list(self.imported),
                    "prewarmed_bytes": self.prewarmed_bytes,
                    "prewarmed_files": self.prewarmed_files,
                    "children": len(self._children)}
        if op == "spawn":
            return self._op_spawn(obj)
        if op == "poll":
            return self._op_poll(obj)
        if op == "shutdown":
            self._stopping = True
            return {"state": "ok"}
        return {"state": "rejected", "error": f"unknown op {op!r}"}

    # ------------------------------------------------------------- loop

    def run(self) -> int:
        self._listener = proto.listen(self.address)
        self._listener.settimeout(0.25)
        try:
            while not self._stopping:
                try:
                    conn, _addr = self._listener.accept()
                except TimeoutError:
                    continue
                except OSError:
                    break
                try:
                    conn.settimeout(5.0)
                    msg = proto.recv_msg(conn, deadline_s=5.0)
                    if msg is None:
                        continue
                    obj, _arrays = msg
                    obj.pop("_mux", None)
                    proto.send_msg(conn, self.handle(obj))
                except (OSError, proto.ProtocolError):
                    pass  # a broken client must not kill the parent
                finally:
                    try:
                        conn.close()
                    except OSError:
                        pass
        finally:
            try:
                self._listener.close()
            except OSError:
                pass
            proto.unlink_address(self.address)
        return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="csmom-prefork",
        description="forkserver-style prefork parent for serve workers")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--preimport", default=_PREFORK_DEFAULT_IMPORTS,
                    help="comma-separated modules to import before "
                         "serving forks (never initializes a backend)")
    ap.add_argument("--prewarm-dir", default="",
                    help="AOT cache directory to fault into the page "
                         "cache before the first fork")
    args = ap.parse_args(argv)
    srv = PreforkServer(args.socket, preimport=args.preimport,
                        prewarm_dir=args.prewarm_dir)
    srv.warm()
    return srv.run()


# ---------------------------------------------------------- autoscaler ----

class AutoscalerPolicy:
    """Pure hysteresis-banded scaling policy (no clocks, no I/O).

    ``decide(now_s, offered_rps, n_ready)`` returns one reasoned
    decision dict per tick: ``scale_up`` / ``scale_down`` / ``hold``,
    always with a human-readable ``reason``.  A band breach must
    SUSTAIN (``sustain_s``) before it acts, every action starts a
    cooldown, and the floor/ceiling are hard bounds — three separate
    guards against thrash on bursty schedules.  The clock is an
    argument (the TokenBucket idiom), so tests drive synthetic demand
    series without sleeping.
    """

    def __init__(self, *, high_rps_per_worker: float,
                 low_rps_per_worker: float, sustain_s: float,
                 cooldown_s: float, min_workers: int, max_workers: int):
        if low_rps_per_worker >= high_rps_per_worker:
            raise ValueError("hysteresis band inverted: low >= high")
        self.high = float(high_rps_per_worker)
        self.low = float(low_rps_per_worker)
        self.sustain_s = float(sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self._above_since: float | None = None
        self._below_since: float | None = None
        self._cooldown_until: float | None = None

    def _decision(self, now_s, action, reason, rps, n_ready) -> dict:
        return {"t_s": round(float(now_s), 4), "action": action,
                "reason": reason, "offered_rps": round(float(rps), 3),
                "n_ready": int(n_ready)}

    def decide(self, now_s: float, offered_rps: float,
               n_ready: int) -> dict:
        per = offered_rps / max(1, n_ready)
        mk = lambda a, r: self._decision(now_s, a, r, offered_rps, n_ready)  # noqa: E731
        if self._cooldown_until is not None:
            if now_s < self._cooldown_until:
                return mk("hold", f"cooldown: {self._cooldown_until - now_s:.1f}s "
                                  "until the last action's dead time ends")
            self._cooldown_until = None
        if per > self.high:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now_s
            held = now_s - self._above_since
            if held < self.sustain_s:
                return mk("hold", f"{per:.1f} rps/worker above high "
                                  f"watermark {self.high:.0f}, sustaining "
                                  f"({held:.1f}/{self.sustain_s:.1f}s)")
            self._above_since = None
            if n_ready >= self.max_workers:
                return mk("hold", f"sustained burst ({per:.1f} rps/worker) "
                                  f"but at declared ceiling "
                                  f"{self.max_workers} workers")
            self._cooldown_until = now_s + self.cooldown_s
            return mk("scale_up", f"{per:.1f} rps/worker > high watermark "
                                  f"{self.high:.0f} sustained "
                                  f"{self.sustain_s:.1f}s")
        if per < self.low:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now_s
            held = now_s - self._below_since
            if held < self.sustain_s:
                return mk("hold", f"{per:.1f} rps/worker below low "
                                  f"watermark {self.low:.0f}, sustaining "
                                  f"({held:.1f}/{self.sustain_s:.1f}s)")
            self._below_since = None
            if n_ready <= self.min_workers:
                return mk("hold", f"drained ({per:.1f} rps/worker) but at "
                                  f"declared floor {self.min_workers} "
                                  "workers")
            self._cooldown_until = now_s + self.cooldown_s
            return mk("scale_down", f"{per:.1f} rps/worker < low watermark "
                                    f"{self.low:.0f} sustained "
                                    f"{self.sustain_s:.1f}s")
        self._above_since = self._below_since = None
        return mk("hold", f"{per:.1f} rps/worker inside hysteresis band "
                          f"[{self.low:.0f}, {self.high:.0f}]")


# ---------------------------------------------------------- controller ----

class FleetController:
    """Owns the spare pool, the promotion seam, and the control loop.

    Attaches to a running :class:`PoolSupervisor` as ``wsup.fleet`` and
    registers a death hook.  All spare lifecycle lands in the
    SUPERVISOR's event book under ``spare_*`` names, so the existing
    plumbing (``summary()["events"]`` → ``absolute_events`` → the FLEET
    artifact) carries it with zero new channels — and the serving
    consumers, which filter by event name, never see a spare.
    """

    def __init__(self, wsup, config: FleetConfig, publisher=None,
                 aggregator=None):
        self.wsup = wsup
        self.config = config
        self.publisher = publisher    # RoutesPublisher | None (pool mode)
        self.aggregator = aggregator  # FleetAggregator | None
        self.spares: list = []        # parked WorkerHandle's, NOT in wsup
        self.promotions: list = []
        self.promotions_missed = 0
        self.decisions: list = []
        self.quota_applied: list = []
        self.counts = {"spawned": 0, "ready": 0, "promoted": 0,
                       "backfills": 0, "died_parked": 0}
        self._all_spare_ids: list = []
        self._spare_seq = 0
        self._lock = threading.Lock()
        self._backfill_lock = threading.Lock()
        self._stop = threading.Event()
        self._loop_thread: threading.Thread | None = None
        self._prefork_proc = None
        self._prefork_address: str | None = None
        self._policy = AutoscalerPolicy(
            high_rps_per_worker=config.high_rps_per_worker,
            low_rps_per_worker=config.low_rps_per_worker,
            sustain_s=config.sustain_s, cooldown_s=config.cooldown_s,
            min_workers=config.min_workers,
            max_workers=config.max_workers) if config.autoscale else None
        self._quota_current: float | None = None
        self._quota_cooldown_until: float | None = None
        self._last_hold_reason: str | None = None

    # ------------------------------------------------------------ prefork

    def _start_prefork(self) -> None:
        self._prefork_address = os.path.join(self.wsup.run_dir,
                                             "prefork.sock")
        prewarm = ""
        try:
            from csmom_tpu.utils.jit_cache import cache_dir

            prewarm = cache_dir(self.wsup.config.cache_subdir) or ""
        except Exception:
            pass
        argv = [sys.executable, "-m", "csmom_tpu.serve.fleet",
                "--socket", self._prefork_address]
        if prewarm:
            argv += ["--prewarm-dir", prewarm]
        log_path = os.path.join(self.wsup.run_dir, "prefork.log")
        log = open(log_path, "ab")
        try:
            self._prefork_proc = subprocess.Popen(
                argv, stdout=log, stderr=log, env=self.wsup._spawn_env())
        finally:
            log.close()
        give_up = mono_now_s() + 60.0
        last_err = "never pinged"
        while mono_now_s() < give_up:
            if self._prefork_proc.poll() is not None:
                last_err = f"exited rc={self._prefork_proc.returncode}"
                break
            try:
                obj = self._probe_prefork()
                if obj.get("state") == "ok":
                    self.wsup._event(
                        "prefork_ready", "prefork",
                        imported=obj.get("imported"),
                        prewarmed_bytes=obj.get("prewarmed_bytes"))
                    return
            except (OSError, proto.ProtocolError) as e:
                last_err = f"{type(e).__name__}: {e}"[:120]
            self._stop.wait(0.1)
        # fall back to plain Popen spawns rather than fail the fleet
        self.wsup._event("prefork_failed", "prefork", reason=last_err)
        self._stop_prefork()

    def _probe_prefork(self) -> dict:
        """One-shot readiness probe of the prefork parent (fresh dial
        by design: the control socket is not a request path)."""
        obj, _ = proto.request_once(self._prefork_address,
                                    {"op": "ping"}, timeout_s=2.0)
        return obj

    def _prefork_admin(self, obj: dict, timeout_s: float = 2.0) -> dict:
        """One-shot admin op (spawn/shutdown) to the prefork parent —
        fresh dial by design, same rationale as :meth:`_probe_prefork`."""
        out, _ = proto.request_once(self._prefork_address, obj,
                                    timeout_s=timeout_s)
        return out

    def _stop_prefork(self) -> None:
        proc, self._prefork_proc = self._prefork_proc, None
        if proc is None:
            return
        try:
            self._prefork_admin({"op": "shutdown"})
        except (OSError, proto.ProtocolError):
            pass
        try:
            proc.wait(timeout=3.0)
        except subprocess.TimeoutExpired:
            proc.kill()

    # ------------------------------------------------------------- spares

    def _spawn_spare(self, kind: str = "spare") -> WorkerHandle | None:
        """Spawn + demonstrated-ready probe one spare (blocking).  The
        spare is a full worker on its own socket; it just never enters
        the routes file until promoted."""
        with self._lock:
            seq = self._spare_seq
            self._spare_seq += 1
        sid = f"s{seq}"
        h = WorkerHandle(
            slot=-1, worker_id=sid,
            socket_path=self.wsup._slot_address(
                self.wsup.config.n_workers + seq))
        h.log_path = os.path.join(self.wsup.run_dir, f"{sid}.g0.log")
        h.spawn_kind = "spare"
        argv = self.wsup._worker_argv(h)
        t_spawn = mono_now_s()
        spawned_via = "popen"
        if self._prefork_proc is not None \
                and self._prefork_proc.poll() is None:
            try:
                obj = self._prefork_admin(
                    {"op": "spawn", "argv": argv[3:],
                     "log_path": h.log_path}, timeout_s=5.0)
                if obj.get("state") == "ok":
                    h.proc = _PreforkChild(int(obj["pid"]),
                                           self._prefork_address)
                    spawned_via = "prefork"
            except (OSError, proto.ProtocolError):
                pass
        if h.proc is None:
            log = open(h.log_path, "ab")
            try:
                h.proc = subprocess.Popen(argv, stdout=log, stderr=log,
                                          env=self.wsup._spawn_env())
            finally:
                log.close()
        h.t_spawned_s = t_spawn
        with self._lock:
            self.counts["spawned"] += 1
            self._all_spare_ids.append(sid)
        self.wsup._event("spare_spawn", sid, pid=h.proc.pid, via=spawned_via,
                         kind=kind)
        give_up = t_spawn + self.config.spare_ready_timeout_s
        while mono_now_s() < give_up and not self._stop.is_set():
            rc = h.proc.poll()
            if rc is not None:
                self.wsup._event("spare_death", sid, rc=rc, phase="starting")
                with self._lock:
                    self.counts["died_parked"] += 1
                return None
            report = health.readiness(h.socket_path, timeout_s=2.0)
            if report.get("ok"):
                h.state = "ready"
                h.t_ready_s = mono_now_s()
                h.ready_report = report
                with self._lock:
                    self.counts["ready"] += 1
                self.wsup._event(
                    "spare_ready", sid, via=spawned_via,
                    fresh_compiles=report.get("fresh_compiles"),
                    wall_s=round(h.t_ready_s - t_spawn, 3),
                    walls=report.get("walls"))
                return h
            self._stop.wait(self.wsup.config.poll_interval_s)
        self.wsup._event("spare_ready_timeout", sid)
        self.wsup._reap(h)
        return None

    def _fill_pool(self, target: int, kind: str) -> None:
        """Grow the parked pool to ``target`` ready spares (serialized
        by the backfill lock so racing deaths don't double-spawn)."""
        with self._backfill_lock:
            while not self._stop.is_set():
                with self._lock:
                    if len(self.spares) >= target:
                        return
                # the backfill lock EXISTS to serialize slow spawns —
                # nothing on a request path ever contends it (only the
                # fill/backfill threads), so blocking under it is the
                # design, not a hidden wait
                # lint: allow[lock-order] backfill lock serializes slow spawns by design
                h = self._spawn_spare(kind=kind)
                if h is None:
                    return  # spawn/probe failed: stay short rather than
                    #         hot-spin a spawn that just demonstrated failure
                with self._lock:
                    self.spares.append(h)

    def _backfill_async(self) -> None:
        with self._lock:
            self.counts["backfills"] += 1
        self.wsup._event("spare_backfill", "fleet",
                         pool=len(self.spares),
                         target=self.config.spares)
        threading.Thread(target=self._fill_pool,
                         args=(self.config.spares, "backfill"),
                         name="csmom-fleet-backfill", daemon=True).start()

    # ---------------------------------------------------------- promotion

    def _on_worker_death(self, victim: WorkerHandle, t_kill: float) -> bool:
        """The supervisor's death hook: promote a parked spare into the
        victim's slot.  Returns True when the death is CLAIMED (no
        backoff re-warm); False hands the slot back to the supervisor's
        normal machinery (no spare left, or the spare was dead too)."""
        if self._stop.is_set():
            return False
        while True:
            with self._lock:
                spare = None
                for i, s in enumerate(self.spares):
                    if s.state == "ready":
                        spare = self.spares.pop(i)
                        break
            if spare is None:
                with self._lock:
                    self.promotions_missed += 1
                self.wsup._event("spare_promotion_missed",
                                 victim.worker_id,
                                 reason="no ready spare parked")
                return False
            # demonstrated-ready at promotion time, not just at spawn: a
            # spare that died parked must fall through to the next one
            if spare.proc.poll() is not None \
                    or not health.readiness(spare.socket_path,
                                            timeout_s=2.0).get("ok"):
                self.wsup._event("spare_death", spare.worker_id,
                                 rc=spare.proc.poll(), phase="parked")
                with self._lock:
                    self.counts["died_parked"] += 1
                continue
            break
        t0 = self.wsup.t0_mono_s
        with self._lock:
            victim.proc = spare.proc
            victim.socket_path = spare.socket_path
            victim.log_path = spare.log_path
            victim.generation += 1
            victim.spawn_kind = "spare-promotion"
            victim.restarts = 0
            victim.t_spawned_s = t_kill
            victim.t_ready_s = mono_now_s()
            victim.ready_report = spare.ready_report
            victim.state = "ready"
            victim.reason = None
            victim.next_restart_at = None
            self.counts["promoted"] += 1
            wall = victim.t_ready_s - t_kill
            self.promotions.append({
                "victim": victim.worker_id,
                "spare": spare.worker_id,
                "generation": victim.generation,
                "t_kill_s": round(t_kill - t0, 4),
                "t_ready_s": round(victim.t_ready_s - t0, 4),
                "wall_s": round(wall, 4),
            })
        self.wsup._event("spare_promoted", spare.worker_id,
                         victim=victim.worker_id,
                         generation=victim.generation)
        # the promotion IS a ready transition for the victim's slot: one
        # lifecycle sample in the spare-promotion regime, closing the
        # capacity account's kill window
        self.wsup._event(
            "ready", victim.worker_id, generation=victim.generation,
            spawn_kind="spare-promotion",
            fresh_compiles=(victim.ready_report or {}).get(
                "fresh_compiles"),
            wall_s=round(wall, 3),
            walls=(victim.ready_report or {}).get("walls"))
        self.wsup._gauge_ready()
        if self.publisher is not None:
            # routability is one routes publish away — this is the whole
            # point: O(publish), not O(re-warm)
            try:
                self.publisher.publish_once()
            except OSError:
                pass  # the interval publisher retries on its own clock
        self._backfill_async()
        return True

    # -------------------------------------------------------- autoscaling

    def _record_decision(self, d: dict) -> None:
        """Actions always land; holds land only when their reason
        CHANGES (the elastic block stays reasoned, not flooded)."""
        with self._lock:
            if d["action"] == "hold":
                if d["reason"] == self._last_hold_reason:
                    return
                self._last_hold_reason = d["reason"]
            else:
                self._last_hold_reason = None
            self.decisions.append(d)

    def _scale_up(self) -> None:
        wsup = self.wsup
        slot = len(wsup.handles)
        h = WorkerHandle(slot=slot,
                         worker_id=f"{wsup.slot_prefix}{slot}",
                         socket_path=wsup._slot_address(slot))
        wsup.handles.append(h)
        wsup._spawn(h)
        threading.Thread(target=wsup._probe_until_ready,
                         args=(h, wsup.config.ready_timeout_s),
                         daemon=True).start()

    def _scale_down(self) -> None:
        wsup = self.wsup
        victim = None
        for h in reversed(wsup.handles):
            if h.state == "ready":
                victim = h
                break
        if victim is None:
            return
        victim.state = "draining"
        self.wsup._event("scale_down_drain", victim.worker_id,
                         generation=victim.generation)
        threading.Thread(target=wsup._drain_stop, args=(victim,),
                         daemon=True).start()

    def _admin_tune_quota(self, now_rel: float, offered_rps: float) -> None:
        """One-shot ``tune_quota`` admin op to each ready worker (fresh
        dial by design: quota retunes must not ride a channel the
        request path might sever)."""
        c = self.config
        desired = min(c.quota_ceiling_rps,
                      max(c.quota_floor_rps,
                          offered_rps * c.quota_headroom))
        if self._quota_cooldown_until is not None \
                and mono_now_s() < self._quota_cooldown_until:
            return
        cur = self._quota_current
        if cur is not None and cur > 0 \
                and abs(desired - cur) / cur < c.quota_min_rel_change:
            return
        applied_to = []
        for h in self.wsup.ready_workers():
            try:
                obj, _ = proto.request_once(
                    h.socket_path,
                    {"op": "tune_quota", "slo_class": c.quota_class,
                     "quota_rps": desired,
                     "quota_burst": desired * 1.5}, timeout_s=2.0)
                if obj.get("state") == "ok":
                    applied_to.append(h.worker_id)
            except (OSError, proto.ProtocolError):
                pass
        if not applied_to:
            return
        self._quota_current = desired
        self._quota_cooldown_until = mono_now_s() + c.cooldown_s
        rec = {"t_s": round(now_rel, 4), "slo_class": c.quota_class,
               "quota_rps": round(desired, 3),
               "applied_to": applied_to}
        with self._lock:
            self.quota_applied.append(rec)
        self._record_decision({
            "t_s": round(now_rel, 4), "action": "tune_quota",
            "reason": (f"{c.quota_class} offered {offered_rps:.1f} rps → "
                       f"quota {desired:.1f} rps (headroom "
                       f"{c.quota_headroom}×, within "
                       f"[{c.quota_floor_rps:.0f}, "
                       f"{c.quota_ceiling_rps:.0f}])"),
            "offered_rps": round(offered_rps, 3),
            "n_ready": len(self.wsup.ready_workers())})

    def _autoscale_tick(self) -> None:
        agg = self.aggregator
        if agg is None or self._policy is None:
            return
        now = mono_now_s()
        now_rel = now - self.wsup.t0_mono_s
        rps = agg.demand_recent_rps(self.config.demand_horizon_s)
        n_ready = len(self.wsup.ready_workers())
        d = self._policy.decide(now, rps, n_ready)
        d = dict(d, t_s=round(now_rel, 4))
        self._record_decision(d)
        if d["action"] == "scale_up":
            self._scale_up()
        elif d["action"] == "scale_down":
            self._scale_down()
        cls_rps = agg.demand_recent_rps(self.config.demand_horizon_s,
                                        slo_class=self.config.quota_class)
        self._admin_tune_quota(now_rel, cls_rps)

    # --------------------------------------------------------------- loop

    def _loop(self) -> None:
        next_autoscale = mono_now_s()
        while not self._stop.is_set():
            # parked spares must be ALIVE spares: a corpse in the pool
            # would promote thin air
            dead = []
            with self._lock:
                parked = list(self.spares)
            for s in parked:
                if s.state == "ready" and s.proc.poll() is not None:
                    dead.append(s)
            for s in dead:
                with self._lock:
                    if s in self.spares:
                        self.spares.remove(s)
                    self.counts["died_parked"] += 1
                self.wsup._event("spare_death", s.worker_id,
                                 rc=s.proc.poll(), phase="parked")
                self._backfill_async()
            if self.config.autoscale \
                    and mono_now_s() >= next_autoscale:
                next_autoscale = (mono_now_s()
                                  + self.config.autoscale_interval_s)
                try:
                    self._autoscale_tick()
                except Exception as e:  # the loop must outlive a bad tick
                    self.wsup._event("autoscale_error", "fleet",
                                     error=f"{type(e).__name__}: {e}"[:200])
            self._stop.wait(self.config.poll_interval_s)

    # ---------------------------------------------------------- lifecycle

    def start(self, wait_ready: bool = True) -> "FleetController":
        if self.config.prefork:
            self._start_prefork()
        if self.config.spares > 0:
            if wait_ready:
                self._fill_pool(self.config.spares, "initial")
            else:
                threading.Thread(target=self._fill_pool,
                                 args=(self.config.spares, "initial"),
                                 daemon=True).start()
        self.wsup.death_hooks.append(self._on_worker_death)
        self.wsup.fleet = self
        self._loop_thread = threading.Thread(
            target=self._loop, name="csmom-fleet-controller", daemon=True)
        self._loop_thread.start()
        return self

    def stop(self) -> None:
        """Idempotent: unhook, stop the loop, drain parked spares, then
        shut the prefork parent down (last — promoted children's polls
        route through it until they drain)."""
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self.wsup.death_hooks.remove(self._on_worker_death)
        except ValueError:
            pass
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=3.0)
        with self._backfill_lock:
            with self._lock:
                parked, self.spares = list(self.spares), []
        for s in parked:
            self.wsup._drain_stop(s)
            self.wsup._event("spare_stopped", s.worker_id)
        self._stop_prefork()

    # ------------------------------------------------------------ summary

    def summary(self) -> dict:
        """The closed-world ``fleet.elastic`` block (validated by
        ``chaos/invariants._validate_fleet``)."""
        c = self.config
        with self._lock:
            return {
                "armed": True,
                "spares_configured": c.spares,
                "prefork": bool(self._prefork_address is not None),
                "autoscale": c.autoscale,
                "spare_ids": list(self._all_spare_ids),
                "spares": dict(self.counts),
                "promotions": [dict(p) for p in self.promotions],
                "promotions_missed": self.promotions_missed,
                "decisions": [dict(d) for d in self.decisions],
                "quota": {
                    "slo_class": c.quota_class,
                    "floor_rps": c.quota_floor_rps,
                    "ceiling_rps": c.quota_ceiling_rps,
                    "applied": [dict(q) for q in self.quota_applied],
                },
                "bounds": {"min_workers": c.min_workers,
                           "max_workers": c.max_workers},
            }


if __name__ == "__main__":
    sys.exit(main())
