"""Health and readiness for the serving pool — demonstrated, not declared.

Two distinct probes, because they answer different operational questions
(the Kubernetes liveness/readiness split, applied to worker processes):

- **Liveness** (:func:`liveness`): "does the process respond?"  A ping
  over the worker socket with a short timeout.  Failing liveness means
  restart; it says nothing about whether the worker could serve.
- **Readiness** (:func:`readiness`): "may the router send traffic?"  The
  worker's own report: every bucket shape warmed, one self-probe request
  per endpoint actually SERVED through the full admission → coalesce →
  dispatch path (the ``cli/serve.py`` demonstrated-ready pattern), zero
  fresh compiles since the warm snapshot, and a matching AOT cache
  version.  A worker that cannot prove all four is not ready — the
  router never routes to it and the supervisor never drains its
  predecessor during a rolling restart.

**Cache version** (:func:`aot_cache_version`): the rolling-restart
contract is *warm-before-ready* — a replacement worker loads the
serialized-executable AOT cache instead of compiling.  That only holds
when router and worker agree on what the cache contains, so the version
token fingerprints everything that keys the compiled world: the bucket
grid, the endpoints, the engine parameters, and the installed jax
version (a jax upgrade invalidates serialized executables wholesale).  A
worker handed an ``--expect-cache-version`` that does not match its own
computation REFUSES to become ready with a pointed message instead of
silently compiling inside the serving window — version skew must cost a
deploy abort, never a latency cliff.

**Cold-cache honesty** (:func:`cache_readiness`): ``csmom serve`` with
the jax engine checks the on-disk warmup evidence BEFORE warming: the
warmup report must exist in the shared cache namespace and cover every
entry of the selected bucket profile error-free.  Missing or stale means
"run ``csmom warmup --profiles serve`` first", as a nonzero exit — not a
silent multi-second compile pause inside what claimed to be a ready
probe.

Stdlib-only (numpy rides in via proto): safe to import from the
supervisor's monitor loop and the fast rehearse tier without jax.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os

from csmom_tpu.registry import serve_endpoints
from csmom_tpu.serve import proto
from csmom_tpu.serve.buckets import bucket_spec

__all__ = ["aot_cache_version", "cache_readiness", "expected_entry_names",
           "liveness", "readiness"]

# the operator remedy every cold/stale/skewed cache message points at —
# one string so the tests can pin that the pointer never drifts
WARMUP_POINTER = "run `csmom warmup --profiles serve` first"


def aot_cache_version(profile: str, *, lookback: int = 12, skip: int = 1,
                      n_bins: int = 10, mode: str = "rank",
                      engine: str = "jax",
                      mesh_devices: int | None = None) -> str:
    """Deterministic fingerprint of the compiled world this pool expects.

    Jax-free: the jax version is read from package metadata, not an
    import, so the supervisor can stamp versions without initializing a
    backend.  The token changes iff something that invalidates the AOT
    cache changes — bucket geometry, endpoint set, engine params, or the
    jax release that serialized the executables.  The mesh engine's
    compiled world is ALSO keyed by its topology (``mesh_devices``, the
    worker's pinned slice size): a program sharded 8 ways is not the
    2-way program, so a pool resized without re-warming must read as
    skew, not compile in-window.  The default (single-device jax)
    basis is byte-identical to the r11 one — existing version tokens
    do not churn.
    """
    spec = bucket_spec(profile)
    try:
        from importlib.metadata import version

        jax_ver = version("jax")
    except Exception:
        jax_ver = "unknown"
    basis = {
        "profile": spec.name,
        "months": spec.months,
        "asset_buckets": list(spec.asset_buckets),
        "batch_buckets": list(spec.batch_buckets),
        "dtype": spec.dtype,
        "endpoints": list(serve_endpoints()),
        "engine_params": {"lookback": lookback, "skip": skip,
                          "n_bins": n_bins, "mode": mode},
        "jax": jax_ver,
    }
    if engine != "jax":
        basis["engine"] = engine
    if mesh_devices is not None:
        basis["mesh_devices"] = int(mesh_devices)
    blob = json.dumps(basis, sort_keys=True).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:12]


def expected_entry_names(profile: str,
                         mesh_devices: int | None = None) -> set:
    """The manifest entry names ``csmom warmup --profiles <profile>``
    must have compiled — derived from bucket geometry alone (the same
    ``serve.{kind}.b{B}@{A}x{M}`` scheme ``compile/manifest.py`` uses),
    so this check never needs jax.  With ``mesh_devices`` the names are
    the MESH profile's (``mesh.serve....d<n>``): shard counts derive
    from the same divisor arithmetic the variants use
    (:func:`csmom_tpu.mesh.pinning.shards_for` — stdlib) and the
    placement rule table (:func:`csmom_tpu.mesh.rules.serve_axis_for`
    — pure regex), so the check still never needs jax."""
    spec = bucket_spec(profile)
    if mesh_devices is None:
        return {f"serve.{kind}.b{B}@{A}x{M}"
                for kind in serve_endpoints() for B, A, M in spec.shapes()}
    from csmom_tpu.mesh.pinning import shards_for
    from csmom_tpu.mesh.rules import serve_axis_for

    out = set()
    for kind in serve_endpoints():
        axis = serve_axis_for(kind)
        for B, A, M in spec.shapes():
            n = shards_for(B if axis == "batch" else A, mesh_devices)
            out.add(f"mesh.serve.{kind}.b{B}@{A}x{M}.d{n}")
    # the mesh engine's scaling probe warms a single-device reference
    # entry at the largest bucket; it is part of the profile (same name
    # scheme as registry.builtin's mesh feeder) so the gate covers it
    probe = serve_endpoints()[0]
    out.add(f"mesh.serve.single-probe.{probe}."
            f"b{spec.batch_buckets[-1]}@{spec.max_assets}x{spec.months}")
    return out


def cache_readiness(profile: str, cache_subdir: str = "bench",
                    mesh_devices: int | None = None) -> tuple:
    """``(ready, reason)`` for the on-disk AOT cache of ``profile``.

    Ready means: the persistent cache is enabled, its warmup report
    exists, the report covers every expected serve entry with no error,
    and the cache directory still holds serialized executables (a report
    describing an evicted cache is stale evidence).  ``reason`` always
    names the remedy (``WARMUP_POINTER``) when not ready.
    """
    from csmom_tpu.compile.aot import REPORT_NAME, read_warmup_report
    from csmom_tpu.utils.jit_cache import cache_dir

    d = cache_dir(cache_subdir)
    if d is None:
        return False, ("persistent AOT cache disabled (CSMOM_JIT_CACHE=0): "
                       "a zero-compile restart is impossible; re-enable it "
                       f"and {WARMUP_POINTER}")
    report = read_warmup_report(cache_subdir)
    if isinstance(report, str):
        return False, (f"no warmup evidence for cache {d}: {report} — "
                       f"{WARMUP_POINTER}")
    entries = report.get("entries")
    if not isinstance(entries, list):
        return False, (f"warmup report in {d} has no entries list — "
                       f"stale/damaged evidence; {WARMUP_POINTER}")
    warmed = {e.get("name") for e in entries
              if isinstance(e, dict) and "error" not in e}
    expected = expected_entry_names(profile, mesh_devices)
    pointer = (WARMUP_POINTER.replace("--profiles serve",
                                      "--profiles serve-mesh")
               if mesh_devices is not None else WARMUP_POINTER)
    missing = sorted(expected - warmed)
    if missing:
        return False, (
            f"AOT cache cold for bucket profile {profile!r}"
            + (f" on a d{mesh_devices} mesh" if mesh_devices else "") +
            f": {len(missing)} of {len(expected)} serve "
            f"shapes have no warm evidence (first missing: {missing[0]}) — "
            f"{pointer}")
    cached = [p for p in glob.glob(os.path.join(d, "*"))
              if os.path.isfile(p) and os.path.basename(p) != REPORT_NAME]
    if not cached:
        return False, (
            f"warmup report present but cache {d} holds no serialized "
            f"executables (evicted?) — stale evidence; {pointer}")
    return True, (f"cache {d}: all {len(expected)} "
                  f"serve shapes warm, {len(cached)} serialized entries")


# ---------------------------------------------------------------- probes ---

def liveness(socket_path: str, timeout_s: float = 2.0) -> tuple:
    """``(alive, reason)``: does the worker process answer a ping?"""
    try:
        obj, _ = proto.request_once(socket_path, {"op": "ping"},
                               timeout_s=timeout_s)
    except (OSError, proto.ProtocolError) as e:
        return False, f"{type(e).__name__}: {e}"
    if obj.get("ok"):
        return True, "pong"
    return False, f"ping answered without ok: {obj}"


def readiness(socket_path: str, timeout_s: float = 5.0) -> dict:
    """The worker's readiness report (see :mod:`csmom_tpu.serve.worker`),
    or a not-ready dict carrying the probe failure as the reason.  The
    report's ``ok`` is the routing decision; everything else is the
    evidence behind it (warm shapes, per-endpoint probe states, fresh
    compiles, cache version)."""
    try:
        obj, _ = proto.request_once(socket_path, {"op": "ready"},
                               timeout_s=timeout_s)
        return obj
    except (OSError, proto.ProtocolError) as e:
        return {"ok": False,
                "reason": f"readiness probe failed: {type(e).__name__}: {e}"}
