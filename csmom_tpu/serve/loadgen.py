"""Deterministic open-loop load generator + the SERVE artifact writer.

Open-loop means arrivals fire on the SCHEDULE's clock, not the
service's: a slow service does not throttle the generator, so overload
shows up as real queue growth, expiry, and backpressure rejection —
exactly the degradation surface the serve layer exists to manage.  (A
closed-loop generator that waits for each response would hide the knee:
coordinated omission.)

Determinism: one seeded ``random.Random`` drives everything — arrival
times (exponential inter-arrivals per schedule segment: Poisson
traffic), endpoint mix, SLO-class mix, universe sizes, panel reuse, and
the synthetic panels — so a rehearse scenario or a regression hunt
replays the exact request stream from ``(schedule, seed)`` alone.

Schedules are either explicit (``"2x30,2x60"`` = 2 s at 30 req/s then
2 s at 60) or NAMED (ISSUE 8): ``bursty`` (quiet baseline punctuated by
hard bursts — the adaptive batcher's reason to exist), ``diurnal`` (a
compressed day: ramp up, peak, ramp down), and ``adversarial``
(universe sizes hugging the bucket-grid boundaries, the worst case for
padding overhead).  A named schedule also presets the load SHAPE that
makes it meaningful: bursty/diurnal mix in a heavy ``bulk`` share (so
quota enforcement is exercised), reuse a fraction of panels (so the
result cache sees repeats), and bump the panel version mid-run (so
cache invalidation is demonstrated inside the same artifact, with zero
stale hits as a schema rule).

The run lands as ``SERVE_<run>.json`` (schema v4): throughput headline
PLUS ``offered_rps`` (so an offered-load-limited run is never misread
as a saturation ceiling — the r11 footnote, now a field), request
accounting globally, per SLO class AND per ENDPOINT (all closed by
schema: :mod:`csmom_tpu.chaos.invariants` kind ``serve``; the endpoint
name set must be registered engines — ISSUE 9), per-class latency
percentiles against each class's budget, the cache book (hit rate,
zero stale hits), p50/p95/p99 queue / service / total latency, the
batch-size histogram with padding overhead and fire reasons, and the
in-window fresh-compile count.  v4 adds the SLO error-budget burn
accounting (``classes.<name>.violations`` / ``budget_burn``, via
:func:`csmom_tpu.obs.metrics.budget_burn`) and bounded per-request
latency samples in ``extra.samples`` — both schema rules.
:mod:`csmom_tpu.obs.ledger` ingests these rows
(``serve_throughput_rps``, ``serve_p99_ms``, ``serve_cache_hit_rate``,
per-class p99s, per-endpoint ``serve_ep_<name>_p99_ms``,
``serve_p99_under_burst_ms`` for bursty runs — the p99 rows now carry
their sample lists, so :mod:`csmom_tpu.obs.regress` backs verdicts with
bootstrap CIs instead of degrading to point-delta), so serve
performance joins the cross-run regression gate like every bench wall.

Naming rule (the TELEMETRY rule, extended): only round artifacts
(``SERVE_rNN.json``) are committable evidence; ``SERVE_smoke*.json`` /
``SERVE_rehearse*.json`` are regenerated per run and gitignored.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
import time

import numpy as np

from csmom_tpu.registry import serve_surface, workload_kinds
from csmom_tpu.serve.service import ServeConfig, SignalService
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["LoadConfig", "NAMED_SCHEDULES", "arrival_offsets",
           "build_artifact", "build_fabric_artifact",
           "build_pool_artifact", "parse_schedule", "resolve_schedule",
           "run_fabric_loadgen", "run_loadgen", "run_pool_loadgen",
           "synth_panel", "write_artifact"]

# schema v3 (ISSUE 9): per-endpoint books + latency, endpoint set
# validated against the engine registry by chaos/invariants.  v4 (ISSUE
# 13): per-class SLO error-budget burn accounting (violations +
# budget_burn per class book) and bounded per-request latency samples in
# extra.samples, both schema rules so the burn rows and the CI backing
# can never silently vanish from committed evidence.
SCHEMA_VERSION = 4
POOL_SCHEMA_VERSION = 1
FABRIC_SCHEMA_VERSION = 1

# the r15 PER-WORKER cache hit rate (SERVE_MESH_r15.json): the number
# the fabric's consistent-hash routing exists to beat at POOL level —
# identical requests that round-robin across workers split their
# repeats across N private caches; landing them on the SAME worker
# compounds the hit rate instead
R15_PER_WORKER_HIT_RATE = 0.246

# the r10/r11 default mixes, expressed as an SLO-class mix
_DEFAULT_MIX = (("interactive", 0.6), ("standard", 0.15), ("bulk", 0.25))

# named schedules (ISSUE 8): segment string + the load shape that makes
# the schedule meaningful.  All well under 4 s of wall on CPU.
NAMED_SCHEDULES = {
    # quiet baseline punctuated by hard bursts: the bursts outrun the
    # bulk quota (rejected_quota > 0) while interactive stays inside its
    # budget; panels repeat within a version epoch (cache hits) and the
    # panel version bumps mid-run (invalidation, zero stale hits)
    "bursty": {
        "schedule": "0.5x8,0.3x240,0.5x8,0.3x300,0.5x10,0.3x260,0.4x8",
        "class_mix": (("interactive", 0.45), ("standard", 0.15),
                      ("bulk", 0.4)),
        "reuse_fraction": 0.35,
        "version_bumps": 1,
        "use_class_deadlines": True,
    },
    # a compressed trading day: ramp to a midday peak and back down
    "diurnal": {
        "schedule": "0.35x10,0.35x40,0.35x90,0.35x140,0.35x90,"
                    "0.35x40,0.35x10",
        "class_mix": (("interactive", 0.5), ("standard", 0.2),
                      ("bulk", 0.3)),
        "reuse_fraction": 0.25,
        "version_bumps": 1,
        "use_class_deadlines": True,
    },
    # universe sizes hugging the bucket-grid boundaries: every request
    # lands exactly AT a bucket edge or one past it, maximizing padding
    # pressure and bucket churn — the worst honest case for pad_fraction
    "adversarial": {
        "schedule": "1.6x70",
        "class_mix": _DEFAULT_MIX,
        "boundary_hug": True,
        "use_class_deadlines": True,
    },
}


@dataclasses.dataclass(frozen=True)
class Segment:
    duration_s: float
    rps: float


def parse_schedule(spec: str) -> tuple:
    """``"2x25,3x60"`` -> (Segment(2, 25), Segment(3, 60)): run 2 s at
    25 req/s, then 3 s at 60 req/s.  Named schedules resolve first via
    :func:`resolve_schedule`."""
    if spec in NAMED_SCHEDULES:
        spec = NAMED_SCHEDULES[spec]["schedule"]
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            dur, _, rate = part.partition("x")
            out.append(Segment(float(dur), float(rate)))
        except ValueError:
            raise ValueError(
                f"bad schedule segment {part!r}: use DURxRPS, e.g. 2x25, "
                f"or a named schedule ({', '.join(sorted(NAMED_SCHEDULES))})"
            ) from None
    if not out:
        raise ValueError(f"empty schedule {spec!r}")
    return tuple(out)


def resolve_schedule(spec: str) -> tuple:
    """``(schedule_str, schedule_kind, preset_overrides)`` for a CLI
    ``--schedule`` value: named schedules expand to their segments and
    carry the LoadConfig preset that makes them meaningful; an explicit
    DURxRPS string passes through with kind ``custom``."""
    if spec in NAMED_SCHEDULES:
        preset = dict(NAMED_SCHEDULES[spec])
        schedule = preset.pop("schedule")
        return schedule, spec, preset
    return spec, "custom", {}


def schedule_duration_s(segments: tuple) -> float:
    return sum(seg.duration_s for seg in segments)


def arrival_offsets(segments: tuple, rng: random.Random) -> list:
    """Seeded Poisson arrival offsets (seconds from start) covering every
    segment — the deterministic request clock."""
    out: list = []
    t0 = 0.0
    for seg in segments:
        if seg.rps <= 0:
            t0 += seg.duration_s
            continue
        t = t0 + rng.expovariate(seg.rps)
        while t < t0 + seg.duration_s:
            out.append(t)
            t += rng.expovariate(seg.rps)
        t0 += seg.duration_s
    return out


def synth_panel(rng: random.Random, n_assets: int, months: int,
                kind: str) -> tuple:
    """One deterministic request panel: a positive random walk (prices)
    or positive level noise (volume), with a seeded sprinkle of masked
    gaps so the mask path is always exercised.  The family is the
    REGISTERED endpoint's declaration (``panel_family``), so a new
    endpoint states what its synthetic workload looks like at
    registration instead of patching the generator."""
    r = np.random.default_rng(rng.getrandbits(32))
    try:
        family = serve_surface(kind).panel_family
    except (KeyError, ValueError):
        family = "price"  # an unknown kind still gets a well-formed panel
    if family == "volume":
        values = r.lognormal(mean=12.0, sigma=0.5,
                             size=(n_assets, months)).astype(np.float32)
    else:
        steps = r.normal(0.0, 0.04, size=(n_assets, months)).astype(np.float32)
        values = 100.0 * np.exp(np.cumsum(steps, axis=1), dtype=np.float32)
    mask = r.random((n_assets, months)) > 0.02
    mask[:, 0] = True  # every asset observed at least once, from the start
    values = np.where(mask, values, np.nan).astype(np.float32)
    return values, mask


@dataclasses.dataclass(frozen=True)
class LoadConfig:
    """One load-generation run (everything the artifact must replay)."""

    schedule: str = "2x40"
    seed: int = 0
    kinds: tuple | None = None          # None = every registered workload
    deadline_s: float | None = 0.5
    interactive_fraction: float = 0.7   # legacy 2-class knob (see mix())
    class_mix: tuple | None = None      # ((class, weight), ...) wins
    schedule_kind: str = "custom"       # "bursty"/"diurnal"/... or custom
    reuse_fraction: float = 0.0         # P(reuse a recent panel) -> hits
    version_bumps: int = 0              # mid-run panel_version bumps
    use_class_deadlines: bool = False   # None deadline -> class budget
    boundary_hug: bool = False          # adversarial bucket-edge sizes
    max_assets: int | None = None       # default: the spec's largest bucket
    run_id: str = "smoke"

    def resolved_kinds(self) -> tuple:
        """The endpoint mix: explicit ``kinds`` wins; the default is
        surface (d) — every registered servable engine that opted into
        the synthetic workload, so a newly registered endpoint joins
        the load mix (and lands ledger rows) with no loadgen edit."""
        return tuple(self.kinds) if self.kinds else workload_kinds()

    def mix(self) -> tuple:
        """The effective class mix: explicit ``class_mix`` wins; else the
        legacy two-way split (``batch`` spelled as its alias target)."""
        if self.class_mix:
            return tuple(self.class_mix)
        f = self.interactive_fraction
        return (("interactive", f), ("bulk", 1.0 - f))


def _pick_class(mix: tuple, rng: random.Random) -> str:
    total = sum(w for _, w in mix) or 1.0
    x = rng.random() * total
    acc = 0.0
    for name, w in mix:
        acc += w
        if x <= acc:
            return name
    return mix[-1][0]


def _boundary_sizes(spec, max_assets: int) -> list:
    """Bucket-boundary-hugging universe sizes: exactly AT each asset
    bucket (zero asset padding) and one PAST each non-largest bucket
    (forcing the next bucket — maximum padding), clipped to the cap."""
    sizes = set()
    for i, a in enumerate(spec.asset_buckets):
        if a <= max_assets:
            sizes.add(a)
        if i + 1 < len(spec.asset_buckets) and a + 1 <= max_assets:
            sizes.add(a + 1)
    return sorted(sizes) or [max_assets]


# bounded per-request latency sample lists persisted into the artifact
# (extra.samples): enough for obs.regress's block bootstrap to put a CI
# behind every serve p99 row, small enough that a committed artifact
# stays reviewable.  Deterministic: seeded index sample, chronological
# order kept (the block bootstrap assumes consecutive samples share
# state, exactly like bench reps).
SAMPLE_CAP = 512
CLASS_SAMPLE_CAP = 256


def _bounded_samples(values_ms: list, cap: int, seed: int) -> list:
    if len(values_ms) <= cap:
        return [round(v, 4) for v in values_ms]
    idx = sorted(random.Random(seed).sample(range(len(values_ms)), cap))
    return [round(values_ms[i], 4) for i in idx]


def _latency_samples(load: "LoadConfig", requests: list,
                     scope_prefixes: bool = True) -> dict:
    """``extra.samples`` for a serve artifact: total-latency ms per
    request, globally plus per SLO class and per endpoint (scope-keyed,
    so the ledger attaches each row its OWN distribution)."""
    served = [r for r in requests
              if r.state == "served" and r.total_s is not None]
    out = {"serve_total_ms": _bounded_samples(
        [1e3 * r.total_s for r in served], SAMPLE_CAP, load.seed)}
    if not scope_prefixes:
        return out
    for name in sorted({r.priority for r in served}):
        out[f"class:{name}"] = _bounded_samples(
            [1e3 * r.total_s for r in served if r.priority == name],
            CLASS_SAMPLE_CAP, load.seed + 1)
    for kind in load.resolved_kinds():
        mine = [1e3 * r.total_s for r in served if r.kind == kind]
        if mine:
            out[f"ep:{kind}"] = _bounded_samples(mine, CLASS_SAMPLE_CAP,
                                                 load.seed + 2)
    return out


def _percentiles(samples: list) -> dict:
    """Nearest-rank p50/p95/p99 in milliseconds (None when unobserved).

    Nearest-rank is ``ceil(q*N) - 1`` (0-based): with N=2 the p50 is the
    FIRST sample, with N=100 the p99 is the 99th — ``int(q*N)`` would be
    one rank high exactly when q*N is integral, a bias that shifts with
    sample count and would feed the regression gate noise."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None}
    s = sorted(samples)

    def pick(q):
        return round(1e3 * s[max(0, math.ceil(q * len(s)) - 1)], 3)

    return {"p50": pick(0.50), "p95": pick(0.95), "p99": pick(0.99)}


def run_loadgen(service: SignalService, load: LoadConfig) -> dict:
    """Drive ``service`` with the seeded open-loop schedule; returns the
    artifact object (not yet written).

    The service must be started; it is drained and stopped before the
    books are closed, so the accounting invariant is evaluated on a
    quiet queue.
    """
    rng = random.Random(load.seed)
    segments = parse_schedule(load.schedule)
    offsets = arrival_offsets(segments, rng)
    spec = service.spec
    max_assets = min(load.max_assets or spec.max_assets, spec.max_assets)
    mix = load.mix()
    boundary = (_boundary_sizes(spec, max_assets)
                if load.boundary_hug else None)

    # panel-version epochs: with bumps armed, every request is stamped
    # with the current epoch and the version floor rises mid-run — the
    # cache must show hits inside an epoch and ZERO stale hits across
    # the bump (the acceptance property SERVE_r13.json pins)
    epoch = 1 if load.version_bumps > 0 else None
    bump_at = sorted(
        max(1, round(len(offsets) * (k + 1) / (load.version_bumps + 1)))
        for k in range(load.version_bumps)
    ) if load.version_bumps > 0 else []
    kinds = load.resolved_kinds()
    recent: dict = {k: [] for k in kinds}

    requests = []
    t_start = mono_now_s()
    for i, off in enumerate(offsets):
        if bump_at and i == bump_at[0]:
            bump_at.pop(0)
            epoch += 1
            service.notify_panel_version(epoch)
        delay = (t_start + off) - mono_now_s()
        if delay > 0:
            time.sleep(delay)  # open loop: the schedule's clock rules
        kind = rng.choice(list(kinds))
        pool = recent[kind]
        if pool and rng.random() < load.reuse_fraction:
            values, mask = pool[rng.randrange(len(pool))]
        else:
            if boundary is not None:
                n_assets = boundary[rng.randrange(len(boundary))]
            else:
                n_assets = rng.randint(2, max_assets)
            values, mask = synth_panel(rng, n_assets, spec.months, kind)
            pool.append((values, mask))
            del pool[:-8]  # a small window of reusable recents per kind
        cls = _pick_class(mix, rng)
        requests.append(service.submit(
            kind, values, mask, priority=cls,
            deadline_s=(None if load.use_class_deadlines
                        else load.deadline_s),
            panel_version=epoch,
        ))
    # close the books: wait for every request to reach a terminal state,
    # then drain-stop the worker
    give_up = mono_now_s() + 30.0
    for r in requests:
        r.wait(timeout=max(0.0, give_up - mono_now_s()))
    service.stop(drain=True)
    wall_s = mono_now_s() - t_start
    return build_artifact(service, load, requests, wall_s)


def _platform(service: SignalService) -> str:
    if service.engine.name == "stub":
        return "stub"
    import jax

    return jax.default_backend()


def _class_blocks(service: SignalService, requests: list) -> dict:
    """The per-class books + measured latency vs budget.  ``within_budget``
    is the class's p99 promise judged against measurement: True/False
    once the class served anything, None when it never did."""
    from csmom_tpu.obs.metrics import budget_burn

    stats = service.class_stats()
    out = {}
    for name, book in stats.items():
        served = [r for r in requests
                  if r.priority == name and r.state == "served"]
        lat = _percentiles([r.total_s for r in served
                            if r.total_s is not None])
        p99 = lat["p99"]
        budget = book.get("budget_ms")
        violations = (sum(1 for r in served if r.total_s is not None
                          and 1e3 * r.total_s > budget)
                      if budget is not None else 0)
        out[name] = {
            **{k: book[k] for k in ("admitted", "served", "rejected",
                                    "expired", "rejected_quota")},
            "rank": book["rank"],
            "budget_ms": budget,
            "quota_rps": book["quota_rps"],
            "queue_share": book["queue_share"],
            "latency_ms": lat,
            "within_budget": (None if p99 is None or budget is None
                              else bool(p99 <= budget)),
            # SLO error-budget accounting (obs.metrics.budget_burn):
            # observed violation rate over the allowed rate at the 99%
            # target — the serve_<class>_budget_burn ledger row's source
            "violations": violations,
            "budget_burn": (None if budget is None
                            else budget_burn(len(served), violations)),
        }
    return out


def _endpoint_blocks(load: LoadConfig, requests: list) -> dict:
    """Surface (d)'s evidence: per-ENDPOINT books + latency, keyed by
    registry name.  Every submitted request lands in exactly one
    endpoint's book, so the served counts sum to the global book (a
    schema rule of serve v3)."""
    out = {}
    for kind in load.resolved_kinds():
        mine = [r for r in requests if r.kind == kind]
        served = [r for r in mine if r.state == "served"]
        out[kind] = {
            "submitted": len(mine),
            "served": len(served),
            "rejected": sum(1 for r in mine if r.state == "rejected"),
            "expired": sum(1 for r in mine if r.state == "expired"),
            "latency_ms": _percentiles(
                [r.total_s for r in served if r.total_s is not None]),
        }
    return out


def build_artifact(service: SignalService, load: LoadConfig,
                   requests: list, wall_s: float) -> dict:
    """The SERVE artifact (schema v3): headline + offered load + global,
    per-class AND per-endpoint accounting + cache book + latency +
    batches."""
    acct = service.accounting()
    served = [r for r in requests if r.state == "served"]
    throughput = round(acct["served"] / wall_s, 3) if wall_s > 0 else 0.0
    segments = parse_schedule(load.schedule)
    duration = schedule_duration_s(segments)
    offered_rps = round(len(requests) / duration, 3) if duration else 0.0
    lat = {
        "queue": _percentiles(
            [r.queue_wait_s for r in requests if r.queue_wait_s is not None]),
        "service": _percentiles(
            [r.service_s for r in served if r.service_s is not None]),
        "total": _percentiles(
            [r.total_s for r in served if r.total_s is not None]),
    }
    fresh = service.fresh_compiles()
    spec = service.spec
    sched_label = (load.schedule_kind if load.schedule_kind != "custom"
                   else load.schedule)
    # the mesh engine's workload fingerprint CARRIES the device count:
    # throughput/latency on d=1 and d=8 are different experiments and
    # the ledger must never pair them (the device-count-keyed-rows rule)
    mesh = None
    mesh_note = ""
    if hasattr(service.engine, "mesh_info"):
        mesh = service.engine.mesh_info(spec)
        mesh["scaling"] = service.engine.scaling_probe(spec)
        mesh_note = f", mesh d{mesh['devices']}"
    workload = (
        f"open-loop {sched_label} rps seed {load.seed}, "
        f"{'/'.join(load.resolved_kinds())} mix, buckets "
        f"B({','.join(map(str, spec.batch_buckets))})x"
        f"A({','.join(map(str, spec.asset_buckets))})x{spec.months}m "
        f"({spec.dtype}, {service.config.engine} engine{mesh_note})"
    )
    extra = {
        "platform": _platform(service),
        "engine": service.config.engine,
        "workload": workload,
        "capacity": service.config.capacity,
        "max_wait_ms": round(1e3 * service.config.max_wait_s, 3),
        "warm_report": service.warm_report,
        # bounded per-request latency samples (chronological), scope-
        # keyed: the ledger attaches these to the p99 rows so the gate
        # gets bootstrap CIs instead of point-delta/suspect verdicts
        "samples": _latency_samples(load, requests),
    }
    if mesh is not None:
        extra["mesh"] = mesh
    if service.spec.name == "serve-smoke":
        extra["smoke"] = ("smoke-bucket run: pipeline-shaped, workload "
                          "reduced — NOT a performance capture")
    return {
        "kind": "serve",
        "schema_version": SCHEMA_VERSION,
        "run_id": load.run_id,
        "metric": "serve_throughput_rps",
        "value": throughput,
        "unit": "req/s",
        "vs_baseline": 1.0,
        "wall_s": round(wall_s, 4),
        # achieved == offered (no rejection, no expiry) means the run
        # measured the LOAD, not the service's ceiling: the ledger flags
        # the throughput row so it never gates against a saturated run
        "offered_limited": bool(acct["rejected"] == 0
                                and acct["expired"] == 0),
        "requests": acct,
        "classes": _class_blocks(service, requests),
        "endpoints": _endpoint_blocks(load, requests),
        "cache": service.cache_stats(),
        "latency_ms": lat,
        "batches": service.batch_stats(),
        "compile": {
            "in_window_fresh_compiles": fresh,
            "note": "backend_compiles delta since the pre-serving warmup "
                    "snapshot: 0 = every dispatch hit a warmed bucket "
                    "shape (the padding contract held)",
        },
        "offered": {
            "schedule": load.schedule,
            "schedule_kind": load.schedule_kind,
            "seed": load.seed,
            "n_arrivals": len(requests),
            "duration_s": round(duration, 4),
            "offered_rps": offered_rps,
            "kinds": list(load.resolved_kinds()),
            "deadline_ms": ("class-budget" if load.use_class_deadlines
                            else None if load.deadline_s is None
                            else round(1e3 * load.deadline_s, 3)),
            "class_mix": {name: w for name, w in load.mix()},
            "reuse_fraction": load.reuse_fraction,
            "version_bumps": load.version_bumps,
        },
        "extra": extra,
    }


# ------------------------------------------------------------------ pool ---

def _open_loop_drive(offsets, submit_arrival, concurrent=None,
                     drain_give_up_s: float = 60.0,
                     artifact_label: str = "pool") -> tuple:
    """The shared open-loop scaffold behind the pool and fabric drives:
    run ``concurrent`` in a side thread, fire ``submit_arrival(i)`` at
    each schedule offset (open loop — the schedule's clock rules, not
    the service's), wait every request terminal within
    ``drain_give_up_s``, then join the side thread with its OWN
    generous budget (a roll can outlast the request drain) and refuse
    to return from a still-mutating fleet rather than let the caller
    land a mid-roll snapshot as evidence.  A ``concurrent`` exception
    is surfaced after the join, never lost.  Returns
    ``(requests, wall_s)``."""
    import threading

    side = None
    side_exc: list = []
    if concurrent is not None:
        def _side():
            try:
                concurrent()
            except BaseException as e:  # surfaced after join, not lost
                side_exc.append(e)

        side = threading.Thread(target=_side, daemon=True)

    requests = []
    t_start = mono_now_s()
    if side is not None:
        side.start()
    for i, off in enumerate(offsets):
        delay = (t_start + off) - mono_now_s()
        if delay > 0:
            time.sleep(delay)  # open loop: the schedule's clock rules
        requests.append(submit_arrival(i))
    give_up = mono_now_s() + drain_give_up_s
    for r in requests:
        r.wait(timeout=max(0.0, give_up - mono_now_s()))
    wall_s = mono_now_s() - t_start
    if side is not None:
        side.join(timeout=300.0)
        if side.is_alive():
            raise RuntimeError(
                f"concurrent action still running after 300s — refusing "
                f"to build the {artifact_label} artifact from an "
                "unsettled fleet")
        if side_exc:
            raise side_exc[0]
    return requests, wall_s


def run_pool_loadgen(router, supervisor, load: LoadConfig,
                     concurrent=None) -> dict:
    """Drive the multi-process pool with the SAME seeded open-loop
    schedule as :func:`run_loadgen`, through the router.

    The pool is NOT stopped here (the caller may still want to kill /
    roll / inspect workers); the books close once every admitted request
    reaches a terminal state — which the router guarantees per request,
    so waiting on the handles IS the drain.

    ``concurrent`` (optional callable) runs in a thread alongside the
    load stream — the chaos lever for "do X UNDER load" scenarios
    (rolling restart, a mid-run kill).  The artifact is built only after
    BOTH the load's requests are terminal AND ``concurrent`` returned,
    so worker stats and fleet events are read from a settled pool."""
    rng = random.Random(load.seed)
    segments = parse_schedule(load.schedule)
    offsets = arrival_offsets(segments, rng)
    spec = router.spec
    max_assets = min(load.max_assets or spec.max_assets, spec.max_assets)
    mix = load.mix()
    kinds = list(load.resolved_kinds())  # hoisted out of the timed loop

    def submit_arrival(_i):
        kind = rng.choice(kinds)
        n_assets = rng.randint(2, max_assets)
        values, mask = synth_panel(rng, n_assets, spec.months, kind)
        return router.submit(kind, values, mask,
                             priority=_pick_class(mix, rng),
                             deadline_s=load.deadline_s)

    requests, wall_s = _open_loop_drive(offsets, submit_arrival,
                                        concurrent, 60.0, "pool")
    return build_pool_artifact(router, supervisor, load, requests, wall_s)


def _pool_fresh_compiles(workers: list):
    """Aggregate in-window fresh compiles across the fleet: the SUM of
    every live worker's count.  A worker that cannot report (dead slot,
    stats error) degrades the total to a reason string — "unknown" must
    never be spelled 0."""
    total = 0
    gaps = []
    for w in workers:
        if w.get("state") != "ready":
            # a replaced slot's history lives in the replacement; a dead/
            # failed slot has no count to contribute — named, not zeroed
            gaps.append(f"{w['worker_id']}: {w.get('state')}")
            continue
        fc = w.get("fresh_compiles")
        if isinstance(fc, int) and not isinstance(fc, bool):
            total += fc
        else:
            gaps.append(f"{w['worker_id']}: {fc!r}")
    if gaps:
        return (f"{total} across reporting workers; not measurable for "
                f"[{'; '.join(gaps)}]")
    return total


def build_pool_artifact(router, supervisor, load: LoadConfig,
                        requests: list, wall_s: float) -> dict:
    """The SERVE_POOL artifact: the router's closed cross-process books,
    hedging/availability headline, and the fleet's evidence."""
    acct = router.accounting()
    served = [r for r in requests if r.state == "served"]
    throughput = round(acct["served"] / wall_s, 3) if wall_s > 0 else 0.0
    segments = parse_schedule(load.schedule)
    duration = schedule_duration_s(segments)
    offered_rps = round(len(requests) / duration, 3) if duration else 0.0
    lat = {"total": _percentiles(
        [r.total_s for r in served if r.total_s is not None])}
    workers = supervisor.worker_stats()
    summary = supervisor.summary()
    fresh = _pool_fresh_compiles(workers)
    spec = router.spec
    cfg = supervisor.config
    ready = [w for w in workers if w.get("state") == "ready"]
    platform = None
    for h in supervisor.handles:
        rep = h.ready_report or {}
        if isinstance(rep.get("platform"), str):
            platform = rep["platform"]
            break
    # the mesh pool's workload key carries its topology (same rule as
    # the single-process path): per-worker device count when pinned,
    # the named worker slices otherwise — two differently-sized mesh
    # pools must never pair in the ledger
    if cfg.engine == "jax-mesh":
        if cfg.devices_per_worker > 0:
            mesh_note = f", {cfg.devices_per_worker} dev/worker"
        else:
            slices = sorted({h.device_slice for h in supervisor.handles
                             if h.device_slice} | set())
            mesh_note = (f", slices {'/'.join(slices)}" if slices
                         else ", unpinned mesh")
    else:
        mesh_note = ""
    workload = (
        f"pool open-loop {load.schedule} rps seed {load.seed}, "
        f"{'/'.join(load.resolved_kinds())} mix, {cfg.n_workers} workers, buckets "
        f"B({','.join(map(str, spec.batch_buckets))})x"
        f"A({','.join(map(str, spec.asset_buckets))})x{spec.months}m "
        f"({spec.dtype}, {cfg.engine} engine{mesh_note})"
    )
    extra = {
        "platform": platform,
        "engine": cfg.engine,
        "workload": workload,
        "hedge_policy": {
            "fraction": router.config.hedge_fraction,
            "floor_ms": round(1e3 * router.config.hedge_floor_s, 3),
            "max_attempts": router.config.max_attempts,
        },
        "cache_version": summary["expect_cache_version"],
        # same CI backing as the single-process artifact: bounded
        # per-request total-latency samples for the pool p99 rows
        "samples": {"serve_pool_total_ms": _bounded_samples(
            [1e3 * r.total_s for r in served if r.total_s is not None],
            SAMPLE_CAP, load.seed)},
    }
    if spec.name == "serve-smoke":
        extra["smoke"] = ("smoke-bucket pool run: pipeline-shaped, "
                          "workload reduced — NOT a performance capture")
    admitted = max(1, acct["admitted"])
    return {
        "kind": "serve_pool",
        "schema_version": POOL_SCHEMA_VERSION,
        "run_id": load.run_id,
        "metric": "serve_pool_throughput_rps",
        "value": throughput,
        "unit": "req/s",
        "vs_baseline": 1.0,
        "wall_s": round(wall_s, 4),
        # same honesty flag as the single-process artifact: a run the
        # pool fully kept up with measured the LOAD, not the ceiling
        "offered_limited": bool(acct["rejected"] == 0
                                and acct["expired"] == 0),
        "requests": acct,
        "availability": router.availability(),
        "hedge": {
            "hedged": acct["hedged"],
            "rate": round(acct["hedged"] / admitted, 4),
            "wins": acct["hedge_wins"],
            "suppressed": acct["duplicates_suppressed"],
        },
        "latency_ms": lat,
        "pool": {
            "n_workers": cfg.n_workers,
            "ready_workers_end": len(ready),
            "kills": summary["kills"],
            "restarts": summary["restarts"],
            "rolls_completed": summary["rolls_completed"],
            "events": summary["events"][:200],
        },
        "workers": workers,
        "compile": {
            "in_window_fresh_compiles": fresh,
            "note": "sum of per-worker backend_compiles deltas since each "
                    "worker's own warmup snapshot: 0 = no worker compiled "
                    "inside the serving window (warm-before-ready held "
                    "across spawns, restarts, and rolls)",
        },
        "offered": {
            "schedule": load.schedule,
            "schedule_kind": load.schedule_kind,
            "seed": load.seed,
            "n_arrivals": len(requests),
            "duration_s": round(duration, 4),
            "offered_rps": offered_rps,
            "kinds": list(load.resolved_kinds()),
            "deadline_ms": (None if load.deadline_s is None
                            else round(1e3 * load.deadline_s, 3)),
            "class_mix": {name: w for name, w in load.mix()},
        },
        "extra": extra,
    }


# ---------------------------------------------------------------- fabric ---

def run_fabric_loadgen(client, router_sup, worker_sup, load: LoadConfig,
                       concurrent=None) -> dict:
    """Drive the THREE-TIER fabric (loadgen → router replicas → workers)
    with the seeded open-loop schedule, through a
    :class:`~csmom_tpu.serve.fabric.FabricClient`.

    Same determinism contract as :func:`run_loadgen`, plus the pool-level
    cache shape: ``reuse_fraction`` repeats recent panels per kind, so
    the consistent-hash routing has identical requests to land on the
    same worker — the per-worker result cache compounding into a pool
    cache is exactly what the artifact measures.  ``concurrent`` runs
    alongside the stream (the chaos lever: a router SIGKILL plus a
    worker SIGKILL mid-burst is the rehearsed r18 scenario) and the
    books close only after the requests are terminal AND it returned.
    """
    from csmom_tpu.serve.buckets import bucket_spec

    rng = random.Random(load.seed)
    segments = parse_schedule(load.schedule)
    offsets = arrival_offsets(segments, rng)
    spec = bucket_spec(worker_sup.config.profile)
    max_assets = min(load.max_assets or spec.max_assets, spec.max_assets)
    mix = load.mix()
    kinds = list(load.resolved_kinds())
    recent: dict = {k: [] for k in kinds}

    state = {"epoch": 1 if load.version_bumps > 0 else None}
    bump_at = sorted(
        max(1, round(len(offsets) * (k + 1) / (load.version_bumps + 1)))
        for k in range(load.version_bumps)
    ) if load.version_bumps > 0 else []

    def submit_arrival(i):
        if bump_at and i == bump_at[0]:
            bump_at.pop(0)
            state["epoch"] += 1
            # a panel-version bump reaches the workers per request (the
            # version rides the wire); old-epoch cache entries can only
            # be refused, never served — the stale_hits == 0 schema rule
            for pool in recent.values():
                pool.clear()
        kind = rng.choice(kinds)
        pool = recent[kind]
        if pool and rng.random() < load.reuse_fraction:
            values, mask = pool[rng.randrange(len(pool))]
        else:
            n_assets = rng.randint(2, max_assets)
            values, mask = synth_panel(rng, n_assets, spec.months, kind)
            pool.append((values, mask))
            del pool[:-8]  # a small window of reusable recents per kind
        return client.submit(
            kind, values, mask, priority=_pick_class(mix, rng),
            deadline_s=load.deadline_s, panel_version=state["epoch"])

    # the fabric drain allows 90s (vs the pool's 60): a double kill can
    # park a request behind TWO tiers' respawns before it settles
    requests, wall_s = _open_loop_drive(offsets, submit_arrival,
                                        concurrent, 90.0, "fabric")
    return build_fabric_artifact(client, router_sup, worker_sup, load,
                                 requests, wall_s)


def _fleet_block(sup, stats: list) -> dict:
    """One tier's fleet evidence (router or worker supervisor)."""
    summary = sup.summary()
    return {
        "n_slots": sup.config.n_workers,
        "ready_end": sum(1 for s in stats if s.get("state") == "ready"),
        "kills": summary["kills"],
        "restarts": summary["restarts"],
        "rolls_completed": summary["rolls_completed"],
        "events": summary["events"][:200],
    }


def _worker_cache_aggregate(worker_stats: list) -> dict:
    """The fleet-wide worker cache book: sums across every REPORTING
    worker, with the non-reporting slots NAMED (a corpse's book died
    with it — the client tier's ``served_cache_hits`` is the count that
    survives, these sums are the per-worker evidence)."""
    agg = {k: 0 for k in ("hits", "misses", "lookups", "stale_hits",
                          "stale_blocked", "stale_put_refused",
                          "inserts", "evictions", "invalidated")}
    lost = []
    reporting = 0
    for w in worker_stats:
        cache = w.get("cache")
        if not isinstance(cache, dict):
            lost.append(f"{w.get('worker_id')}: {w.get('state')}")
            continue
        reporting += 1
        for k in agg:
            v = cache.get(k)
            if isinstance(v, int) and not isinstance(v, bool):
                agg[k] += v
    agg["reporting"] = reporting
    agg["lost"] = lost
    return agg


def build_fabric_artifact(client, router_sup, worker_sup,
                          load: LoadConfig, requests: list,
                          wall_s: float) -> dict:
    """The SERVE_FABRIC artifact: the CLIENT tier's closed books (the
    outermost ledger — the one a SIGKILLed replica cannot take with it),
    per-replica router books, the worker fleet, and the pool-level cache
    rate the consistent-hash routing exists to produce."""
    acct = client.accounting()
    served = [r for r in requests if r.state == "served"]
    throughput = round(acct["served"] / wall_s, 3) if wall_s > 0 else 0.0
    segments = parse_schedule(load.schedule)
    duration = schedule_duration_s(segments)
    offered_rps = round(len(requests) / duration, 3) if duration else 0.0
    router_stats = router_sup.router_stats()
    worker_stats = worker_sup.worker_stats()
    fresh = _pool_fresh_compiles(worker_stats)
    cache_agg = _worker_cache_aggregate(worker_stats)
    pool_hit_rate = (round(acct["served_cache_hits"] / acct["served"], 4)
                     if acct["served"] else 0.0)

    # router-tier hedge sums across the replicas still standing; a dead
    # replica's books are reported lost, and the hedged SERVED count the
    # client observed is the number that cannot die with a corpse
    r_hedged = r_wins = r_suppressed = 0
    r_lost = []
    for r in router_stats:
        a = r.get("accounting")
        if isinstance(a, dict):
            r_hedged += a.get("hedged", 0)
            r_wins += a.get("hedge_wins", 0)
            r_suppressed += a.get("duplicates_suppressed", 0)
        else:
            r_lost.append(f"{r.get('router_id')}: {r.get('state')}")
    admitted = max(1, acct["admitted"])

    platform = None
    for h in worker_sup.handles:
        rep = h.ready_report or {}
        if isinstance(rep.get("platform"), str):
            platform = rep["platform"]
            break
    from csmom_tpu.serve.buckets import bucket_spec

    wcfg = worker_sup.config
    spec = bucket_spec(wcfg.profile)
    scheme = "tcp" if wcfg.transport == "tcp" else "unix"
    workload = (
        f"fabric open-loop {load.schedule} rps seed {load.seed}, "
        f"{'/'.join(load.resolved_kinds())} mix, "
        f"{router_sup.config.n_workers} routers x {wcfg.n_workers} "
        f"workers over {scheme}, buckets "
        f"B({','.join(map(str, spec.batch_buckets))})x"
        f"A({','.join(map(str, spec.asset_buckets))})x{spec.months}m "
        f"({spec.dtype}, {wcfg.engine} engine)"
    )
    extra = {
        "platform": platform,
        "engine": wcfg.engine,
        "workload": workload,
        "cache_version": worker_sup.expect_cache_version,
        # the persistent transport's evidence (ISSUE 15): client-tier
        # channel books — reuses >> dials is what erased the r18
        # connection-per-request tail; per-replica books ride in the
        # router stats ("channels" blocks)
        "client_channels": (client.channels.stats()
                            if hasattr(client, "channels") else None),
        "samples": {"serve_fabric_total_ms": _bounded_samples(
            [1e3 * r.total_s for r in served if r.total_s is not None],
            SAMPLE_CAP, load.seed)},
    }
    if spec.name == "serve-smoke":
        extra["smoke"] = ("smoke-bucket fabric run: pipeline-shaped, "
                          "workload reduced — NOT a performance capture")
    # observatory provenance (ISSUE 20 satellite): an armed fleet
    # observatory costs ~+0.3-0.4 ms p50 at steady 25 rps but +5-13 ms
    # p50 under the 240-300 rps bursts of the committed schedule (A/B
    # measured at r20 — the cost is distributed across client span
    # recording, the demand hook, and in-router emitters, not one hot
    # line).  Recording the state lets the ledger footnote the latency
    # rows mechanically instead of leaving the r19->r20 p50 step (28.6
    # -> 49.9) to look like an unexplained regression.
    from csmom_tpu.obs import fleet as obs_fleet
    extra["observatory_armed"] = bool(obs_fleet.armed())
    return {
        "kind": "serve_fabric",
        "schema_version": FABRIC_SCHEMA_VERSION,
        "run_id": load.run_id,
        "metric": "serve_fabric_throughput_rps",
        "value": throughput,
        "unit": "req/s",
        "vs_baseline": 1.0,
        "wall_s": round(wall_s, 4),
        "offered_limited": bool(acct["rejected"] == 0
                                and acct["expired"] == 0),
        "transport": {
            "scheme": scheme,
            "routers": router_sup.config.n_workers,
            "workers": wcfg.n_workers,
        },
        "requests": acct,
        "availability": client.availability(),
        "cache": {
            # the fabric headline: hit rate at POOL level, counted at
            # the client (a worker corpse cannot take it along), vs the
            # r15 per-worker baseline the hash routing had to beat
            "pool_hit_rate": pool_hit_rate,
            "served_cache_hits": acct["served_cache_hits"],
            "served": acct["served"],
            "per_worker_baseline": R15_PER_WORKER_HIT_RATE,
            "workers": cache_agg,
        },
        "hedge": {
            "served_hedged": acct["served_hedged"],
            "rate": round(acct["served_hedged"] / admitted, 4),
            "router_tier": {
                "hedged": r_hedged,
                "wins": r_wins,
                "suppressed": r_suppressed,
                "books_lost": r_lost,
            },
        },
        "latency_ms": {"total": _percentiles(
            [r.total_s for r in served if r.total_s is not None])},
        "routers": {
            "replicas": router_stats,
            **_fleet_block(router_sup, router_stats),
        },
        "workers": {
            "stats": worker_stats,
            **_fleet_block(worker_sup, worker_stats),
        },
        "compile": {
            "in_window_fresh_compiles": fresh,
            "note": "sum of per-worker backend_compiles deltas since "
                    "each worker's own warmup snapshot: 0 = no worker "
                    "compiled inside the serving window (router "
                    "replicas hold no compiled world at all)",
        },
        "offered": {
            "schedule": load.schedule,
            "schedule_kind": load.schedule_kind,
            "seed": load.seed,
            "n_arrivals": len(requests),
            "duration_s": round(duration, 4),
            "offered_rps": offered_rps,
            "kinds": list(load.resolved_kinds()),
            "deadline_ms": (None if load.deadline_s is None
                            else round(1e3 * load.deadline_s, 3)),
            "class_mix": {name: w for name, w in load.mix()},
            "reuse_fraction": load.reuse_fraction,
            "version_bumps": load.version_bumps,
        },
        "extra": extra,
    }


def write_artifact(out_dir: str, obj: dict, prefix: str = "SERVE") -> str:
    """Atomically land ``<prefix>_<run>.json``; returns the path.  Pool
    artifacts pass ``prefix="SERVE_POOL"`` (same committable-name rule:
    only ``_rNN`` names are round evidence)."""
    name = f"{prefix}_{obj['run_id']}.json"
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path
