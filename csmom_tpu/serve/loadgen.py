"""Deterministic open-loop load generator + the SERVE artifact writer.

Open-loop means arrivals fire on the SCHEDULE's clock, not the
service's: a slow service does not throttle the generator, so overload
shows up as real queue growth, expiry, and backpressure rejection —
exactly the degradation surface the serve layer exists to manage.  (A
closed-loop generator that waits for each response would hide the knee:
coordinated omission.)

Determinism: one seeded ``random.Random`` drives everything — arrival
times (exponential inter-arrivals per schedule segment: Poisson
traffic), endpoint mix, universe sizes, priorities, and the synthetic
panels — so a rehearse scenario or a regression hunt replays the exact
request stream from ``(schedule, seed)`` alone.

The run lands as ``SERVE_<run>.json``: throughput headline, request
accounting (the served + rejected + expired == admitted invariant is IN
the schema — :mod:`csmom_tpu.chaos.invariants` kind ``serve`` refuses an
artifact whose books do not balance), p50/p95/p99 queue / service /
total latency, the batch-size histogram with the padding overhead, and
the in-window fresh-compile count.  :mod:`csmom_tpu.obs.ledger` ingests
these rows (``serve_throughput_rps``, ``serve_p99_ms``, ...), so serve
performance joins the cross-run regression gate like every bench wall.

Naming rule (the TELEMETRY rule, extended): only round artifacts
(``SERVE_rNN.json``) are committable evidence; ``SERVE_smoke*.json`` /
``SERVE_rehearse*.json`` are regenerated per run and gitignored.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
import time

import numpy as np

from csmom_tpu.serve.buckets import ENDPOINTS
from csmom_tpu.serve.service import ServeConfig, SignalService
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["LoadConfig", "arrival_offsets", "build_artifact",
           "build_pool_artifact", "parse_schedule", "run_loadgen",
           "run_pool_loadgen", "synth_panel", "write_artifact"]

SCHEMA_VERSION = 1
POOL_SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Segment:
    duration_s: float
    rps: float


def parse_schedule(spec: str) -> tuple:
    """``"2x25,3x60"`` -> (Segment(2, 25), Segment(3, 60)): run 2 s at
    25 req/s, then 3 s at 60 req/s."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            dur, _, rate = part.partition("x")
            out.append(Segment(float(dur), float(rate)))
        except ValueError:
            raise ValueError(
                f"bad schedule segment {part!r}: use DURxRPS, e.g. 2x25"
            ) from None
    if not out:
        raise ValueError(f"empty schedule {spec!r}")
    return tuple(out)


def arrival_offsets(segments: tuple, rng: random.Random) -> list:
    """Seeded Poisson arrival offsets (seconds from start) covering every
    segment — the deterministic request clock."""
    out: list = []
    t0 = 0.0
    for seg in segments:
        if seg.rps <= 0:
            t0 += seg.duration_s
            continue
        t = t0 + rng.expovariate(seg.rps)
        while t < t0 + seg.duration_s:
            out.append(t)
            t += rng.expovariate(seg.rps)
        t0 += seg.duration_s
    return out


def synth_panel(rng: random.Random, n_assets: int, months: int,
                kind: str) -> tuple:
    """One deterministic request panel: a positive random walk (prices)
    or positive level noise (volume), with a seeded sprinkle of masked
    gaps so the mask path is always exercised."""
    r = np.random.default_rng(rng.getrandbits(32))
    if kind == "turnover":
        values = r.lognormal(mean=12.0, sigma=0.5,
                             size=(n_assets, months)).astype(np.float32)
    else:
        steps = r.normal(0.0, 0.04, size=(n_assets, months)).astype(np.float32)
        values = 100.0 * np.exp(np.cumsum(steps, axis=1), dtype=np.float32)
    mask = r.random((n_assets, months)) > 0.02
    mask[:, 0] = True  # every asset observed at least once, from the start
    values = np.where(mask, values, np.nan).astype(np.float32)
    return values, mask


@dataclasses.dataclass(frozen=True)
class LoadConfig:
    """One load-generation run (everything the artifact must replay)."""

    schedule: str = "2x40"
    seed: int = 0
    kinds: tuple = ENDPOINTS
    deadline_s: float | None = 0.5
    interactive_fraction: float = 0.7
    max_assets: int | None = None     # default: the spec's largest bucket
    run_id: str = "smoke"


def _percentiles(samples: list) -> dict:
    """Nearest-rank p50/p95/p99 in milliseconds (None when unobserved).

    Nearest-rank is ``ceil(q*N) - 1`` (0-based): with N=2 the p50 is the
    FIRST sample, with N=100 the p99 is the 99th — ``int(q*N)`` would be
    one rank high exactly when q*N is integral, a bias that shifts with
    sample count and would feed the regression gate noise."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None}
    s = sorted(samples)

    def pick(q):
        return round(1e3 * s[max(0, math.ceil(q * len(s)) - 1)], 3)

    return {"p50": pick(0.50), "p95": pick(0.95), "p99": pick(0.99)}


def run_loadgen(service: SignalService, load: LoadConfig) -> dict:
    """Drive ``service`` with the seeded open-loop schedule; returns the
    artifact object (not yet written).

    The service must be started; it is drained and stopped before the
    books are closed, so the accounting invariant is evaluated on a
    quiet queue.
    """
    rng = random.Random(load.seed)
    segments = parse_schedule(load.schedule)
    offsets = arrival_offsets(segments, rng)
    spec = service.spec
    max_assets = min(load.max_assets or spec.max_assets, spec.max_assets)

    requests = []
    t_start = mono_now_s()
    for off in offsets:
        delay = (t_start + off) - mono_now_s()
        if delay > 0:
            time.sleep(delay)  # open loop: the schedule's clock rules
        kind = rng.choice(list(load.kinds))
        n_assets = rng.randint(2, max_assets)
        values, mask = synth_panel(rng, n_assets, spec.months, kind)
        prio = ("interactive" if rng.random() < load.interactive_fraction
                else "batch")
        requests.append(service.submit(kind, values, mask, priority=prio,
                                       deadline_s=load.deadline_s))
    # close the books: wait for every request to reach a terminal state,
    # then drain-stop the worker
    give_up = mono_now_s() + 30.0
    for r in requests:
        r.wait(timeout=max(0.0, give_up - mono_now_s()))
    service.stop(drain=True)
    wall_s = mono_now_s() - t_start
    return build_artifact(service, load, requests, wall_s)


def _platform(service: SignalService) -> str:
    if service.engine.name == "stub":
        return "stub"
    import jax

    return jax.default_backend()


def build_artifact(service: SignalService, load: LoadConfig,
                   requests: list, wall_s: float) -> dict:
    """The SERVE artifact: headline + accounting + latency + batches."""
    acct = service.accounting()
    served = [r for r in requests if r.state == "served"]
    throughput = round(acct["served"] / wall_s, 3) if wall_s > 0 else 0.0
    lat = {
        "queue": _percentiles(
            [r.queue_wait_s for r in requests if r.queue_wait_s is not None]),
        "service": _percentiles(
            [r.service_s for r in served if r.service_s is not None]),
        "total": _percentiles(
            [r.total_s for r in served if r.total_s is not None]),
    }
    fresh = service.fresh_compiles()
    spec = service.spec
    workload = (
        f"open-loop {load.schedule} rps seed {load.seed}, "
        f"{'/'.join(load.kinds)} mix, buckets "
        f"B({','.join(map(str, spec.batch_buckets))})x"
        f"A({','.join(map(str, spec.asset_buckets))})x{spec.months}m "
        f"({spec.dtype}, {service.config.engine} engine)"
    )
    extra = {
        "platform": _platform(service),
        "engine": service.config.engine,
        "workload": workload,
        "capacity": service.config.capacity,
        "max_wait_ms": round(1e3 * service.config.max_wait_s, 3),
        "warm_report": service.warm_report,
    }
    if service.spec.name == "serve-smoke":
        extra["smoke"] = ("smoke-bucket run: pipeline-shaped, workload "
                          "reduced — NOT a performance capture")
    return {
        "kind": "serve",
        "schema_version": SCHEMA_VERSION,
        "run_id": load.run_id,
        "metric": "serve_throughput_rps",
        "value": throughput,
        "unit": "req/s",
        "vs_baseline": 1.0,
        "wall_s": round(wall_s, 4),
        "requests": acct,
        "latency_ms": lat,
        "batches": service.batch_stats(),
        "compile": {
            "in_window_fresh_compiles": fresh,
            "note": "backend_compiles delta since the pre-serving warmup "
                    "snapshot: 0 = every dispatch hit a warmed bucket "
                    "shape (the padding contract held)",
        },
        "offered": {
            "schedule": load.schedule,
            "seed": load.seed,
            "n_arrivals": len(requests),
            "kinds": list(load.kinds),
            "deadline_ms": (None if load.deadline_s is None
                            else round(1e3 * load.deadline_s, 3)),
            "interactive_fraction": load.interactive_fraction,
        },
        "extra": extra,
    }


# ------------------------------------------------------------------ pool ---

def run_pool_loadgen(router, supervisor, load: LoadConfig,
                     concurrent=None) -> dict:
    """Drive the multi-process pool with the SAME seeded open-loop
    schedule as :func:`run_loadgen`, through the router.

    The pool is NOT stopped here (the caller may still want to kill /
    roll / inspect workers); the books close once every admitted request
    reaches a terminal state — which the router guarantees per request,
    so waiting on the handles IS the drain.

    ``concurrent`` (optional callable) runs in a thread alongside the
    load stream — the chaos lever for "do X UNDER load" scenarios
    (rolling restart, a mid-run kill).  The artifact is built only after
    BOTH the load's requests are terminal AND ``concurrent`` returned,
    so worker stats and fleet events are read from a settled pool."""
    import threading

    rng = random.Random(load.seed)
    segments = parse_schedule(load.schedule)
    offsets = arrival_offsets(segments, rng)
    spec = router.spec
    max_assets = min(load.max_assets or spec.max_assets, spec.max_assets)

    side = None
    side_exc: list = []
    if concurrent is not None:
        def _side():
            try:
                concurrent()
            except BaseException as e:  # surfaced after join, not lost
                side_exc.append(e)

        side = threading.Thread(target=_side, daemon=True)

    requests = []
    t_start = mono_now_s()
    if side is not None:
        side.start()
    for off in offsets:
        delay = (t_start + off) - mono_now_s()
        if delay > 0:
            time.sleep(delay)  # open loop: the schedule's clock rules
        kind = rng.choice(list(load.kinds))
        n_assets = rng.randint(2, max_assets)
        values, mask = synth_panel(rng, n_assets, spec.months, kind)
        prio = ("interactive" if rng.random() < load.interactive_fraction
                else "batch")
        requests.append(router.submit(kind, values, mask, priority=prio,
                                      deadline_s=load.deadline_s))
    give_up = mono_now_s() + 60.0
    for r in requests:
        r.wait(timeout=max(0.0, give_up - mono_now_s()))
    wall_s = mono_now_s() - t_start
    if side is not None:
        # the artifact's "built after a settled pool" contract: give the
        # concurrent action its OWN generous budget (a roll can outlast
        # the request drain), and refuse to build from a still-mutating
        # fleet rather than land a mid-roll snapshot as evidence
        side.join(timeout=300.0)
        if side.is_alive():
            raise RuntimeError(
                "concurrent action still running after 300s — refusing "
                "to build the pool artifact from an unsettled fleet")
        if side_exc:
            raise side_exc[0]
    return build_pool_artifact(router, supervisor, load, requests, wall_s)


def _pool_fresh_compiles(workers: list):
    """Aggregate in-window fresh compiles across the fleet: the SUM of
    every live worker's count.  A worker that cannot report (dead slot,
    stats error) degrades the total to a reason string — "unknown" must
    never be spelled 0."""
    total = 0
    gaps = []
    for w in workers:
        if w.get("state") != "ready":
            # a replaced slot's history lives in the replacement; a dead/
            # failed slot has no count to contribute — named, not zeroed
            gaps.append(f"{w['worker_id']}: {w.get('state')}")
            continue
        fc = w.get("fresh_compiles")
        if isinstance(fc, int) and not isinstance(fc, bool):
            total += fc
        else:
            gaps.append(f"{w['worker_id']}: {fc!r}")
    if gaps:
        return (f"{total} across reporting workers; not measurable for "
                f"[{'; '.join(gaps)}]")
    return total


def build_pool_artifact(router, supervisor, load: LoadConfig,
                        requests: list, wall_s: float) -> dict:
    """The SERVE_POOL artifact: the router's closed cross-process books,
    hedging/availability headline, and the fleet's evidence."""
    acct = router.accounting()
    served = [r for r in requests if r.state == "served"]
    throughput = round(acct["served"] / wall_s, 3) if wall_s > 0 else 0.0
    lat = {"total": _percentiles(
        [r.total_s for r in served if r.total_s is not None])}
    workers = supervisor.worker_stats()
    summary = supervisor.summary()
    fresh = _pool_fresh_compiles(workers)
    spec = router.spec
    cfg = supervisor.config
    ready = [w for w in workers if w.get("state") == "ready"]
    platform = None
    for h in supervisor.handles:
        rep = h.ready_report or {}
        if isinstance(rep.get("platform"), str):
            platform = rep["platform"]
            break
    workload = (
        f"pool open-loop {load.schedule} rps seed {load.seed}, "
        f"{'/'.join(load.kinds)} mix, {cfg.n_workers} workers, buckets "
        f"B({','.join(map(str, spec.batch_buckets))})x"
        f"A({','.join(map(str, spec.asset_buckets))})x{spec.months}m "
        f"({spec.dtype}, {cfg.engine} engine)"
    )
    extra = {
        "platform": platform,
        "engine": cfg.engine,
        "workload": workload,
        "hedge_policy": {
            "fraction": router.config.hedge_fraction,
            "floor_ms": round(1e3 * router.config.hedge_floor_s, 3),
            "max_attempts": router.config.max_attempts,
        },
        "cache_version": summary["expect_cache_version"],
    }
    if spec.name == "serve-smoke":
        extra["smoke"] = ("smoke-bucket pool run: pipeline-shaped, "
                          "workload reduced — NOT a performance capture")
    admitted = max(1, acct["admitted"])
    return {
        "kind": "serve_pool",
        "schema_version": POOL_SCHEMA_VERSION,
        "run_id": load.run_id,
        "metric": "serve_pool_throughput_rps",
        "value": throughput,
        "unit": "req/s",
        "vs_baseline": 1.0,
        "wall_s": round(wall_s, 4),
        "requests": acct,
        "availability": router.availability(),
        "hedge": {
            "hedged": acct["hedged"],
            "rate": round(acct["hedged"] / admitted, 4),
            "wins": acct["hedge_wins"],
            "suppressed": acct["duplicates_suppressed"],
        },
        "latency_ms": lat,
        "pool": {
            "n_workers": cfg.n_workers,
            "ready_workers_end": len(ready),
            "kills": summary["kills"],
            "restarts": summary["restarts"],
            "rolls_completed": summary["rolls_completed"],
            "events": summary["events"][:200],
        },
        "workers": workers,
        "compile": {
            "in_window_fresh_compiles": fresh,
            "note": "sum of per-worker backend_compiles deltas since each "
                    "worker's own warmup snapshot: 0 = no worker compiled "
                    "inside the serving window (warm-before-ready held "
                    "across spawns, restarts, and rolls)",
        },
        "offered": {
            "schedule": load.schedule,
            "seed": load.seed,
            "n_arrivals": len(requests),
            "kinds": list(load.kinds),
            "deadline_ms": (None if load.deadline_s is None
                            else round(1e3 * load.deadline_s, 3)),
            "interactive_fraction": load.interactive_fraction,
        },
        "extra": extra,
    }


def write_artifact(out_dir: str, obj: dict, prefix: str = "SERVE") -> str:
    """Atomically land ``<prefix>_<run>.json``; returns the path.  Pool
    artifacts pass ``prefix="SERVE_POOL"`` (same committable-name rule:
    only ``_rNN`` names are round evidence)."""
    name = f"{prefix}_{obj['run_id']}.json"
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path
