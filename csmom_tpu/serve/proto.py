"""Wire protocol for the serving fabric: framed JSON + raw array payloads.

The routers, the health probes, and the workers speak one tiny protocol
over a stream socket: a 4-byte big-endian frame length, then a
length-prefixed JSON header, then the concatenated raw bytes of any
numpy arrays the header declares (name / dtype / shape / nbytes, in
order).  Binary payloads because a request panel is up to ``128 x 60``
float32 — base64-in-JSON would inflate every dispatch by a third for
nothing; JSON headers because every *control* field stays greppable in a
socket dump.

**Addresses** (the r18 horizontal-fabric round): every connect/listen
takes an address string —

=====================  ==================================================
address                meaning
=====================  ==================================================
``unix:/path/w0.sock``  an ``AF_UNIX`` stream socket (same host)
``tcp:host:port``       an ``AF_INET`` stream socket (cross-host)
``/path/w0.sock``       bare paths stay unix (r11 back-compat)
=====================  ==================================================

so the same supervisor/router/worker machinery runs one-host pools over
unix sockets AND multi-container fabrics over TCP by changing nothing
but the address strings.

Design constraints this encodes:

- **Bounded**: a frame larger than ``MAX_FRAME_BYTES`` is refused with a
  pointed message AT READ TIME, before the payload is allocated (a
  corrupt or hostile length prefix must never become a gigabyte
  ``bytearray``), and array specs are validated against the declared
  byte count before a single array is materialized.
- **Receive deadlines**: every frame read carries a deadline
  (``RECV_DEADLINE_S`` default).  ``_recv_exact`` re-arms the socket
  timeout per read from the REMAINING budget, so a stalled — or
  byte-trickling — peer raises a pointed :class:`ProtocolError` when the
  budget runs out instead of resetting a per-read timeout forever.  The
  r11 ``_recv_exact`` blocked as long as the peer kept the socket alive;
  a wedged worker could pin a router thread indefinitely.
- **Connection-per-request**: the router opens one connection per
  dispatch attempt.  That keeps hedging trivial (two attempts are two
  independent sockets; abandoning one cannot corrupt the other's
  framing) and makes a worker crash legible — the kernel resets the
  socket, the router sees ``ConnectionError``/EOF, and the attempt
  fails fast instead of waiting out a deadline on a corpse.
- **Stdlib + numpy only, no jax**: health probes and the supervisor's
  monitor loop must stay importable in processes that never touch a
  device (the same split as ``serve/buckets.py``).

**Chaos** (the ``serve.transport`` checkpoint): every ``score``-op
round trip visits ``serve.transport`` before connecting, so a fault
plan can break the WIRE instead of a process — ``conn_reset`` raises a
connection reset into the caller's failover handling, ``net_delay``
stalls the transport by ``CSMOM_CHAOS_NET_DELAY_S`` (an induced
straggler: the hedging policy is what the scenario then measures), and
``partition`` cuts THIS process off from the peer address it was about
to dial for ``CSMOM_CHAOS_PARTITION_S`` seconds (every connect to that
peer fails instantly until the partition heals — the router losing a
worker host mid-burst).  Probe/lifecycle ops do not visit the
checkpoint, so supervisor probes keep deterministic hit counts.

Request tracing rides the header, not the framing: a ``score`` frame may
carry a ``trace`` entry (trace id, endpoint, SLO class, panel version —
identity only, never timestamps, so each process keeps its own clock and
stitching works on durations), and the peer's reply then carries a
``trace_half`` entry with its server-side stage chain.  The protocol
itself is unchanged — untraced deployments serialize not one extra byte,
and an old worker simply ignores the field (see
:mod:`csmom_tpu.obs.trace` for the stitching contract).

Ops the worker answers (see :mod:`csmom_tpu.serve.worker`); the router
replica answers the same lifecycle set (see
:mod:`csmom_tpu.serve.router`):

=========  ==================================================
op         meaning
=========  ==================================================
ping       liveness: "the process responds" — no service state
ready      readiness report (warm + self-probe + cache version)
score      one scoring request (arrays: values, mask)
stats      accounting / batch stats / fresh-compile count
drain      stop admitting, drain the queue, report accounting
stop       drain, then exit the process
=========  ==================================================
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time

import numpy as np

from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["MAX_FRAME_BYTES", "RECV_DEADLINE_S", "ProtocolError",
           "connect", "free_tcp_port", "listen", "parse_address",
           "recv_msg", "request", "send_msg", "unlink_address"]

# largest legal frame: the biggest production micro-panel is ~30 KB, so
# 32 MB is three orders of magnitude of headroom while still refusing a
# garbage length prefix before it can exhaust memory
MAX_FRAME_BYTES = 32 * 1024 * 1024

# total budget for receiving ONE frame (header + payload).  Generous
# against any honest peer (a full frame is one sendall away), tight
# against a wedged one: a worker that stops mid-frame costs the router
# this much wall, never a thread forever.
RECV_DEADLINE_S = 30.0

_LEN = struct.Struct("!I")

# chaos partition state (the `partition` action at serve.transport):
# peer address -> monotonic heal time.  Process-local on purpose — a
# partition separates THIS process from a peer host, not the world.
_PARTITION_LOCK = threading.Lock()
_PARTITIONED: dict = {}

# fault-duration knobs (chaos actions are caller-interpreted and the
# checkpoint returns only the action name, so durations ride the same
# env channel the plans do)
PARTITION_ENV = "CSMOM_CHAOS_PARTITION_S"
NET_DELAY_ENV = "CSMOM_CHAOS_NET_DELAY_S"
_PARTITION_DEFAULT_S = 1.0
_NET_DELAY_DEFAULT_S = 0.25


class ProtocolError(RuntimeError):
    """A malformed frame (bad length, truncated payload, spec mismatch,
    or a receive deadline expiring on a stalled peer)."""


# ------------------------------------------------------------ addresses ---

def parse_address(address: str) -> tuple:
    """``("unix", path)`` or ``("tcp", (host, port))`` for an address
    string.  Bare paths are unix (the r11 spelling); ``tcp:`` needs
    ``host:port`` with an integer port."""
    if address.startswith("unix:"):
        path = address[len("unix:"):]
        if not path:
            raise ValueError(f"empty unix socket path in {address!r}")
        return "unix", path
    if address.startswith("tcp:"):
        rest = address[len("tcp:"):]
        host, sep, port_s = rest.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"bad tcp address {address!r}: use tcp:host:port")
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(
                f"bad tcp port in {address!r}: {port_s!r} is not an "
                "integer") from None
        if not 0 <= port <= 65535:
            raise ValueError(f"tcp port {port} outside [0, 65535]")
        return "tcp", (host, port)
    return "unix", address


def free_tcp_port(host: str = "127.0.0.1") -> int:
    """One currently-free TCP port (bind-to-0 then release).  Classic
    small race with other port grabbers; fine for the loopback fabrics
    the supervisor spawns, where it owns the port range in practice."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return int(s.getsockname()[1])
    finally:
        s.close()


def listen(address: str, backlog: int = 64) -> socket.socket:
    """A bound, listening server socket for ``address`` (unix or tcp).
    Unix paths are unlinked first (a crashed predecessor's stale socket
    file must not block the bind); tcp sets ``SO_REUSEADDR`` for the
    same reason."""
    scheme, target = parse_address(address)
    if scheme == "unix":
        try:
            os.unlink(target)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(target)
    else:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(target)
    srv.listen(backlog)
    return srv


def unlink_address(address: str) -> None:
    """Remove a unix socket path (no-op for tcp) — shutdown hygiene."""
    scheme, target = parse_address(address)
    if scheme == "unix":
        try:
            os.unlink(target)
        except OSError:
            pass


def _partitioned_until(address: str) -> float | None:
    with _PARTITION_LOCK:
        heal_at = _PARTITIONED.get(address)
        if heal_at is None:
            return None
        if mono_now_s() >= heal_at:
            del _PARTITIONED[address]
            return None
        return heal_at


def _chaos_env_s(env: str, default_s: float) -> float:
    """A chaos duration knob from the environment, defaulting on a
    malformed value — a typo'd \"250ms\" must degrade to the default
    fault, not raise an unhandled ValueError through the dispatch
    thread and strand its request non-terminal."""
    raw = os.environ.get(env)
    if not raw:
        return default_s
    try:
        return float(raw)
    except ValueError:
        return default_s


def _chaos_transport(address: str, op: str) -> None:
    """The ``serve.transport`` checkpoint, fired per score-op dial.

    Caller-interpreted actions: ``conn_reset`` raises into the caller's
    existing connection-failure handling; ``net_delay`` sleeps the
    configured straggler delay; ``partition`` cuts this process off from
    ``address`` for the configured window (subsequent dials fail
    instantly until it heals).  An already-armed partition fails the
    dial whether or not a fault fires on this visit.
    """
    from csmom_tpu.chaos.inject import checkpoint

    fired = checkpoint("serve.transport", addr=address, op=op)
    if fired == "partition":
        heal_s = _chaos_env_s(PARTITION_ENV, _PARTITION_DEFAULT_S)
        with _PARTITION_LOCK:
            _PARTITIONED[address] = mono_now_s() + heal_s
    elif fired == "net_delay":
        time.sleep(_chaos_env_s(NET_DELAY_ENV, _NET_DELAY_DEFAULT_S))
    elif fired == "conn_reset":
        raise ConnectionResetError(
            f"chaos conn_reset injected at serve.transport (peer "
            f"{address})")
    if _partitioned_until(address) is not None:
        raise ConnectionRefusedError(
            f"chaos partition: this process is partitioned from "
            f"{address} (heals in <= "
            f"{os.environ.get(PARTITION_ENV, _PARTITION_DEFAULT_S)}s)")


def connect(address: str, timeout_s: float) -> socket.socket:
    """One connected, timeout-armed client socket to a worker/router."""
    scheme, target = parse_address(address)
    family = socket.AF_UNIX if scheme == "unix" else socket.AF_INET
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.settimeout(timeout_s)
    try:
        sock.connect(target)
    except OSError:
        sock.close()
        raise
    return sock


def send_msg(sock: socket.socket, obj: dict, arrays: dict | None = None) -> None:
    """Send one frame: ``obj`` as the JSON header plus raw array bytes.

    ``arrays`` maps name -> ndarray; each is serialized C-contiguous and
    declared in the header's ``_arrays`` spec list so the receiver can
    slice them back without a second round trip.
    """
    specs = []
    blobs = []
    for name, arr in (arrays or {}).items():
        a = np.ascontiguousarray(arr)
        specs.append({"name": name, "dtype": str(a.dtype),
                      "shape": list(a.shape), "nbytes": int(a.nbytes)})
        blobs.append(a.tobytes())
    header = dict(obj)
    header["_arrays"] = specs
    hb = json.dumps(header).encode("utf-8")
    payload = _LEN.pack(len(hb)) + hb + b"".join(blobs)
    if len(payload) > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"frame of {len(payload)} bytes exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES}); split the request")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int, give_up_s: float) -> bytes:
    """Exactly ``n`` bytes from ``sock`` before the ``give_up_s``
    monotonic deadline.  The socket timeout is re-armed per read from
    the REMAINING budget — a peer trickling one byte per timeout window
    used to reset the clock forever; now the total wall is bounded."""
    buf = bytearray()
    while len(buf) < n:
        remaining = give_up_s - mono_now_s()
        if remaining <= 0:
            raise ProtocolError(
                f"receive deadline expired mid-frame ({len(buf)}/{n} "
                "bytes read) — the peer stalled; closing rather than "
                "wedging this thread")
        sock.settimeout(min(remaining, sock.gettimeout() or remaining))
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            raise ProtocolError(
                f"receive deadline expired mid-frame ({len(buf)}/{n} "
                "bytes read) — the peer stalled; closing rather than "
                "wedging this thread") from None
        if not chunk:
            raise ProtocolError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes read) "
                "— the peer died or reset")
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket,
             deadline_s: float = RECV_DEADLINE_S) -> tuple:
    """Receive one frame; returns ``(obj, arrays)``.

    The whole frame (length prefix + header + payload) must arrive
    within ``deadline_s``.  Every declared array is rebuilt from the
    binary tail; a spec whose byte counts do not reconcile with the
    frame is a protocol error, not a best-effort parse — half a panel
    must never score.  The length prefix is judged against
    ``MAX_FRAME_BYTES`` BEFORE any payload allocation: a corrupt or
    hostile prefix costs a pointed refusal, never the allocation it
    names.
    """
    give_up = mono_now_s() + deadline_s
    # _recv_exact re-arms the socket timeout downward per read; restore
    # the caller's timeout afterwards so a later send/receive on the
    # same connection doesn't inherit a near-zero residual budget
    caller_timeout = sock.gettimeout()
    try:
        (total,) = _LEN.unpack(_recv_exact(sock, _LEN.size, give_up))
        if total > MAX_FRAME_BYTES:
            raise ProtocolError(
                f"declared frame length {total} exceeds MAX_FRAME_BYTES "
                f"({MAX_FRAME_BYTES}) — corrupt length prefix?  Refusing "
                "before allocating it")
        payload = _recv_exact(sock, total, give_up)
    finally:
        try:
            sock.settimeout(caller_timeout)
        except OSError:
            pass  # the socket may already be closed/reset
    if len(payload) < _LEN.size:
        raise ProtocolError("frame shorter than its header length prefix")
    (hlen,) = _LEN.unpack(payload[:_LEN.size])
    if _LEN.size + hlen > total:
        raise ProtocolError(
            f"header length {hlen} overruns the {total}-byte frame")
    try:
        obj = json.loads(payload[_LEN.size:_LEN.size + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"unparseable frame header: {e}") from None
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"frame header must be a JSON object, got {type(obj).__name__}")
    specs = obj.pop("_arrays", [])
    arrays: dict = {}
    off = _LEN.size + hlen
    for spec in specs:
        try:
            name = spec["name"]
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(s) for s in spec["shape"])
            nbytes = int(spec["nbytes"])
        except (KeyError, TypeError, ValueError) as e:
            raise ProtocolError(f"bad array spec {spec!r}: {e}") from None
        want = dtype.itemsize * int(np.prod(shape)) if shape else dtype.itemsize
        if nbytes != want or off + nbytes > total:
            raise ProtocolError(
                f"array {name!r} spec inconsistent with frame "
                f"(declared {nbytes} bytes, shape wants {want}, "
                f"{total - off} remain)")
        arrays[name] = np.frombuffer(
            payload[off:off + nbytes], dtype=dtype).reshape(shape).copy()
        off += nbytes
    if off != total:
        raise ProtocolError(
            f"{total - off} trailing bytes after the declared arrays")
    return obj, arrays


def request(address: str, obj: dict, arrays: dict | None = None,
            timeout_s: float = 5.0) -> tuple:
    """One-shot round trip: connect, send, receive one reply, close.

    ``timeout_s`` bounds the connect AND the whole reply receive (the
    receive-deadline contract), so one call can never outwait its
    budget no matter how the peer misbehaves.  ``score`` ops visit the
    ``serve.transport`` chaos checkpoint before dialing.
    """
    if obj.get("op") == "score":
        _chaos_transport(address, "score")
    sock = connect(address, timeout_s)
    try:
        send_msg(sock, obj, arrays)
        return recv_msg(sock, deadline_s=timeout_s)
    finally:
        try:
            sock.close()
        except OSError:
            pass
