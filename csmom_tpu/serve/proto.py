"""Wire protocol for the serving pool: framed JSON + raw array payloads.

The router, the health probes, and the workers speak one tiny protocol
over a local ``AF_UNIX`` stream socket: a 4-byte big-endian frame
length, then a length-prefixed JSON header, then the concatenated raw
bytes of any numpy arrays the header declares (name / dtype / shape /
nbytes, in order).  Binary payloads because a request panel is up to
``128 x 60`` float32 — base64-in-JSON would inflate every dispatch by a
third for nothing; JSON headers because every *control* field stays
greppable in a socket dump.

Design constraints this encodes:

- **Bounded**: a frame larger than ``MAX_FRAME_BYTES`` is refused at
  read time (a corrupt length prefix must not allocate gigabytes), and
  array specs are validated against the declared byte count before a
  single array is materialized.
- **Connection-per-request**: the router opens one connection per
  dispatch attempt.  That keeps hedging trivial (two attempts are two
  independent sockets; abandoning one cannot corrupt the other's
  framing) and makes a worker crash legible — the kernel resets the
  socket, the router sees ``ConnectionError``/EOF, and the attempt
  fails fast instead of waiting out a deadline on a corpse.
- **Stdlib + numpy only, no jax**: health probes and the supervisor's
  monitor loop must stay importable in processes that never touch a
  device (the same split as ``serve/buckets.py``).

Request tracing rides the header, not the framing: a ``score`` frame may
carry a ``trace`` entry (trace id, endpoint, SLO class, panel version —
identity only, never timestamps, so each process keeps its own clock and
stitching works on durations), and the worker's reply then carries a
``trace_half`` entry with its server-side stage chain.  The protocol
itself is unchanged — untraced deployments serialize not one extra byte,
and an old worker simply ignores the field (see
:mod:`csmom_tpu.obs.trace` for the stitching contract).

Ops the worker answers (see :mod:`csmom_tpu.serve.worker`):

=========  ==================================================
op         meaning
=========  ==================================================
ping       liveness: "the process responds" — no service state
ready      readiness report (warm + self-probe + cache version)
score      one scoring request (arrays: values, mask)
stats      accounting / batch stats / fresh-compile count
drain      stop admitting, drain the queue, report accounting
stop       drain, then exit the worker process
=========  ==================================================
"""

from __future__ import annotations

import json
import socket
import struct

import numpy as np

__all__ = ["MAX_FRAME_BYTES", "ProtocolError", "connect", "recv_msg",
           "request", "send_msg"]

# largest legal frame: the biggest production micro-panel is ~30 KB, so
# 32 MB is three orders of magnitude of headroom while still refusing a
# garbage length prefix before it can exhaust memory
MAX_FRAME_BYTES = 32 * 1024 * 1024

_LEN = struct.Struct("!I")


class ProtocolError(RuntimeError):
    """A malformed frame (bad length, truncated payload, spec mismatch)."""


def connect(socket_path: str, timeout_s: float) -> socket.socket:
    """One connected, timeout-armed client socket to a worker/router."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout_s)
    try:
        sock.connect(socket_path)
    except OSError:
        sock.close()
        raise
    return sock


def send_msg(sock: socket.socket, obj: dict, arrays: dict | None = None) -> None:
    """Send one frame: ``obj`` as the JSON header plus raw array bytes.

    ``arrays`` maps name -> ndarray; each is serialized C-contiguous and
    declared in the header's ``_arrays`` spec list so the receiver can
    slice them back without a second round trip.
    """
    specs = []
    blobs = []
    for name, arr in (arrays or {}).items():
        a = np.ascontiguousarray(arr)
        specs.append({"name": name, "dtype": str(a.dtype),
                      "shape": list(a.shape), "nbytes": int(a.nbytes)})
        blobs.append(a.tobytes())
    header = dict(obj)
    header["_arrays"] = specs
    hb = json.dumps(header).encode("utf-8")
    payload = _LEN.pack(len(hb)) + hb + b"".join(blobs)
    if len(payload) > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"frame of {len(payload)} bytes exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES}); split the request")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ProtocolError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes read) "
                "— the peer died or reset")
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket) -> tuple:
    """Receive one frame; returns ``(obj, arrays)``.

    Every declared array is rebuilt from the binary tail; a spec whose
    byte counts do not reconcile with the frame is a protocol error, not
    a best-effort parse — half a panel must never score.
    """
    (total,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if total > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"declared frame length {total} exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES}) — corrupt length prefix?")
    payload = _recv_exact(sock, total)
    if len(payload) < _LEN.size:
        raise ProtocolError("frame shorter than its header length prefix")
    (hlen,) = _LEN.unpack(payload[:_LEN.size])
    if _LEN.size + hlen > total:
        raise ProtocolError(
            f"header length {hlen} overruns the {total}-byte frame")
    try:
        obj = json.loads(payload[_LEN.size:_LEN.size + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"unparseable frame header: {e}") from None
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"frame header must be a JSON object, got {type(obj).__name__}")
    specs = obj.pop("_arrays", [])
    arrays: dict = {}
    off = _LEN.size + hlen
    for spec in specs:
        try:
            name = spec["name"]
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(s) for s in spec["shape"])
            nbytes = int(spec["nbytes"])
        except (KeyError, TypeError, ValueError) as e:
            raise ProtocolError(f"bad array spec {spec!r}: {e}") from None
        want = dtype.itemsize * int(np.prod(shape)) if shape else dtype.itemsize
        if nbytes != want or off + nbytes > total:
            raise ProtocolError(
                f"array {name!r} spec inconsistent with frame "
                f"(declared {nbytes} bytes, shape wants {want}, "
                f"{total - off} remain)")
        arrays[name] = np.frombuffer(
            payload[off:off + nbytes], dtype=dtype).reshape(shape).copy()
        off += nbytes
    if off != total:
        raise ProtocolError(
            f"{total - off} trailing bytes after the declared arrays")
    return obj, arrays


def request(socket_path: str, obj: dict, arrays: dict | None = None,
            timeout_s: float = 5.0) -> tuple:
    """One-shot round trip: connect, send, receive one reply, close."""
    sock = connect(socket_path, timeout_s)
    try:
        send_msg(sock, obj, arrays)
        return recv_msg(sock)
    finally:
        try:
            sock.close()
        except OSError:
            pass
