"""Wire protocol for the serving fabric: framed JSON + raw array payloads
over PERSISTENT, MULTIPLEXED channels.

The routers, the health probes, and the workers speak one tiny protocol
over a stream socket: a 4-byte big-endian frame length, then a
length-prefixed JSON header, then the concatenated raw bytes of any
numpy arrays the header declares (name / dtype / shape / nbytes, in
order).  Binary payloads because a request panel is up to ``128 x 60``
float32 — base64-in-JSON would inflate every dispatch by a third for
nothing; JSON headers because every *control* field stays greppable in a
socket dump.

**Addresses** (the r18 horizontal-fabric round): every connect/listen
takes an address string —

=====================  ==================================================
address                meaning
=====================  ==================================================
``unix:/path/w0.sock``  an ``AF_UNIX`` stream socket (same host)
``tcp:host:port``       an ``AF_INET`` stream socket (cross-host)
``/path/w0.sock``       bare paths stay unix (r11 back-compat)
=====================  ==================================================

so the same supervisor/router/worker machinery runs one-host pools over
unix sockets AND multi-container fabrics over TCP by changing nothing
but the address strings.

**Persistent multiplexed channels** (the r19 round — this file's hot
path).  r18's connection-per-request design put a fresh TCP connect, a
full JSON header encode, and two payload copies on EVERY hop of EVERY
request; the r18 trace book measured the bill at
``trace_stage_transport_p99_ms = 742 ms`` under burst.  The request
path now runs on long-lived channels:

- :class:`Channel` — one connected stream socket (``TCP_NODELAY`` +
  ``SO_KEEPALIVE``), one writer lock serializing frames out, and
  LEADER/FOLLOWER demultiplexing in: the first waiting dispatcher
  takes the read baton, parses every arriving frame, and delivers
  each reply to the waiter registered under its echoed ``_mux`` id —
  so many in-flight requests interleave on ONE socket (the transport
  shape continuous batching assumes, PAPERS [4]), an out-of-order
  reply settles the right waiter by construction, a solo request's
  reply wakes its own thread straight from the kernel (no dedicated
  reader thread, no extra scheduler hop per reply), and an idle
  channel parks no thread at all.  A reply with no ``_mux`` settles
  the OLDEST pending dispatch (a legacy one-shot peer answers in
  order).
- :class:`ChannelPool` — the per-process registry: bounded channels per
  peer with a per-channel pipeline depth (a saturated channel gets a
  sibling dialed, up to the bound — one channel is one serve loop at
  the peer, and a burst needs a few in parallel), lazy idle reaping,
  and health-checked reconnect with exponential backoff (a peer that
  refuses dials fails fast until the backoff expires instead of
  burning a connect timeout per request).  A request that fails on a
  REUSED channel before its reply started is retried once on a
  freshly dialed channel — a pooled channel whose peer restarted
  between requests must cost a redial, not a failover.
- **Low-copy payload path**: array specs are serialized once per
  ``(name, dtype, shape)`` and cached; the frame goes out as a
  scatter-gather ``sendmsg`` over the header bytes and each array's
  own buffer (no ``b"".join`` copy of the payload); the receive side
  reads into a reusable preallocated buffer via ``recv_into`` instead
  of accreting per-``recv`` chunks.  :class:`HeaderTemplate`
  pre-encodes a request's invariant header fields so the per-request
  encode is a splice of the few variable ones.

Design constraints this keeps from r18:

- **Bounded**: a frame larger than ``MAX_FRAME_BYTES`` is refused with a
  pointed message AT READ TIME, before the payload is allocated (a
  corrupt or hostile length prefix must never become a gigabyte
  buffer), and array specs are validated against the declared byte
  count before a single array is materialized.
- **Receive deadlines**: once a frame STARTS arriving, the whole frame
  must land within ``deadline_s`` (``RECV_DEADLINE_S`` default) — the
  socket timeout is re-armed per read from the REMAINING budget, so a
  stalled or byte-trickling peer raises a pointed
  :class:`ProtocolError` instead of wedging the reader.  On a
  persistent channel that error kills the channel and reason-closes
  every in-flight request on it.  IDLE is different from stalled: a
  channel waiting between frames is healthy, so the reader waits for
  the first byte under a separate (long) idle budget.
- **One-shot compatibility**: :func:`request_once` keeps the r11–r18
  connect/send/receive/close shape for probes and one-shot admin ops
  (ping / ready / stats / drain / stop) — the ``dial-discipline`` lint
  rule bars it from the request hot paths, where the pool is the only
  legal transport.
- **Stdlib + numpy only, no jax**: health probes and the supervisor's
  monitor loop must stay importable in processes that never touch a
  device (the same split as ``serve/buckets.py``).

**Chaos** (the ``serve.transport`` checkpoint): every ``score``
dispatch visits ``serve.transport`` before touching the wire, so a
fault plan can break the WIRE instead of a process — ``conn_reset``
raises a connection reset into the caller's failover handling;
``net_delay`` stalls the transport by ``CSMOM_CHAOS_NET_DELAY_S`` (an
induced straggler: the hedging policy is what the scenario then
measures); ``partition`` cuts THIS process off from the peer address
for ``CSMOM_CHAOS_PARTITION_S`` seconds — and on persistent channels a
partition is a partition: every LIVE channel to that peer is severed
immediately, reason-closing every in-flight request on it (not just
refusing new dials), and every dial to the peer fails instantly until
the partition heals.  Probe/lifecycle ops do not visit the checkpoint,
so supervisor probes keep deterministic hit counts.

Request tracing rides the header, not the framing: a ``score`` frame may
carry a ``trace`` entry (trace id, endpoint, SLO class, panel version —
identity only, never timestamps, so each process keeps its own clock and
stitching works on durations), and the peer's reply then carries a
``trace_half`` entry with its server-side stage chain.  The channel
layer additionally reports when the channel was ACQUIRED and when the
request's frame finished sending (``marks``), so the trace can split
the old opaque ``transport`` stage into ``connect`` / ``send`` /
``recv_wait`` (see :mod:`csmom_tpu.obs.trace`).

Ops the worker answers (see :mod:`csmom_tpu.serve.worker`); the router
replica answers the same lifecycle set (see
:mod:`csmom_tpu.serve.router`):

===========  ==================================================
op           meaning
===========  ==================================================
ping         liveness: "the process responds" — no service state
ready        readiness report (warm + self-probe + cache version)
score        one scoring request (arrays: values, mask)
stats        accounting / batch stats / fresh-compile count
stats_stream one metrics snapshot delta, emitter -> fleet
             aggregator (``obs/fleet.py``): a lifecycle op on a
             PERSISTENT channel, never the request hot path, and
             chaos-free by construction (``serve.transport``
             faults fire only for ``score``)
drain        stop admitting, drain the queue, report accounting
stop         drain, then exit the process
===========  ==================================================
"""

from __future__ import annotations

import functools
import itertools
import json
import math
import os
import socket
import struct
import threading
import time

import numpy as np

from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["Channel", "ChannelPool", "HeaderTemplate", "MAX_FRAME_BYTES",
           "ProtocolError", "RECV_DEADLINE_S", "ReplyTimeout",
           "ScoreHeaderCache", "connect", "free_tcp_port", "listen",
           "parse_address", "recv_msg", "request", "request_once",
           "send_msg", "serve_connection", "tune_stream_socket",
           "unlink_address"]

# largest legal frame: the biggest production micro-panel is ~30 KB, so
# 32 MB is three orders of magnitude of headroom while still refusing a
# garbage length prefix before it can exhaust memory
MAX_FRAME_BYTES = 32 * 1024 * 1024

# total budget for receiving ONE frame (header + payload) once its
# first byte arrived.  Generous against any honest peer (a full frame
# is one sendmsg away), tight against a wedged one: a peer that stops
# mid-frame costs this much wall, never a thread forever.
RECV_DEADLINE_S = 30.0

# how long an accepted SERVER connection may sit idle between frames
# before the serve loop closes it (resource hygiene; the client pool
# transparently redials).  Client channels park no thread while idle —
# the pool's idle reaper owns their lifecycle.
SERVE_IDLE_S = 300.0

_LEN = struct.Struct("!I")

# chaos partition state (the `partition` action at serve.transport):
# peer address -> monotonic heal time.  Process-local on purpose — a
# partition separates THIS process from a peer host, not the world.
# Shared between the pooled and one-shot paths so a partition armed on
# either starves both.
_PARTITION_LOCK = threading.Lock()
_PARTITIONED: dict = {}

# fault-duration knobs (chaos actions are caller-interpreted and the
# checkpoint returns only the action name, so durations ride the same
# env channel the plans do)
PARTITION_ENV = "CSMOM_CHAOS_PARTITION_S"
NET_DELAY_ENV = "CSMOM_CHAOS_NET_DELAY_S"
_PARTITION_DEFAULT_S = 1.0
_NET_DELAY_DEFAULT_S = 0.25


class ProtocolError(RuntimeError):
    """A malformed frame (bad length, truncated payload, spec mismatch,
    or a receive deadline expiring on a stalled peer)."""


class FrameEncodeError(ProtocolError):
    """The caller's own frame could not be encoded (oversized arrays,
    malformed header core) — nothing touched the wire, so retrying on a
    fresh channel can only waste a dial and mask the diagnostic."""


class ReplyTimeout(ProtocolError):
    """A multiplexed request outwaited its reply budget.  The CHANNEL
    is still healthy (other requests may be in flight and the peer may
    still answer — a late reply is dropped by the demux) — only this
    request's attempt failed, so the pool must not redial over it."""


# ------------------------------------------------------------ addresses ---

def parse_address(address: str) -> tuple:
    """``("unix", path)`` or ``("tcp", (host, port))`` for an address
    string.  Bare paths are unix (the r11 spelling); ``tcp:`` needs
    ``host:port`` with an integer port."""
    if address.startswith("unix:"):
        path = address[len("unix:"):]
        if not path:
            raise ValueError(f"empty unix socket path in {address!r}")
        return "unix", path
    if address.startswith("tcp:"):
        rest = address[len("tcp:"):]
        host, sep, port_s = rest.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"bad tcp address {address!r}: use tcp:host:port")
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(
                f"bad tcp port in {address!r}: {port_s!r} is not an "
                "integer") from None
        if not 0 <= port <= 65535:
            raise ValueError(f"tcp port {port} outside [0, 65535]")
        return "tcp", (host, port)
    return "unix", address


def free_tcp_port(host: str = "127.0.0.1") -> int:
    """One currently-free TCP port (bind-to-0 then release).  Classic
    small race with other port grabbers; fine for the loopback fabrics
    the supervisor spawns, where it owns the port range in practice."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return int(s.getsockname()[1])
    finally:
        s.close()


def tune_stream_socket(sock: socket.socket) -> None:
    """Per-connection socket options, applied on BOTH the connect and
    the accept side of every stream: ``TCP_NODELAY`` because the framed
    replies are small and latency-critical — Nagle would sit on a
    sub-MSS reply frame waiting for an ACK that is itself delayed,
    which is precisely the 40 ms-quantum tail the r18 capture paid —
    and ``SO_KEEPALIVE`` so a silently vanished peer (host partition,
    container kill) eventually reads as a dead channel instead of a
    socket that stays "connected" forever.  Unix sockets have neither
    knob (no Nagle, no keepalive) and are left alone."""
    if sock.family != socket.AF_INET:
        return
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    except OSError:
        pass  # an already-reset socket: the first send will report it


def listen(address: str, backlog: int = 64) -> socket.socket:
    """A bound, listening server socket for ``address`` (unix or tcp).
    Unix paths are unlinked first (a crashed predecessor's stale socket
    file must not block the bind); tcp sets ``SO_REUSEADDR`` for the
    same reason."""
    scheme, target = parse_address(address)
    if scheme == "unix":
        try:
            os.unlink(target)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(target)
    else:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(target)
    srv.listen(backlog)
    return srv


def unlink_address(address: str) -> None:
    """Remove a unix socket path (no-op for tcp) — shutdown hygiene."""
    scheme, target = parse_address(address)
    if scheme == "unix":
        try:
            os.unlink(target)
        except OSError:
            pass


def _partition_reason(address: str) -> str:
    return (f"chaos partition: this process is partitioned from "
            f"{address} (heals in <= "
            f"{os.environ.get(PARTITION_ENV, _PARTITION_DEFAULT_S)}s)")


def _partitioned_until(address: str) -> float | None:
    with _PARTITION_LOCK:
        heal_at = _PARTITIONED.get(address)
        if heal_at is None:
            return None
        if mono_now_s() >= heal_at:
            del _PARTITIONED[address]
            return None
        return heal_at


def _chaos_env_s(env: str, default_s: float) -> float:
    """A chaos duration knob from the environment, defaulting on a
    malformed value — a typo'd \"250ms\" must degrade to the default
    fault, not raise an unhandled ValueError through the dispatch
    thread and strand its request non-terminal."""
    raw = os.environ.get(env)
    if not raw:
        return default_s
    try:
        return float(raw)
    except ValueError:
        return default_s


def _chaos_transport(address: str, op: str, on_partition=None) -> None:
    """The ``serve.transport`` checkpoint, fired per score dispatch.

    Caller-interpreted actions: ``conn_reset`` raises into the caller's
    existing connection-failure handling; ``net_delay`` sleeps the
    configured straggler delay; ``partition`` cuts this process off from
    ``address`` for the configured window.  ``on_partition(address,
    reason)`` is the persistent-channel hook: the pool severs every
    LIVE channel to the peer so in-flight requests reason-close — a
    partition breaks streams mid-flight, not just future dials.  An
    already-armed partition fails the dispatch whether or not a fault
    fires on this visit.
    """
    from csmom_tpu.chaos.inject import checkpoint

    fired = checkpoint("serve.transport", addr=address, op=op)
    if fired == "partition":
        heal_s = _chaos_env_s(PARTITION_ENV, _PARTITION_DEFAULT_S)
        with _PARTITION_LOCK:
            _PARTITIONED[address] = mono_now_s() + heal_s
    elif fired == "net_delay":
        time.sleep(_chaos_env_s(NET_DELAY_ENV, _NET_DELAY_DEFAULT_S))
    elif fired == "conn_reset":
        raise ConnectionResetError(
            f"chaos conn_reset injected at serve.transport (peer "
            f"{address})")
    if _partitioned_until(address) is not None:
        reason = _partition_reason(address)
        if on_partition is not None:
            on_partition(address, reason)
        raise ConnectionRefusedError(reason)


def connect(address: str, timeout_s: float) -> socket.socket:
    """One connected, timeout-armed, tuned client socket to a peer."""
    scheme, target = parse_address(address)
    family = socket.AF_UNIX if scheme == "unix" else socket.AF_INET
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.settimeout(timeout_s)
    try:
        sock.connect(target)
    except OSError:
        sock.close()
        raise
    tune_stream_socket(sock)
    return sock


# ------------------------------------------------------------- encoding ---

@functools.lru_cache(maxsize=1024)
def _spec_fragment(name: str, dtype: str, shape: tuple,
                   nbytes: int) -> bytes:
    """One array's header spec as pre-encoded JSON.  The serve tiers
    dispatch the SAME few (name, dtype, bucket-shape) combinations for
    an entire run, so the per-request spec encode collapses to a dict
    probe instead of a ``json.dumps`` of invariant fields."""
    return json.dumps({"name": name, "dtype": dtype,
                       "shape": list(shape), "nbytes": nbytes}).encode()


def _encode_frame(header_core: bytes, arrays: dict | None,
                  mux_id: int | None) -> tuple:
    """``(buffers, total_len)`` for one frame: the length-prefixed
    header (with ``_mux`` and ``_arrays`` spliced into the core object
    bytes) followed by each array's OWN buffer — no payload
    concatenation; the socket layer gathers them."""
    blobs = []
    specs = []
    nbytes_total = 0
    for name, arr in (arrays or {}).items():
        a = np.ascontiguousarray(arr)
        specs.append(_spec_fragment(name, str(a.dtype), a.shape,
                                    int(a.nbytes)))
        blobs.append(a)
        nbytes_total += int(a.nbytes)
    if header_core[:1] != b"{" or header_core[-1:] != b"}":
        raise ProtocolError(
            "header core must be an encoded JSON object (a malformed "
            "template would splice into an unparseable frame and kill "
            "the whole channel at the peer)")
    parts = [header_core[:-1]]
    sep = b"" if header_core == b"{}" else b","
    if mux_id is not None:
        parts.append(sep + b'"_mux":%d' % mux_id)
        sep = b","
    parts.append(sep + b'"_arrays":[' + b",".join(specs) + b"]}")
    hb = b"".join(parts)
    total = _LEN.size + len(hb) + nbytes_total
    if 2 * _LEN.size + len(hb) + nbytes_total > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"frame of {2 * _LEN.size + len(hb) + nbytes_total} bytes "
            f"exceeds MAX_FRAME_BYTES ({MAX_FRAME_BYTES}); split the "
            "request")
    head = _LEN.pack(total) + _LEN.pack(len(hb)) + hb
    buffers = [head]
    for a in blobs:
        buffers.append(memoryview(a).cast("B"))
    return buffers, total


def _send_buffers(sock: socket.socket, buffers: list) -> None:
    """Scatter-gather send: the kernel walks the iovec instead of this
    process concatenating header + payload into one throwaway bytes
    object per frame.  Handles partial sends (sendmsg is not sendall)."""
    views = [memoryview(b) for b in buffers]
    if not hasattr(sock, "sendmsg"):  # pragma: no cover - posix has it
        sock.sendall(b"".join(views))
        return
    while views:
        sent = sock.sendmsg(views)
        while sent > 0 and views:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


class HeaderTemplate:
    """Pre-encoded invariant header fields for the request hot path.

    A dispatch tier's score headers repeat the same op / kind /
    priority / panel-version fields thousands of times per run;
    ``render`` splices only the per-request variable fields (req id,
    deadline, trace identity) onto the cached prefix instead of
    re-``json.dumps``-ing the whole header every dispatch.  ``render``
    returns header-core BYTES accepted by :meth:`Channel.request` and
    :meth:`ChannelPool.request` wherever a header dict is."""

    __slots__ = ("_prefix", "_empty")

    def __init__(self, **invariant):
        core = json.dumps(invariant, separators=(",", ":"))
        self._prefix = core[:-1].encode()
        self._empty = core == "{}"

    def render(self, **variable) -> bytes:
        if not variable:
            return self._prefix + b"}"
        frag = json.dumps(variable, separators=(",", ":")).encode()
        sep = b"" if self._empty else b","
        return self._prefix + sep + frag[1:]


class ScoreHeaderCache:
    """Per-``(kind, class, panel_version)`` pre-encoded score headers —
    the ONE implementation both dispatch tiers (router → workers,
    fabric client → replicas) render their hot-path frames through, so
    a header-field or cache-policy change cannot silently diverge the
    two wire formats.  Bounded: the key space is tiny in production
    (endpoints × classes × one live panel version); a runaway key space
    clears and starts over."""

    __slots__ = ("_templates", "_bound")

    def __init__(self, bound: int = 256):
        self._templates: dict = {}
        self._bound = bound

    def render(self, kind: str, priority: str, panel_version,
               req_id: int, deadline_rel_s, trace_ctx=None) -> bytes:
        key = (kind, priority, panel_version)
        tmpl = self._templates.get(key)
        if tmpl is None:
            if len(self._templates) > self._bound:
                self._templates.clear()
            tmpl = self._templates[key] = HeaderTemplate(
                op="score", kind=kind, priority=priority,
                panel_version=panel_version)
        variable = {"req_id": req_id, "deadline_rel_s": deadline_rel_s}
        if trace_ctx is not None:
            wire = trace_ctx.to_wire()
            if wire is not None:
                # the trace context crosses the process boundary in the
                # frame header (identity only, never timestamps): the
                # peer answers with its half, and the two stitch at the
                # dispatcher
                variable["trace"] = wire
        return tmpl.render(**variable)


def _header_core(obj) -> bytes:
    """Header-core bytes from a dict or pre-rendered template bytes."""
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj)
    return json.dumps(obj).encode("utf-8")


def send_msg(sock: socket.socket, obj, arrays: dict | None = None) -> None:
    """Send one frame: ``obj`` (a dict, or header-core bytes from
    :meth:`HeaderTemplate.render`) as the JSON header plus raw array
    bytes, scatter-gathered onto the socket."""
    buffers, _ = _encode_frame(_header_core(obj), arrays, None)
    _send_buffers(sock, buffers)


# ------------------------------------------------------------- receiving ---

def _recv_into_exact(sock: socket.socket, mv: memoryview,
                     give_up_s: float) -> None:
    """Fill ``mv`` from ``sock`` before the ``give_up_s`` monotonic
    deadline, reading INTO the caller's buffer (no per-chunk bytes
    objects, no final join copy).  The socket timeout is re-armed per
    read from the REMAINING budget — a peer trickling one byte per
    timeout window used to reset the clock forever; now the total wall
    is bounded."""
    n = len(mv)
    got = 0
    while got < n:
        remaining = give_up_s - mono_now_s()
        if remaining <= 0:
            raise ProtocolError(
                f"receive deadline expired mid-frame ({got}/{n} "
                "bytes read) — the peer stalled; closing rather than "
                "wedging this thread")
        sock.settimeout(remaining)
        try:
            k = sock.recv_into(mv[got:])
        except socket.timeout:
            raise ProtocolError(
                f"receive deadline expired mid-frame ({got}/{n} "
                "bytes read) — the peer stalled; closing rather than "
                "wedging this thread") from None
        if not k:
            raise ProtocolError(
                f"connection closed mid-frame ({got}/{n} bytes read) "
                "— the peer died or reset")
        got += k


def _recv_first_byte(sock: socket.socket, idle_timeout_s: float):
    """The idle wait for a frame's FIRST byte: ``None`` on clean EOF
    (the peer closed between frames — a legal channel end), the byte
    on arrival, ``ProtocolError`` when the idle budget expires.

    Waits in bounded windows and NEVER arms blocking mode
    (``settimeout(None)``): a channel socket is shared with a writer
    thread via a ``dup()``'d object, and flipping the underlying fd to
    blocking would change the writer's send semantics mid-frame."""
    deadline = (None if math.isinf(idle_timeout_s)
                else mono_now_s() + idle_timeout_s)
    while True:
        if deadline is None:
            window = 60.0
        else:
            window = deadline - mono_now_s()
            if window <= 0:
                raise _IdleWindow(
                    f"connection idle for {idle_timeout_s:.0f}s — "
                    "closing (the peer pool redials on demand)")
        sock.settimeout(min(60.0, max(0.001, window)))
        try:
            b = sock.recv(1)
        except socket.timeout:
            continue
        return b if b else None


def recv_msg(sock: socket.socket, deadline_s: float = RECV_DEADLINE_S,
             *, idle_timeout_s: float | None = None,
             scratch: bytearray | None = None):
    """Receive one frame; returns ``(obj, arrays)``.

    Strict mode (``idle_timeout_s=None``, the one-shot contract): the
    whole frame — length prefix included — must arrive within
    ``deadline_s``.  Channel mode (``idle_timeout_s`` set): the FIRST
    byte may take up to ``idle_timeout_s`` (``inf`` = wait forever,
    the client reader's mode — the pool owns its lifecycle) and a
    clean EOF at a frame boundary returns ``None``; once the first
    byte lands, the REST of the frame must arrive within
    ``deadline_s`` — idle is healthy, trickling is not.

    ``scratch`` is an optional reusable receive buffer (grown in
    place, never shrunk): a channel reader passes its own so a steady
    request stream allocates no per-frame payload buffers.

    Every declared array is rebuilt from the binary tail; a spec whose
    byte counts do not reconcile with the frame is a protocol error,
    not a best-effort parse — half a panel must never score.  The
    length prefix is judged against ``MAX_FRAME_BYTES`` BEFORE any
    payload allocation: a corrupt or hostile prefix costs a pointed
    refusal, never the allocation it names.
    """
    # _recv_into_exact re-arms the socket timeout downward per read;
    # restore the caller's timeout afterwards so a later send/receive
    # on the same connection doesn't inherit a near-zero residual
    caller_timeout = sock.gettimeout()
    prefix = bytearray(_LEN.size)
    try:
        if idle_timeout_s is None:
            give_up = mono_now_s() + deadline_s
            _recv_into_exact(sock, memoryview(prefix), give_up)
        else:
            first = _recv_first_byte(sock, idle_timeout_s)
            if first is None:
                return None
            give_up = mono_now_s() + deadline_s
            prefix[0] = first[0]
            _recv_into_exact(sock, memoryview(prefix)[1:], give_up)
        (total,) = _LEN.unpack(prefix)
        if total > MAX_FRAME_BYTES:
            raise ProtocolError(
                f"declared frame length {total} exceeds MAX_FRAME_BYTES "
                f"({MAX_FRAME_BYTES}) — corrupt length prefix?  Refusing "
                "before allocating it")
        if scratch is None:
            scratch = bytearray(total)
        elif len(scratch) < total:
            scratch.extend(bytes(total - len(scratch)))
        payload = memoryview(scratch)[:total]
        _recv_into_exact(sock, payload, give_up)
    finally:
        try:
            sock.settimeout(caller_timeout)
        except OSError:
            pass  # the socket may already be closed/reset
    if total < _LEN.size:
        raise ProtocolError("frame shorter than its header length prefix")
    (hlen,) = _LEN.unpack(payload[:_LEN.size])
    if _LEN.size + hlen > total:
        raise ProtocolError(
            f"header length {hlen} overruns the {total}-byte frame")
    try:
        obj = json.loads(
            bytes(payload[_LEN.size:_LEN.size + hlen]).decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"unparseable frame header: {e}") from None
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"frame header must be a JSON object, got {type(obj).__name__}")
    specs = obj.pop("_arrays", [])
    arrays: dict = {}
    off = _LEN.size + hlen
    for spec in specs:
        try:
            name = spec["name"]
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(s) for s in spec["shape"])
            nbytes = int(spec["nbytes"])
        except (KeyError, TypeError, ValueError) as e:
            raise ProtocolError(f"bad array spec {spec!r}: {e}") from None
        want = dtype.itemsize * int(np.prod(shape)) if shape else dtype.itemsize
        if nbytes != want or off + nbytes > total:
            raise ProtocolError(
                f"array {name!r} spec inconsistent with frame "
                f"(declared {nbytes} bytes, shape wants {want}, "
                f"{total - off} remain)")
        # .copy() because the scratch buffer is reused for the next
        # frame — the array must own its bytes past this call
        arrays[name] = np.frombuffer(
            payload[off:off + nbytes], dtype=dtype).reshape(shape).copy()
        off += nbytes
    if off != total:
        raise ProtocolError(
            f"{total - off} trailing bytes after the declared arrays")
    return obj, arrays


# --------------------------------------------------------------- one-shot ---

def request_once(address: str, obj: dict, arrays: dict | None = None,
                 timeout_s: float = 5.0) -> tuple:
    """One-shot round trip: connect, send, receive one reply, close.

    The r11–r18 transport, kept for PROBES and one-shot admin/lifecycle
    ops (ping / ready / stats / drain / stop), where a fresh connection
    per call is the point — a probe must measure the peer's ability to
    accept, and an admin op must not ride a channel the request path
    might sever.  Request hot paths use :class:`ChannelPool`; the
    ``dial-discipline`` lint rule enforces the split.

    ``timeout_s`` bounds the connect AND the whole reply receive (the
    receive-deadline contract), so one call can never outwait its
    budget no matter how the peer misbehaves.  ``score`` ops visit the
    ``serve.transport`` chaos checkpoint before dialing.
    """
    if obj.get("op") == "score":
        _chaos_transport(address, "score")
    sock = connect(address, timeout_s)
    try:
        send_msg(sock, obj, arrays)
        return recv_msg(sock, deadline_s=timeout_s)
    finally:
        try:
            sock.close()
        except OSError:
            pass


# the pre-r19 name, kept so operator scripts and older tests keep
# working; new non-hot-path call sites should spell request_once
request = request_once


# ----------------------------------------------------------- the channel ---

class _IdleWindow(ProtocolError):
    """An idle window elapsed with no frame started (leader's read
    slice) — not an error, re-check budgets and wait again.  Subclasses
    ProtocolError so the SERVER loop's existing catch treats an idle
    expiry there as the connection close it already was."""


class _Waiter:
    """One in-flight request's parking spot on a channel.

    ``obj``/``error`` are the truth; ``event`` is only a wakeup hint
    (a leader exiting pokes one waiter's event WITHOUT a reply so it
    takes over reading) — every consumer re-checks obj/error after any
    wake, so hint races are benign by construction."""

    __slots__ = ("event", "obj", "arrays", "error")

    def __init__(self):
        self.event = threading.Event()
        self.obj = None
        self.arrays = None
        self.error: BaseException | None = None

    @property
    def done(self) -> bool:
        return self.obj is not None or self.error is not None


class Channel:
    """One persistent, multiplexed connection to a peer.

    Many requests interleave: each send is tagged with a ``_mux`` id
    under the writer lock, and replies route to the waiter registered
    under the echoed id.  A reply with no tag settles the oldest
    pending request (a legacy in-order peer).  Any transport error —
    reset, EOF, a mid-frame receive deadline — kills the channel and
    fails EVERY in-flight request with the reason, so a partition
    mid-stream reason-closes the stream, never wedges it.

    **Leader/follower demux — no reader thread.**  The first dispatcher
    to grab the read baton (``_rlock``) reads frames, delivering each
    reply to its waiter, until its OWN reply lands; then it returns and
    pokes a pending follower to take over.  A solo request's reply
    therefore wakes the requesting thread STRAIGHT from the kernel —
    one scheduler hop, exactly like the old socket-per-request design —
    and a dedicated reader thread's extra wake-parse-wake hop (which
    under a CPU-saturated burst quantized every reply to scheduler
    latency) never exists.  An idle channel parks no thread at all.
    """

    __slots__ = ("address", "alive", "close_reason", "last_used_s",
                 "created_s", "frame_deadline_s", "_sock", "_wsock",
                 "_wlock", "_plock", "_rlock", "_scratch", "_pending",
                 "_mux_ids", "orphan_replies", "_timeout_orphaned")

    # how long one frame WRITE may take before the channel is judged
    # wedged (a full kernel buffer against a stalled peer)
    SEND_TIMEOUT_S = RECV_DEADLINE_S

    # a leader's read slice: long enough to stay parked in the kernel
    # for the common case, short enough to re-check its own deadline
    LEAD_IDLE_SLICE_S = 0.25
    # a follower's safety-net poll (pokes normally wake it sooner)
    FOLLOW_WAIT_S = 0.25

    def __init__(self, address: str, sock: socket.socket,
                 frame_deadline_s: float = RECV_DEADLINE_S):
        self.address = address
        self.frame_deadline_s = frame_deadline_s
        self._sock = sock
        # the writer gets its OWN socket object over a dup'd fd with a
        # FIXED timeout: the read side re-arms the original's timeout
        # per read (idle windows, frame deadlines), and Python socket
        # timeouts are per-object — sharing one object between threads
        # would race the writer's send budget.  Neither object ever
        # arms blocking mode, so the shared fd's mode never flips
        # under a concurrent operation.
        self._wsock = sock.dup()
        self._wsock.settimeout(self.SEND_TIMEOUT_S)
        self.alive = True
        self.close_reason: str | None = None
        self.last_used_s = mono_now_s()
        self.created_s = self.last_used_s
        self._wlock = threading.Lock()     # serializes frames OUT
        self._plock = threading.Lock()     # guards the pending registry
        self._rlock = threading.Lock()     # the read baton (the leader)
        self._scratch = bytearray()        # leader-only receive buffer
        # mux id -> _Waiter; dict insertion order doubles as the
        # oldest-pending order for legacy untagged replies (entries are
        # popped on completion, so nothing accumulates per request)
        self._pending: dict = {}
        self._mux_ids = itertools.count(1)
        self.orphan_replies = 0            # replies whose waiter gave up
        self._timeout_orphaned = False     # a waiter once gave up: an
        #                                    untagged reply could be its

    @property
    def in_flight(self) -> int:
        # lock-free read on purpose: a load-balancing/reap HEURISTIC,
        # not an invariant — taking _plock here would hand the pool's
        # registry lock a global ordering constraint for a count that
        # may be stale by the time the caller acts on it anyway
        return len(self._pending)

    def request(self, obj, arrays: dict | None, timeout_s: float,
                marks: dict | None = None) -> tuple:
        """One multiplexed round trip on this channel.  ``obj`` is a
        header dict or :meth:`HeaderTemplate.render` bytes.  ``marks``
        (optional dict) receives ``t_sent_s`` — the monotonic instant
        the frame finished sending — for the trace's transport split."""
        mux = next(self._mux_ids)
        w = _Waiter()
        with self._plock:
            if not self.alive:
                raise ConnectionResetError(
                    f"channel to {self.address} is closed "
                    f"({self.close_reason})")
            self._pending[mux] = w
        try:
            try:
                buffers, _ = _encode_frame(_header_core(obj), arrays,
                                           mux)
            except ProtocolError as e:
                # the REQUEST is malformed, not the channel: surface
                # the pointed diagnostic, never the redial path
                raise FrameEncodeError(str(e)) from None
            try:
                # the writer lock EXISTS to serialize frame writes on
                # one socket; it guards nothing else, is a leaf, and
                # the send is bounded by wsock's fixed SEND_TIMEOUT_S
                with self._wlock:
                    # lint: allow[lock-order] serializing the send IS this leaf lock's purpose
                    _send_buffers(self._wsock, buffers)
            except OSError as e:
                self._die(f"send failed: {type(e).__name__}: {e}")
                raise
            self.last_used_s = mono_now_s()
            if marks is not None:
                marks["t_sent_s"] = self.last_used_s
            out = self._await_reply(w, mono_now_s() + timeout_s,
                                    timeout_s)
            self.last_used_s = mono_now_s()
            return out
        finally:
            with self._plock:
                self._pending.pop(mux, None)

    # ---------------------------------------------------- leader/follower --

    def _await_reply(self, w: _Waiter, give_up_s: float,
                     timeout_s: float) -> tuple:
        """Wait for ``w``'s reply, leading the channel's reads whenever
        no one else is: the leader parses every arriving frame and
        delivers it to its waiter (possibly itself); followers sleep on
        their own events and inherit the baton by poke when the leader
        returns."""
        while True:
            if w.error is not None:
                raise w.error
            if w.obj is not None:
                return w.obj, w.arrays
            remaining = give_up_s - mono_now_s()
            if remaining <= 0:
                self._timeout_orphaned = True
                raise ReplyTimeout(
                    f"no reply from {self.address} within "
                    f"{timeout_s:.1f}s (channel healthy; the late reply "
                    "will be dropped by the demux)")
            if self._rlock.acquire(blocking=False):
                try:
                    self._lead(w, give_up_s)
                finally:
                    self._rlock.release()
                    self._poke_follower()
            else:
                # follower: the leader delivers our reply (event set
                # with obj) or pokes us to take over (event set, no
                # obj) — the loop top re-checks truth either way
                w.event.wait(min(remaining, self.FOLLOW_WAIT_S))
                w.event.clear()

    def _lead(self, w: _Waiter, give_up_s: float) -> None:
        """Read frames until OUR reply lands, our budget runs out, or
        the channel dies (death reason-closes every waiter)."""
        while not w.done:
            remaining = give_up_s - mono_now_s()
            if remaining <= 0:
                return
            try:
                msg = recv_msg(
                    self._sock, self.frame_deadline_s,
                    idle_timeout_s=min(remaining,
                                       self.LEAD_IDLE_SLICE_S),
                    scratch=self._scratch)
            except _IdleWindow:
                continue  # no frame started; re-check our budget
            except (OSError, ProtocolError, ValueError) as e:
                self._die(f"{type(e).__name__}: {e}")
                return
            if msg is None:
                self._die("peer closed the channel")
                return
            self._deliver(*msg)

    def _deliver(self, obj: dict, arrays: dict) -> None:
        mux = obj.pop("_mux", None)
        with self._plock:
            if mux is None:
                if len(self._pending) > 1 or self._timeout_orphaned:
                    # an untagged reply can only be attributed when ONE
                    # request is in flight: registration order is not
                    # send order (the writer lock decides that), so
                    # guessing could hand thread A thread B's scores.
                    # A legacy peer must not be multiplexed — and
                    # after ANY timeout the lone pending waiter may not
                    # be this reply's requester either.  Kill the
                    # channel; the reason-closed requests fail over.
                    die = True
                else:
                    mux = next(iter(self._pending), None)
                    die = False
            else:
                die = False
            wt = self._pending.get(mux)
        if die:
            self._die("untagged reply that cannot be attributed (multiple "
                      "requests in flight, or a prior timeout orphaned "
                      "one) — a legacy in-order peer cannot be "
                      "multiplexed")
            return
        if wt is None:
            # the waiter timed out and moved on: drop the late reply
            # (counted — a rising number means the reply budget is
            # tighter than the peer's service time)
            self.orphan_replies += 1
            return
        wt.obj, wt.arrays = obj, arrays
        wt.event.set()

    def _poke_follower(self) -> None:
        """Wake one undelivered waiter so leadership never strands: the
        poked waiter re-checks its truth, finds no reply, and takes the
        baton (its FOLLOW_WAIT_S poll is only the safety net)."""
        with self._plock:
            for wt in self._pending.values():
                if not wt.done:
                    wt.event.set()
                    return

    def _die(self, reason: str) -> None:
        """Mark dead and reason-close every in-flight request (the
        exactly-once guard: only the first reason sticks)."""
        with self._plock:
            if not self.alive:
                return
            self.alive = False
            self.close_reason = str(reason)[:200]
            waiters = list(self._pending.values())
            self._pending.clear()
        for s in (self._sock, self._wsock):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        for w in waiters:
            w.error = ConnectionResetError(
                f"channel to {self.address} died mid-request: "
                f"{self.close_reason}")
            w.event.set()

    def close(self, reason: str = "closed by pool") -> None:
        self._die(reason)


class ChannelPool:
    """Per-peer bounded channel registry: dial on demand, reuse across
    requests, reap idle, back off on a refusing peer.

    The hot-path transport (ISSUE 15).  One pool per dispatch tier
    (router → workers; fabric client → router replicas); probes and
    admin ops stay on :func:`request_once`.
    """

    def __init__(self, max_per_peer: int = 8, idle_reap_s: float = 60.0,
                 connect_timeout_s: float = 2.0,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 1.0,
                 frame_deadline_s: float = RECV_DEADLINE_S,
                 pipeline_depth: int = 8):
        self.max_per_peer = max(1, int(max_per_peer))
        self.idle_reap_s = idle_reap_s
        self.connect_timeout_s = connect_timeout_s
        self.frame_deadline_s = frame_deadline_s
        # how many in-flight requests one channel carries before the
        # pool prefers dialing another (up to max_per_peer).  One
        # channel is one read baton here and one serve-loop thread at
        # the peer — under a burst, spreading frames across a few
        # parallel loops is what keeps a GIL-bound tier's frame
        # parsing off the critical path; past the bound, requests
        # share the least-loaded channel anyway (mux absorbs it).
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._mu = threading.Lock()        # registry only; never held
        #                                    across a dial or a request
        self._channels: dict = {}          # address -> [Channel, ...]
        self._dialing: dict = {}           # address -> in-flight dial count
        self._backoff: dict = {}           # address -> (fails, retry_at_s)
        self._rr = itertools.count()
        # counters (exposed via stats(); the fabric artifact's evidence
        # that the transport actually reused connections)
        self.dials = 0
        self.dial_failures = 0
        self.reuses = 0
        self.stale_retries = 0
        self.severed = 0
        self.reaped_idle = 0

    # ------------------------------------------------------------ acquire --

    def _acquire(self, address: str,
                 newer_than_s: float | None = None) -> tuple:
        """``(channel, fresh)`` — a healthy channel to ``address``,
        dialing one when the peer has capacity.  Raises the dial error
        (or a fast-fail during reconnect backoff).

        ``newer_than_s`` is the stale-retry floor: only channels
        CREATED after that instant count as reusable (the caller just
        watched an older one die), so concurrent retries against a
        restarted peer share one sibling dial under the per-peer bound
        instead of each bursting its own connect."""
        dial_give_up = mono_now_s() + self.connect_timeout_s
        while True:
            now = mono_now_s()
            to_reap: list = []
            reuse = None
            backoff_err = None
            dial = False
            with self._mu:
                chans = self._channels.setdefault(address, [])
                # lazy idle reap + dead-channel pruning (no reaper
                # thread: the next acquire is the natural maintenance
                # point).  The closes themselves run AFTER the registry
                # lock releases — the pool lock must not order against
                # channel internals.
                kept = []
                for ch in chans:
                    if not ch.alive:
                        continue
                    if (ch.in_flight == 0
                            and now - ch.last_used_s > self.idle_reap_s):
                        to_reap.append(ch)
                        self.reaped_idle += 1
                        continue
                    kept.append(ch)
                chans[:] = kept
                usable = (kept if newer_than_s is None
                          else [c for c in kept
                                if c.created_s > newer_than_s])
                best = (min(usable, key=lambda c: c.in_flight)
                        if usable else None)
                capacity = (len(kept) + self._dialing.get(address, 0)
                            < self.max_per_peer)
                if (best is not None
                        and (best.in_flight < self.pipeline_depth
                             or not capacity)):
                    # a channel with pipeline headroom — or the peer
                    # is at its channel bound: mux onto the least
                    # loaded.  Saturated channels with capacity left
                    # fall through to dial: one channel is one serve
                    # loop at the peer, and a burst needs a few of
                    # them in parallel.
                    self.reuses += 1
                    reuse = best
                    reuse.last_used_s = now
                else:
                    fails, retry_at = self._backoff.get(address, (0, 0.0))
                    if fails and now < retry_at:
                        if best is not None:
                            # a refusing peer with live channels: keep
                            # using them, just don't dial into backoff
                            self.reuses += 1
                            reuse = best
                            reuse.last_used_s = now
                        else:
                            backoff_err = ConnectionRefusedError(
                                f"peer {address} in reconnect backoff "
                                f"after {fails} dial failure(s) "
                                f"(retries in {retry_at - now:.2f}s)")
                    elif capacity:
                        # reserve a dial slot under the lock; the
                        # connect itself runs OUTSIDE it (a slow dial
                        # must not serialize other peers' acquires)
                        self._dialing[address] = \
                            self._dialing.get(address, 0) + 1
                        dial = True
                    elif best is not None:
                        # at capacity with dials in flight: share the
                        # least loaded live channel, don't overshoot
                        self.reuses += 1
                        reuse = best
                        reuse.last_used_s = now
                    # else: no usable channel and the dial budget is
                    # all in flight — wait for a sibling's dial below
            for r in to_reap:
                r.close("idle-reaped")
            if backoff_err is not None:
                raise backoff_err
            if reuse is not None:
                return reuse, False
            if dial:
                break
            if mono_now_s() >= dial_give_up:
                raise ConnectionRefusedError(
                    f"timed out waiting for an in-flight dial to "
                    f"{address} ({self.connect_timeout_s:.1f}s)")
            time.sleep(0.005)
        try:
            sock = connect(address, self.connect_timeout_s)
        except OSError:
            with self._mu:
                self._dialing[address] -= 1
                fails = self._backoff.get(address, (0, 0.0))[0] + 1
                delay = min(self.backoff_cap_s,
                            self.backoff_base_s * (2 ** (fails - 1)))
                self._backoff[address] = (fails, mono_now_s() + delay)
                self.dial_failures += 1
            raise
        ch = Channel(address, sock,
                     frame_deadline_s=self.frame_deadline_s)
        with self._mu:
            self._dialing[address] -= 1
            self._backoff.pop(address, None)
            self._channels.setdefault(address, []).append(ch)
            self.dials += 1
        return ch, True

    # ------------------------------------------------------------ request --

    def request(self, address: str, obj, arrays: dict | None = None,
                timeout_s: float = 5.0, marks: dict | None = None,
                fire_chaos: bool = True) -> tuple:
        """One request over a pooled channel; the hot-path replacement
        for :func:`request_once`.

        ``marks`` (optional dict) receives ``t_acquired_s`` (channel in
        hand — a dial or a pool hit) and ``t_sent_s`` (frame fully
        written) so the caller's trace can split ``transport`` into
        connect / send / recv_wait.  A failure on a REUSED channel
        before any reply is retried once on a channel dialed AFTER the
        failure (a pooled channel whose peer restarted between requests
        is a redial, not a failover) — within the SAME ``timeout_s``
        budget, so one call never outwaits the attempt bound its caller
        derived deadlines from.  ``fire_chaos`` visits the
        ``serve.transport`` checkpoint (the score-dispatch contract);
        a ``partition`` fault severs every live channel to the peer —
        in-flight requests included — until it heals.
        """
        if fire_chaos:
            _chaos_transport(address, "score", on_partition=self._sever)
        give_up = mono_now_s() + timeout_s
        ch, fresh = self._acquire(address)
        if marks is not None:
            marks["t_acquired_s"] = mono_now_s()
        try:
            return ch.request(obj, arrays, timeout_s, marks=marks)
        except (ReplyTimeout, FrameEncodeError):
            # the channel is healthy: the attempt expired, or the
            # request itself could not be framed — neither is a
            # transport failure a redial could fix
            raise
        except (OSError, ProtocolError):
            if fresh:
                raise
            if fire_chaos and _partitioned_until(address) is not None:
                # the channel died because a partition severed it: a
                # transparent redial would reconnect straight across
                # the armed partition — the contract says every dial
                # fails until it heals
                raise ConnectionRefusedError(_partition_reason(address))
            # the reuse gamble lost (peer restarted / idle-closed the
            # far end): one transparent retry on a channel newer than
            # the failure — concurrent retries share ONE sibling dial
            # under the per-peer bound instead of bursting N connects
            # at a peer that just restarted.  Scoring is pure, so
            # re-sending after a torn send is safe.
            t_fail = mono_now_s()
            with self._mu:
                self.stale_retries += 1
            ch2, _ = self._acquire(address, newer_than_s=t_fail)
            if marks is not None:
                marks["t_acquired_s"] = mono_now_s()
            return ch2.request(obj, arrays,
                               max(0.05, give_up - mono_now_s()),
                               marks=marks)

    # ----------------------------------------------------------- severing --

    def _sever(self, address: str, reason: str) -> None:
        """Close every live channel to ``address`` (reason-closing all
        in-flight requests on them) — the partition-mid-stream hook."""
        with self._mu:
            chans = self._channels.pop(address, [])
        for ch in chans:
            if ch.alive:
                with self._mu:
                    self.severed += 1
            ch.close(reason)

    def close(self) -> None:
        """Close every channel (teardown hygiene)."""
        with self._mu:
            all_chans = [ch for chans in self._channels.values()
                         for ch in chans]
            self._channels.clear()
        for ch in all_chans:
            ch.close("pool closed")

    def stats(self) -> dict:
        with self._mu:
            live = sum(1 for chans in self._channels.values()
                       for ch in chans if ch.alive)
            orphans = sum(ch.orphan_replies
                          for chans in self._channels.values()
                          for ch in chans)
            return {
                "live_channels": live,
                "dials": self.dials,
                "dial_failures": self.dial_failures,
                "reuses": self.reuses,
                "stale_retries": self.stale_retries,
                "severed": self.severed,
                "reaped_idle": self.reaped_idle,
                "orphan_replies": orphans,
            }


# ------------------------------------------------------------ server loop ---

def serve_connection(conn: socket.socket, handler, on_stop=None,
                     idle_timeout_s: float = SERVE_IDLE_S) -> None:
    """Serve one ACCEPTED connection until EOF / idle expiry / error:
    framed requests in, framed replies out, many in flight.

    ``handler(obj, arrays) -> (reply_obj, reply_arrays | None)`` runs
    per frame — ``score`` work on its own thread so a slow dispatch
    never head-of-line-blocks the channel's other requests (the
    worker-side half of the multiplexing contract); lifecycle ops
    inline (they are cheap and their ordering vs the frames around
    them is part of the drain semantics).  Replies echo the request's
    ``_mux`` id under one writer lock.  ``on_stop()`` fires after a
    ``stop`` op's reply is written.  A one-shot peer (no ``_mux``,
    closes after its reply) exits the loop via clean EOF.
    """
    tune_stream_socket(conn)
    # a finite timeout BEFORE anything else: recv_msg restores the
    # socket's prior timeout after every frame, and restoring None
    # would flip the open file description (shared with the dup'd
    # write socket below) into blocking mode — a reply to a stalled
    # peer could then block past SEND_TIMEOUT_S while holding the
    # writer lock
    conn.settimeout(RECV_DEADLINE_S)
    wlock = threading.Lock()
    # same split as Channel: reply threads write through their own
    # dup'd socket object with a fixed timeout while the serve loop
    # re-arms the original's timeout per read — per-object timeouts
    # must not race across threads
    wconn = conn.dup()
    wconn.settimeout(Channel.SEND_TIMEOUT_S)

    def _reply(mux, reply, reply_arrays):
        core = _header_core(reply)
        buffers, _ = _encode_frame(core, reply_arrays, mux)
        # the reply lock EXISTS to serialize frame writes on this one
        # socket; a leaf guarding nothing else, send bounded by wconn's
        # fixed timeout
        with wlock:
            # lint: allow[lock-order] serializing the send IS this leaf lock's purpose
            _send_buffers(wconn, buffers)

    def _run_one(obj, arrays, mux):
        op = obj.get("op")
        try:
            reply, reply_arrays = handler(obj, arrays)
        except Exception as e:  # a handler bug must not kill the channel
            reply, reply_arrays = {
                "state": "rejected",
                "error": f"handler error: {type(e).__name__}: {e}"[:200],
            }, None
        try:
            _reply(mux, reply, reply_arrays)
        except OSError:
            return  # peer gone; nothing to tell it
        if op == "stop" and on_stop is not None:
            on_stop()

    scratch = bytearray()
    try:
        while True:
            msg = recv_msg(conn, idle_timeout_s=idle_timeout_s,
                           scratch=scratch)
            if msg is None:
                return  # clean EOF between frames
            obj, arrays = msg
            mux = obj.pop("_mux", None)
            if obj.get("op") == "score":
                threading.Thread(target=_run_one, args=(obj, arrays, mux),
                                 daemon=True).start()
            else:
                _run_one(obj, arrays, mux)
    except (OSError, ProtocolError):
        pass  # the peer vanished, stalled, or spoke garbage: drop it
    finally:
        for s in (conn, wconn):
            try:
                s.close()
            except OSError:
                pass
