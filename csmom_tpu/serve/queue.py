"""Bounded admission queue: SLO classes, deadlines, closed per-class books.

The front door of the signal service.  Four properties the rest of the
serve pipeline (and the chaos scenarios) build on:

- **Bounded, rejecting**: the queue holds at most ``capacity`` requests.
  A submit against a full queue is REJECTED immediately with a
  retry-after hint derived from the observed drain rate — backpressure
  instead of unbounded buffering, so overload degrades into fast, honest
  rejections rather than a latency collapse followed by an OOM.
- **SLO classes, not bare priorities** (:mod:`csmom_tpu.serve.slo`):
  every request belongs to a named class (``interactive`` > ``standard``
  > ``bulk``; the r10 name ``batch`` aliases to ``bulk``) carrying a
  deadline budget, an admission token-bucket quota, and a queue-share
  bound.  Over-quota and over-share submissions reject at the door
  (``rejected_quota``, per class) BEFORE they can occupy capacity — a
  bulk tenant provably cannot starve interactive admission, and
  collection order prefers lower rank, so it cannot starve dispatch
  either.
- **Deadlines are cancellations**: every request may carry a monotonic
  deadline; one that expires while still queued is marked ``expired``
  and NEVER dispatched (the batcher's collect pass skips it) — scoring a
  signal nobody is still waiting for would burn device time that live
  requests need.  A request whose dispatch began before its deadline is
  served even if it finishes late (the work was already spent).
- **Closed accounting, globally AND per class**: every request presented
  via :meth:`submit` terminates in exactly one of ``served`` /
  ``rejected`` / ``expired``, and the counters prove it —
  ``served + rejected + expired == admitted`` once drained, for the
  global book and for every class book (:meth:`invariant_violations` is
  the mechanical check; the SERVE artifact schema enforces both).
  Terminal transitions go through one guarded method, so a request can
  never be double-counted or silently dropped — even when a worker
  crashes mid-batch.  Coalesced followers (identical in-flight requests
  sharing one dispatch, :mod:`csmom_tpu.serve.cache`) resolve INSIDE the
  leader's exactly-once transition, so each waiter reaches its terminal
  state exactly once and the books count every one of them.

Collection is deadline-aware (the adaptive batcher's contract): collect
fires when a full bucket's worth is waiting, when the coalescing window
closes, or EARLY when any queued request's remaining deadline budget
dips under the caller's risk margin — the Orca-style continuous-
batching refinement adapted to padded shape buckets (see
:mod:`csmom_tpu.serve.batcher` and PAPERS.md [4]).

Stdlib-only, thread-safe, and all timing through
:func:`csmom_tpu.utils.deadline.mono_now_s` (the monotonic helper — the
time-discipline lint pins this module wall-clock-free).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from collections import deque

from csmom_tpu.serve.slo import SLOPolicy, default_policy
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["AdmissionQueue", "PRIORITIES", "Request", "TERMINAL_STATES"]

# legacy export (the r10 pair); the live class set comes from the policy
PRIORITIES = ("interactive", "batch")
TERMINAL_STATES = ("served", "rejected", "expired")

_IDS = itertools.count(1)

# retry-after hint bounds (see _retry_after_locked): before the first
# request has ever been served the EMA drain rate is UNDEFINED, so the
# hint falls back to a conservative per-request default instead of
# surfacing None/0 to the first overloaded callers; and however deep the
# queue or slow the drain, the hint is capped — "retry in 90 s" is not
# actionable advice from a bounded queue, it is a misread of a transient
RETRY_AFTER_COLD_PER_REQ_S = 0.005
RETRY_AFTER_MIN_S = 0.001
RETRY_AFTER_MAX_S = 2.0

# the per-class terminal counter names every class book carries
_CLASS_COUNTERS = ("admitted", "served", "rejected", "expired",
                   "rejected_quota")


@dataclasses.dataclass
class Request:
    """One scoring request and its life-cycle record.

    ``values``/``mask`` are the request's panel (numpy ``[A, M]``); the
    service pads them into a bucket shape at dispatch.  ``deadline_s`` is
    ABSOLUTE monotonic seconds (None = no deadline).  State moves
    ``queued -> dispatched -> served`` on the happy path, or terminates
    early in ``rejected`` / ``expired``; ``wait()`` blocks the caller
    until a terminal state.  A coalesced follower (state ``coalesced``)
    never enters the deques: it resolves with its leader.
    """

    kind: str
    values: object
    mask: object
    n_assets: int
    priority: str = "interactive"
    deadline_s: float | None = None
    # the live-panel version the request's inputs were snapshotted at
    # (None for batch-panel requests); stamped through to the response so
    # ingest-vs-serve version reconciliation is checkable arithmetic
    panel_version: int | None = None
    req_id: int = dataclasses.field(default_factory=lambda: next(_IDS))
    state: str = "queued"
    result: object = None
    error: str | None = None
    retry_after_s: float | None = None
    cache_hit: bool = False
    coalesced: bool = False
    cache_key: object = None     # set on cache-eligible leaders (service)
    t_submit_s: float = 0.0
    t_dispatch_s: float | None = None
    t_done_s: float | None = None
    # the request's trace context (obs.trace): None when tracing is off
    # AND the request was built outside a service; the shared no-op
    # singleton when a service minted it disarmed.  Call sites guard on
    # None so bare test Requests cost nothing.
    trace: object = dataclasses.field(default=None, repr=False,
                                      compare=False)
    followers: list = dataclasses.field(default_factory=list, repr=False,
                                        compare=False)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the request is terminal; True iff it is."""
        return self._done.wait(timeout)

    @property
    def queue_wait_s(self) -> float | None:
        """Seconds spent queued before dispatch (or before early
        termination for rejected/expired requests)."""
        end = self.t_dispatch_s if self.t_dispatch_s is not None else self.t_done_s
        return None if end is None else max(0.0, end - self.t_submit_s)

    @property
    def service_s(self) -> float | None:
        """Dispatch-to-done seconds (None until served)."""
        if self.t_dispatch_s is None or self.t_done_s is None:
            return None
        return max(0.0, self.t_done_s - self.t_dispatch_s)

    @property
    def total_s(self) -> float | None:
        return (None if self.t_done_s is None
                else max(0.0, self.t_done_s - self.t_submit_s))

    def expired_at(self, now_s: float) -> bool:
        return self.deadline_s is not None and now_s > self.deadline_s


class AdmissionQueue:
    """Bounded multi-class FIFO with quotas and deadline cancellation.

    ``admitted`` counts every request PRESENTED via submit (the
    accounting denominator): a queue-full or over-quota rejection is a
    presented request that terminated in ``rejected``, so the invariant
    ``served + rejected + expired == admitted`` closes over backpressure
    and quota enforcement too — nothing the caller ever handed us can
    vanish from the ledger.  The same equation closes PER CLASS.
    """

    def __init__(self, capacity: int = 64,
                 policy: SLOPolicy | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.policy = policy or default_policy()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queues = {name: deque() for name in self.policy.names()}
        self._buckets = {c.name: c.make_bucket()
                         for c in self.policy.classes}
        # accounting counters (see invariant_violations)
        self.admitted = 0
        self.served = 0
        self.rejected = 0
        self.expired = 0
        self.rejected_queue_full = 0
        self.rejected_worker_crash = 0
        self.rejected_unserveable = 0
        self.rejected_quota = 0
        self.served_cache_hits = 0
        self.served_coalesced = 0
        self.rejected_coalesced = 0
        # requests refused because their live-panel snapshot version had
        # skewed beyond the service's allowance (the streaming analogue
        # of the pool's AOT-cache version gate)
        self.rejected_version_skew = 0
        # requests dispatched AFTER their deadline had already passed —
        # structurally 0 (collect cancels first); the counter exists so
        # the artifact can CLAIM it, not hope it
        self.expired_dispatched = 0
        # per-class books: class name -> {admitted, served, ...}
        self.by_class = {name: dict.fromkeys(_CLASS_COUNTERS, 0)
                         for name in self.policy.names()}
        # EMA of per-request service seconds, feeding the retry-after hint
        self._ema_per_req_s: float | None = None

    def resolve_class(self, name: str) -> str:
        return self.policy.resolve_name(name)

    def retune_quota(self, cls_name: str, quota_rps: float,
                     quota_burst: float | None = None) -> bool:
        """The autoscaler's seam (``serve/fleet.py``): retune a class's
        admission quota IN PLACE.  Only classes that already carry a
        bucket are tunable — granting an unquota'd class a quota at
        runtime would change admission semantics, not tune them.
        Returns True when applied."""
        cls = self.policy.resolve(cls_name)
        with self._lock:
            bucket = self._buckets.get(cls.name)
            if bucket is None or quota_rps <= 0:
                return False
            bucket.rate = float(quota_rps)
            bucket.burst = float(quota_burst if quota_burst
                                 and quota_burst > 0 else 1.5 * quota_rps)
            return True

    # ------------------------------------------------------------- admit --

    def submit(self, req: Request) -> Request:
        """Admit or reject ``req``; returns it either way (terminal state
        and ``retry_after_s`` set on rejection).  Admission order:
        global capacity (a full queue is backpressure no matter the
        class), class queue share, THEN the class quota bucket — a
        request the queue could not have held anyway must not burn a
        quota token, or one overload episode would punish the class
        twice (once as backpressure, again as a drained bucket when the
        queue frees)."""
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics

        cls = self.policy.resolve(req.priority)
        req.priority = cls.name
        req.t_submit_s = mono_now_s()
        checkpoint("serve.admit", kind=req.kind, priority=req.priority)
        with self._lock:
            self.admitted += 1
            self.by_class[cls.name]["admitted"] += 1
            queue_full = self._depth_locked() >= self.capacity
            over_share = (not queue_full
                          and len(self._queues[cls.name])
                          >= cls.max_queued(self.capacity))
            if queue_full or over_share:
                if over_share:
                    # the class hit ITS bound, not the queue's: quota
                    # enforcement, counted in the class's own book
                    self.rejected_quota += 1
                    self.by_class[cls.name]["rejected_quota"] += 1
                else:
                    self.rejected_queue_full += 1
                req.retry_after_s = self._retry_after_locked()
                what = (f"class {cls.name!r} queue share "
                        f"({cls.max_queued(self.capacity)} of "
                        f"{self.capacity} slots)" if over_share
                        else f"queue full ({self.capacity} queued)")
                self._terminate_locked(
                    req, "rejected",
                    error=f"{what}; retry after "
                          f"~{req.retry_after_s:.3f}s",
                )
                # metrics mirror the books: a share rejection is quota
                # enforcement, not capacity exhaustion
                metrics.counter("serve.rejected_quota" if over_share
                                else "serve.rejected_queue_full").inc()
                return req
            bucket = self._buckets[cls.name]
            if bucket is not None and not bucket.try_take(req.t_submit_s):
                self.rejected_quota += 1
                self.by_class[cls.name]["rejected_quota"] += 1
                req.retry_after_s = max(RETRY_AFTER_MIN_S,
                                        min(RETRY_AFTER_MAX_S,
                                            1.0 / bucket.rate))
                self._terminate_locked(
                    req, "rejected",
                    error=f"class {cls.name!r} over its admission quota "
                          f"({bucket.rate:g} req/s sustained); retry "
                          f"after ~{req.retry_after_s:.3f}s",
                )
                metrics.counter("serve.rejected_quota").inc()
                return req
            self._queues[cls.name].append(req)
            if req.trace is not None:
                req.trace.mark("admit")
            metrics.gauge("serve.queue_depth").set(self._depth_locked())
            self._nonempty.notify()
        return req

    def serve_at_door(self, req: Request, result) -> Request:
        """Present-and-serve in one step: a cache hit.  The request still
        counts toward ``admitted`` and ``served`` so the books close over
        cache hits like everything else."""
        from csmom_tpu.obs import metrics

        cls = self.policy.resolve(req.priority)
        req.priority = cls.name
        with self._lock:
            self.admitted += 1
            self.by_class[cls.name]["admitted"] += 1
            req.t_submit_s = mono_now_s()
            req.cache_hit = True
            if self._terminate_locked(req, "served", result=result):
                self.served_cache_hits += 1
                metrics.counter("serve.cache_hits").inc()
        return req

    def attach_follower(self, leader: Request, follower: Request) -> bool:
        """Attach ``follower`` to ``leader`` (identical in-flight request
        sharing one dispatch).  False iff the leader is already terminal
        — the caller re-checks the cache instead.  An attached follower
        is admitted (counted) and resolves inside the leader's terminal
        transition."""
        cls = self.policy.resolve(follower.priority)
        follower.priority = cls.name
        with self._lock:
            if leader.state in TERMINAL_STATES:
                return False
            follower.state = "coalesced"
            follower.coalesced = True
            follower.t_submit_s = mono_now_s()
            leader.followers.append(follower)
            self.admitted += 1
            self.by_class[cls.name]["admitted"] += 1
        return True

    def _retry_after_locked(self) -> float:
        """Drain-rate estimate: depth * observed per-request service
        time, clamped to [RETRY_AFTER_MIN_S, RETRY_AFTER_MAX_S].

        Cold start: before anything has been served, ``_ema_per_req_s``
        is None (and a degenerate 0.0 EMA is falsy too) — the bounded
        default ``RETRY_AFTER_COLD_PER_REQ_S`` stands in, so the FIRST
        overload rejection already carries an actionable float hint,
        never None (the regression that motivated these named bounds).
        """
        per_req = (self._ema_per_req_s if self._ema_per_req_s
                   else RETRY_AFTER_COLD_PER_REQ_S)
        return min(RETRY_AFTER_MAX_S,
                   max(RETRY_AFTER_MIN_S, self._depth_locked() * per_req))

    # ------------------------------------------------------------ collect --

    def _depth_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depth(self) -> int:
        with self._lock:
            return self._depth_locked()

    def _expire_locked(self, now_s: float) -> None:
        """Cancel every queued request whose deadline has passed — BEFORE
        any of them can be gathered into a micro-batch."""
        from csmom_tpu.obs import metrics

        for q in self._queues.values():
            live = [r for r in q if not r.expired_at(now_s)]
            if len(live) != len(q):
                for r in q:
                    if r.expired_at(now_s):
                        self._terminate_locked(
                            r, "expired",
                            error="deadline expired while queued "
                                  "(never dispatched)",
                        )
                        metrics.counter("serve.expired").inc()
                q.clear()
                q.extend(live)

    def _min_budget_locked(self, kind: str, now_s: float) -> float | None:
        """Smallest remaining deadline budget among queued requests of
        ``kind`` (None = none carries a deadline) — the early-fire
        signal the adaptive batcher acts on."""
        best = None
        for q in self._queues.values():
            for r in q:
                if r.kind == kind and r.deadline_s is not None:
                    rem = r.deadline_s - now_s
                    if best is None or rem < best:
                        best = rem
        return best

    def collect(self, max_n: int, window_s: float, stop: threading.Event,
                risk_s: float = 0.0) -> tuple:
        """Gather up to ``max_n`` same-endpoint requests for one
        micro-batch; returns ``(requests, fire_reason)``.

        Blocks until at least one live request exists (or ``stop`` is
        set, returning ``([], "stopped")``).  Selection: the oldest
        request of the lowest-rank non-empty class fixes the endpoint;
        remaining slots fill with same-endpoint requests, lower ranks
        first.  Expired requests are cancelled here and never returned.

        Fire reasons (the adaptive-dispatch decision, recorded per batch
        in the SERVE artifact):

        - ``"full"``: a full ``max_n`` is waiting — dispatch now, the
          batch cannot grow further on the warmed bucket grid.
        - ``"deadline_risk"``: some queued request's remaining budget
          dipped under ``risk_s`` (the caller's estimate of one batch
          service time plus margin) — firing later would expire it.
        - ``"window"``: the coalescing window since the first arrival
          closed without either trigger above.
        - ``"refill"``: ``window_s <= 0`` — the engine just freed with
          work already waiting, so the next micro-batch dispatches
          immediately with whatever is queued (continuous batching:
          under sustained load the window never adds latency).
        """
        deadline = None
        while not stop.is_set():
            with self._lock:
                now = mono_now_s()
                self._expire_locked(now)
                first = self._peek_locked()
                if first is not None:
                    if deadline is None:
                        deadline = now + max(0.0, window_s)
                    n_kind = self._count_kind_locked(first.kind)
                    if n_kind >= max_n:
                        return self._take_locked(first.kind, max_n), "full"
                    if risk_s > 0.0:
                        budget = self._min_budget_locked(first.kind, now)
                        # at risk = the request cannot survive waiting
                        # out the REST of the coalescing window and then
                        # one batch service time: fire now, don't let a
                        # window optimization expire a live deadline
                        if budget is not None and budget <= (
                                (deadline - now) + risk_s):
                            return (self._take_locked(first.kind, max_n),
                                    "deadline_risk")
                    if now >= deadline:
                        reason = "refill" if window_s <= 0.0 else "window"
                        return self._take_locked(first.kind, max_n), reason
                    # capped wait: queued deadlines may expire (or dip
                    # into risk) before the coalescing window closes, so
                    # re-sweep periodically
                    self._nonempty.wait(
                        timeout=max(min(deadline - now, 0.05), 0.001))
                else:
                    # empty queue: nothing to sweep, nothing to coalesce —
                    # block until a submit notifies (or stop() wakes us);
                    # an idle service must not spin.  The stop re-check
                    # HOLDS THE LOCK: stop() sets the event before wake()
                    # can acquire it, so a stop that completed between the
                    # loop-top check and here is seen now instead of its
                    # notify being lost to a waiter that hadn't waited yet
                    deadline = None
                    if stop.is_set():
                        return [], "stopped"
                    self._nonempty.wait()
        return [], "stopped"

    def _peek_locked(self):
        for name in self.policy.names():
            if self._queues[name]:
                return self._queues[name][0]
        return None

    def _count_kind_locked(self, kind: str) -> int:
        return sum(1 for q in self._queues.values() for r in q
                   if r.kind == kind)

    def _take_locked(self, kind: str, max_n: int) -> list:
        from csmom_tpu.obs import metrics

        out: list = []
        for name in self.policy.names():
            q = self._queues[name]
            keep = deque()
            while q:
                r = q.popleft()
                if r.kind == kind and len(out) < max_n:
                    if r.trace is not None:
                        r.trace.mark("queue_wait")
                    out.append(r)
                else:
                    keep.append(r)
            self._queues[name] = keep
        metrics.gauge("serve.queue_depth").set(self._depth_locked())
        return out

    # ----------------------------------------------------------- terminal --

    def _terminate_locked(self, req: Request, state: str,
                          result=None, error: str | None = None) -> bool:
        """The single guarded terminal transition.  Increments the
        terminal counters (global + per class) and resolves any coalesced
        followers — all inside the exactly-once guard, so neither the
        leader nor a follower can be double-counted."""
        if req.state in TERMINAL_STATES:
            return False  # exactly-once: a terminal request never moves
        req.state = state
        req.result = result
        if error is not None:
            req.error = error
        req.t_done_s = mono_now_s()
        self._bump_class_locked(req.priority, state)
        if state == "served":
            self.served += 1
            if req.service_s is not None:
                ema = self._ema_per_req_s
                self._ema_per_req_s = (
                    req.service_s if ema is None
                    else 0.8 * ema + 0.2 * req.service_s)
        elif state == "expired":
            self.expired += 1
        else:
            self.rejected += 1
        if req.trace is not None:
            # the trace closes inside the SAME exactly-once guard as the
            # request: one complete (served) or one reasoned partial per
            # admitted request — the closed-trace-books contract.  The
            # residual auto-labels as the stage after the last mark
            # (queued -> queue_wait, post-dispatch -> serialize).
            req.trace.close(state, reason=req.error)
        req._done.set()
        # coalesced followers ride the leader's fate: served with the
        # same result, or rejected with the leader's outcome as reason.
        # The deadline contract survives coalescing: a follower whose
        # own deadline had already passed when the shared dispatch BEGAN
        # expires (the same never-dispatch-expired rule the deques
        # enforce); one whose dispatch began in time is served even if
        # it finishes late (the work was already spent — shared or not).
        if req.followers:
            followers, req.followers = req.followers, []
            for f in followers:
                if f.state in TERMINAL_STATES:
                    continue  # defensive; a follower is only ever ours
                if state == "served" and f.expired_at(
                        req.t_dispatch_s if req.t_dispatch_s is not None
                        else req.t_done_s):
                    f.state = "expired"
                    f.error = ("deadline expired before the coalesced "
                               "dispatch began (never dispatched)")
                    self.expired += 1
                    self._bump_class_locked(f.priority, "expired")
                elif state == "served":
                    f.state = "served"
                    # mutable dict payloads are copied per waiter so no
                    # coalesced caller can edit what another one reads
                    # (ndarray payloads arrive frozen from the dispatch)
                    f.result = (dict(result) if isinstance(result, dict)
                                else result)
                    # the leader's dispatch served the follower too: its
                    # timeline shares the dispatch instant
                    f.t_dispatch_s = req.t_dispatch_s
                    self.served += 1
                    self.served_coalesced += 1
                    self._bump_class_locked(f.priority, "served")
                else:
                    f.state = "rejected"
                    f.error = (f"coalesced onto request "
                               f"{req.req_id} which ended {state}"
                               + (f": {error}" if error else ""))
                    self.rejected += 1
                    self.rejected_coalesced += 1
                    self._bump_class_locked(f.priority, "rejected")
                if f.trace is not None:
                    # a follower never queued or dispatched: its whole
                    # wall is the shared wait, labeled coalesce
                    f.trace.set(coalesced=True).close(
                        f.state, reason=f.error, stage="coalesce")
                f.t_done_s = req.t_done_s
                f._done.set()
        return True

    def _bump_class_locked(self, class_name: str, state: str) -> None:
        book = self.by_class.get(class_name)
        if book is not None:
            book[state] += 1

    def finish_expired(self, req: Request,
                       error: str = "deadline expired while queued "
                                    "(never dispatched)") -> None:
        """Expire a request OUTSIDE the collect sweep — the dispatch
        boundary's last-instant check (a deadline can pass in the gap
        between collection and dispatch; the contract is enforced at the
        boundary, not hoped about)."""
        with self._lock:
            self._terminate_locked(req, "expired", error=error)

    def mark_dispatched(self, req: Request, now_s: float) -> None:
        with self._lock:
            req.state = "dispatched"
            req.t_dispatch_s = now_s
            if req.expired_at(now_s):
                # structurally unreachable (collect sweeps, then the
                # dispatch boundary re-checks); counted so the artifact's
                # expired_dispatched == 0 is a measurement, not a hope
                self.expired_dispatched += 1

    def finish_served(self, req: Request, result) -> None:
        with self._lock:
            self._terminate_locked(req, "served", result=result)

    def reject_at_door(self, req: Request, error: str,
                       version_skew: bool = False) -> None:
        """Present-and-reject in one step (unserveable shape/endpoint, or
        a skewed live-panel version): the request still counts toward
        ``admitted`` so the accounting equation closes over door
        rejections too."""
        cls = self.policy.resolve(req.priority)
        req.priority = cls.name
        with self._lock:
            self.admitted += 1
            self.by_class[cls.name]["admitted"] += 1
            req.t_submit_s = mono_now_s()
            if self._terminate_locked(req, "rejected", error=error):
                if version_skew:
                    self.rejected_version_skew += 1
                else:
                    self.rejected_unserveable += 1

    def finish_rejected(self, req: Request, error: str,
                        worker_crash: bool = False) -> None:
        with self._lock:
            if self._terminate_locked(req, "rejected", error=error):
                if worker_crash:
                    self.rejected_worker_crash += 1
                else:
                    self.rejected_unserveable += 1

    # --------------------------------------------------------- accounting --

    def wake(self) -> None:
        """Nudge a collect() blocked on the condition (shutdown path)."""
        with self._lock:
            self._nonempty.notify_all()

    def accounting(self) -> dict:
        with self._lock:
            return {
                "admitted": self.admitted,
                "served": self.served,
                "rejected": self.rejected,
                "expired": self.expired,
                "expired_dispatched": self.expired_dispatched,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_worker_crash": self.rejected_worker_crash,
                "rejected_unserveable": self.rejected_unserveable,
                "rejected_version_skew": self.rejected_version_skew,
                "rejected_quota": self.rejected_quota,
                "rejected_coalesced": self.rejected_coalesced,
                "served_cache_hits": self.served_cache_hits,
                "served_coalesced": self.served_coalesced,
                "in_queue": self._depth_locked(),
            }

    def class_accounting(self) -> dict:
        """Per-class books (class name -> closed terminal counters)."""
        with self._lock:
            return {name: dict(book)
                    for name, book in self.by_class.items()}

    def invariant_violations(self) -> list:
        """The closed-accounting check (empty = holds).  Valid once the
        queue is drained: every admitted request must sit in exactly one
        terminal bucket — globally and inside every class book."""
        a = self.accounting()
        classes = self.class_accounting()
        out = []
        if a["in_queue"]:
            out.append(f"queue not drained: {a['in_queue']} still queued")
        total = a["served"] + a["rejected"] + a["expired"]
        if total != a["admitted"]:
            out.append(
                f"request accounting broken: served {a['served']} + "
                f"rejected {a['rejected']} + expired {a['expired']} = "
                f"{total} != admitted {a['admitted']}"
            )
        if a["expired_dispatched"]:
            out.append(
                f"{a['expired_dispatched']} request(s) dispatched after "
                "their deadline — expiry-while-queued must cancel, "
                "never dispatch"
            )
        for name, book in classes.items():
            ct = book["served"] + book["rejected"] + book["expired"]
            if ct != book["admitted"]:
                out.append(
                    f"class {name!r} book broken: served {book['served']} "
                    f"+ rejected {book['rejected']} + expired "
                    f"{book['expired']} = {ct} != admitted "
                    f"{book['admitted']}"
                )
        for key in ("admitted", "served", "rejected", "expired"):
            csum = sum(book[key] for book in classes.values())
            if csum != a[key]:
                out.append(
                    f"class books do not sum to the global book: "
                    f"sum({key}) = {csum} != {a[key]}"
                )
        return out
