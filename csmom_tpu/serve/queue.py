"""Bounded admission queue: backpressure, priorities, deadlines, accounting.

The front door of the signal service.  Three properties the rest of the
serve pipeline (and the chaos scenarios) build on:

- **Bounded, rejecting**: the queue holds at most ``capacity`` requests.
  A submit against a full queue is REJECTED immediately with a
  retry-after hint derived from the observed drain rate — backpressure
  instead of unbounded buffering, so overload degrades into fast, honest
  rejections rather than a latency collapse followed by an OOM.
- **Deadlines are cancellations**: every request may carry a monotonic
  deadline; one that expires while still queued is marked ``expired``
  and NEVER dispatched (the batcher's collect pass skips it) — scoring a
  signal nobody is still waiting for would burn device time that live
  requests need.  A request whose dispatch began before its deadline is
  served even if it finishes late (the work was already spent).
- **Closed accounting**: every request presented via :meth:`submit`
  terminates in exactly one of ``served`` / ``rejected`` / ``expired``,
  and the counters prove it: ``served + rejected + expired == admitted``
  once the queue is drained (:meth:`invariant_violations` is the
  mechanical check the rehearse scenarios and the SERVE artifact
  validator both run).  Terminal transitions go through one guarded
  method, so a request can never be double-counted or silently dropped —
  even when a worker crashes mid-batch.

Two priority classes (``interactive`` > ``batch``): collection always
starts from the oldest interactive request; batch requests of the same
endpoint fill the remaining micro-batch slots.

Stdlib-only, thread-safe, and all timing through
:func:`csmom_tpu.utils.deadline.mono_now_s` (the monotonic helper — the
time-discipline lint pins this module wall-clock-free).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from collections import deque

from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["AdmissionQueue", "PRIORITIES", "Request", "TERMINAL_STATES"]

PRIORITIES = ("interactive", "batch")
TERMINAL_STATES = ("served", "rejected", "expired")

_IDS = itertools.count(1)

# retry-after hint bounds (see _retry_after_locked): before the first
# request has ever been served the EMA drain rate is UNDEFINED, so the
# hint falls back to a conservative per-request default instead of
# surfacing None/0 to the first overloaded callers; and however deep the
# queue or slow the drain, the hint is capped — "retry in 90 s" is not
# actionable advice from a bounded queue, it is a misread of a transient
RETRY_AFTER_COLD_PER_REQ_S = 0.005
RETRY_AFTER_MIN_S = 0.001
RETRY_AFTER_MAX_S = 2.0


@dataclasses.dataclass
class Request:
    """One scoring request and its life-cycle record.

    ``values``/``mask`` are the request's panel (numpy ``[A, M]``); the
    service pads them into a bucket shape at dispatch.  ``deadline_s`` is
    ABSOLUTE monotonic seconds (None = no deadline).  State moves
    ``queued -> dispatched -> served`` on the happy path, or terminates
    early in ``rejected`` / ``expired``; ``wait()`` blocks the caller
    until a terminal state.
    """

    kind: str
    values: object
    mask: object
    n_assets: int
    priority: str = "interactive"
    deadline_s: float | None = None
    # the live-panel version the request's inputs were snapshotted at
    # (None for batch-panel requests); stamped through to the response so
    # ingest-vs-serve version reconciliation is checkable arithmetic
    panel_version: int | None = None
    req_id: int = dataclasses.field(default_factory=lambda: next(_IDS))
    state: str = "queued"
    result: object = None
    error: str | None = None
    retry_after_s: float | None = None
    t_submit_s: float = 0.0
    t_dispatch_s: float | None = None
    t_done_s: float | None = None
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the request is terminal; True iff it is."""
        return self._done.wait(timeout)

    @property
    def queue_wait_s(self) -> float | None:
        """Seconds spent queued before dispatch (or before early
        termination for rejected/expired requests)."""
        end = self.t_dispatch_s if self.t_dispatch_s is not None else self.t_done_s
        return None if end is None else max(0.0, end - self.t_submit_s)

    @property
    def service_s(self) -> float | None:
        """Dispatch-to-done seconds (None until served)."""
        if self.t_dispatch_s is None or self.t_done_s is None:
            return None
        return max(0.0, self.t_done_s - self.t_dispatch_s)

    @property
    def total_s(self) -> float | None:
        return (None if self.t_done_s is None
                else max(0.0, self.t_done_s - self.t_submit_s))

    def expired_at(self, now_s: float) -> bool:
        return self.deadline_s is not None and now_s > self.deadline_s


class AdmissionQueue:
    """Bounded two-priority FIFO with deadline cancellation.

    ``admitted`` counts every request PRESENTED via submit (the
    accounting denominator): a queue-full rejection is a presented
    request that terminated in ``rejected``, so the invariant
    ``served + rejected + expired == admitted`` closes over backpressure
    too — nothing the caller ever handed us can vanish from the ledger.
    """

    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queues = {p: deque() for p in PRIORITIES}
        # accounting counters (see invariant_violations)
        self.admitted = 0
        self.served = 0
        self.rejected = 0
        self.expired = 0
        self.rejected_queue_full = 0
        self.rejected_worker_crash = 0
        self.rejected_unserveable = 0
        # requests refused because their live-panel snapshot version had
        # skewed beyond the service's allowance (the streaming analogue
        # of the pool's AOT-cache version gate)
        self.rejected_version_skew = 0
        # requests dispatched AFTER their deadline had already passed —
        # structurally 0 (collect cancels first); the counter exists so
        # the artifact can CLAIM it, not hope it
        self.expired_dispatched = 0
        # EMA of per-request service seconds, feeding the retry-after hint
        self._ema_per_req_s: float | None = None

    # ------------------------------------------------------------- admit --

    def submit(self, req: Request) -> Request:
        """Admit or reject ``req``; returns it either way (terminal state
        and ``retry_after_s`` set on rejection)."""
        if req.priority not in PRIORITIES:
            raise ValueError(f"unknown priority {req.priority!r}")
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics

        req.t_submit_s = mono_now_s()
        checkpoint("serve.admit", kind=req.kind, priority=req.priority)
        with self._lock:
            self.admitted += 1
            if self._depth_locked() >= self.capacity:
                self.rejected += 1
                self.rejected_queue_full += 1
                req.retry_after_s = self._retry_after_locked()
                self._terminate_locked(
                    req, "rejected",
                    error=f"queue full ({self.capacity} queued); retry after "
                          f"~{req.retry_after_s:.3f}s",
                )
                metrics.counter("serve.rejected_queue_full").inc()
                return req
            self._queues[req.priority].append(req)
            metrics.gauge("serve.queue_depth").set(self._depth_locked())
            self._nonempty.notify()
        return req

    def _retry_after_locked(self) -> float:
        """Drain-rate estimate: depth * observed per-request service
        time, clamped to [RETRY_AFTER_MIN_S, RETRY_AFTER_MAX_S].

        Cold start: before anything has been served, ``_ema_per_req_s``
        is None (and a degenerate 0.0 EMA is falsy too) — the bounded
        default ``RETRY_AFTER_COLD_PER_REQ_S`` stands in, so the FIRST
        overload rejection already carries an actionable float hint,
        never None (the regression that motivated these named bounds).
        """
        per_req = (self._ema_per_req_s if self._ema_per_req_s
                   else RETRY_AFTER_COLD_PER_REQ_S)
        return min(RETRY_AFTER_MAX_S,
                   max(RETRY_AFTER_MIN_S, self._depth_locked() * per_req))

    # ------------------------------------------------------------ collect --

    def _depth_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depth(self) -> int:
        with self._lock:
            return self._depth_locked()

    def _expire_locked(self, now_s: float) -> None:
        """Cancel every queued request whose deadline has passed — BEFORE
        any of them can be gathered into a micro-batch."""
        from csmom_tpu.obs import metrics

        for q in self._queues.values():
            live = [r for r in q if not r.expired_at(now_s)]
            if len(live) != len(q):
                for r in q:
                    if r.expired_at(now_s):
                        self.expired += 1
                        self._terminate_locked(
                            r, "expired",
                            error="deadline expired while queued "
                                  "(never dispatched)",
                        )
                        metrics.counter("serve.expired").inc()
                q.clear()
                q.extend(live)

    def collect(self, max_n: int, window_s: float,
                stop: threading.Event) -> list:
        """Gather up to ``max_n`` same-endpoint requests for one
        micro-batch, waiting at most ``window_s`` past the first arrival
        for co-batchable company.

        Blocks until at least one live request exists (or ``stop`` is
        set, returning ``[]``).  Selection: the oldest request of the
        highest non-empty priority fixes the endpoint; remaining slots
        fill with same-endpoint requests, interactive first.  Expired
        requests are cancelled here and never returned.
        """
        deadline = None
        while not stop.is_set():
            with self._lock:
                self._expire_locked(mono_now_s())
                first = self._peek_locked()
                if first is not None:
                    if deadline is None:
                        deadline = mono_now_s() + window_s
                    if (self._count_kind_locked(first.kind) >= max_n
                            or mono_now_s() >= deadline):
                        return self._take_locked(first.kind, max_n)
                    # capped wait: queued deadlines may expire before the
                    # coalescing window closes, so re-sweep periodically
                    self._nonempty.wait(
                        timeout=max(min(deadline - mono_now_s(), 0.05),
                                    0.001))
                else:
                    # empty queue: nothing to sweep, nothing to coalesce —
                    # block until a submit notifies (or stop() wakes us);
                    # an idle service must not spin.  The stop re-check
                    # HOLDS THE LOCK: stop() sets the event before wake()
                    # can acquire it, so a stop that completed between the
                    # loop-top check and here is seen now instead of its
                    # notify being lost to a waiter that hadn't waited yet
                    deadline = None
                    if stop.is_set():
                        return []
                    self._nonempty.wait()
        return []

    def _peek_locked(self):
        for p in PRIORITIES:
            if self._queues[p]:
                return self._queues[p][0]
        return None

    def _count_kind_locked(self, kind: str) -> int:
        return sum(1 for q in self._queues.values() for r in q
                   if r.kind == kind)

    def _take_locked(self, kind: str, max_n: int) -> list:
        from csmom_tpu.obs import metrics

        out: list = []
        for p in PRIORITIES:
            q = self._queues[p]
            keep = deque()
            while q:
                r = q.popleft()
                if r.kind == kind and len(out) < max_n:
                    out.append(r)
                else:
                    keep.append(r)
            self._queues[p] = keep
        metrics.gauge("serve.queue_depth").set(self._depth_locked())
        return out

    # ----------------------------------------------------------- terminal --

    def _terminate_locked(self, req: Request, state: str,
                          result=None, error: str | None = None) -> bool:
        if req.state in TERMINAL_STATES:
            return False  # exactly-once: a terminal request never moves
        req.state = state
        req.result = result
        if error is not None:
            req.error = error
        req.t_done_s = mono_now_s()
        req._done.set()
        return True

    def finish_expired(self, req: Request,
                       error: str = "deadline expired while queued "
                                    "(never dispatched)") -> None:
        """Expire a request OUTSIDE the collect sweep — the dispatch
        boundary's last-instant check (a deadline can pass in the gap
        between collection and dispatch; the contract is enforced at the
        boundary, not hoped about)."""
        with self._lock:
            if self._terminate_locked(req, "expired", error=error):
                self.expired += 1

    def mark_dispatched(self, req: Request, now_s: float) -> None:
        with self._lock:
            req.state = "dispatched"
            req.t_dispatch_s = now_s
            if req.expired_at(now_s):
                # structurally unreachable (collect sweeps, then the
                # dispatch boundary re-checks); counted so the artifact's
                # expired_dispatched == 0 is a measurement, not a hope
                self.expired_dispatched += 1

    def finish_served(self, req: Request, result) -> None:
        with self._lock:
            if self._terminate_locked(req, "served", result=result):
                self.served += 1
                if req.service_s is not None:
                    ema = self._ema_per_req_s
                    self._ema_per_req_s = (
                        req.service_s if ema is None
                        else 0.8 * ema + 0.2 * req.service_s)

    def reject_at_door(self, req: Request, error: str,
                       version_skew: bool = False) -> None:
        """Present-and-reject in one step (unserveable shape/endpoint, or
        a skewed live-panel version): the request still counts toward
        ``admitted`` so the accounting equation closes over door
        rejections too."""
        with self._lock:
            self.admitted += 1
            req.t_submit_s = mono_now_s()
            if self._terminate_locked(req, "rejected", error=error):
                self.rejected += 1
                if version_skew:
                    self.rejected_version_skew += 1
                else:
                    self.rejected_unserveable += 1

    def finish_rejected(self, req: Request, error: str,
                        worker_crash: bool = False) -> None:
        with self._lock:
            if self._terminate_locked(req, "rejected", error=error):
                self.rejected += 1
                if worker_crash:
                    self.rejected_worker_crash += 1
                else:
                    self.rejected_unserveable += 1

    # --------------------------------------------------------- accounting --

    def wake(self) -> None:
        """Nudge a collect() blocked on the condition (shutdown path)."""
        with self._lock:
            self._nonempty.notify_all()

    def accounting(self) -> dict:
        with self._lock:
            return {
                "admitted": self.admitted,
                "served": self.served,
                "rejected": self.rejected,
                "expired": self.expired,
                "expired_dispatched": self.expired_dispatched,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_worker_crash": self.rejected_worker_crash,
                "rejected_unserveable": self.rejected_unserveable,
                "rejected_version_skew": self.rejected_version_skew,
                "in_queue": self._depth_locked(),
            }

    def invariant_violations(self) -> list:
        """The closed-accounting check (empty = holds).  Valid once the
        queue is drained: every admitted request must sit in exactly one
        terminal bucket."""
        a = self.accounting()
        out = []
        if a["in_queue"]:
            out.append(f"queue not drained: {a['in_queue']} still queued")
        total = a["served"] + a["rejected"] + a["expired"]
        if total != a["admitted"]:
            out.append(
                f"request accounting broken: served {a['served']} + "
                f"rejected {a['rejected']} + expired {a['expired']} = "
                f"{total} != admitted {a['admitted']}"
            )
        if a["expired_dispatched"]:
            out.append(
                f"{a['expired_dispatched']} request(s) dispatched after "
                "their deadline — expiry-while-queued must cancel, "
                "never dispatch"
            )
        return out
