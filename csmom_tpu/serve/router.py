"""Pool router: admission, hedged dispatch, closed cross-process books.

The router is the pool's front door.  It admits every request, fans out
to whichever workers are READY (the supervisor's routable set), and
enforces the serve layer's core invariant ACROSS the process boundary:
every admitted request reaches exactly one terminal state — ``served`` /
``rejected`` / ``expired`` — no matter which worker died, answered late,
or answered twice.

**Hedged retries** (Dean & Barroso, *The Tail at Scale*, CACM 2013):
a request is dispatched to one worker; when a fraction of its deadline
budget elapses with no response, a second attempt fires against a
DIFFERENT worker.  First response wins; the loser's answer is counted
``duplicates_suppressed`` and discarded — the terminal transition is
guarded by one lock, so "exactly once" is structural, not statistical.
Hedging converts a straggling or dying worker from a p99 cliff into one
extra dispatch; the ``hedge_rate`` the artifact records keeps the cost
honest.

**Failover** is the same machinery driven by errors instead of time: a
connection refused/reset (worker crashed, socket gone) fails the attempt
immediately and redispatches to the next worker, up to ``max_attempts``.
Only when every avenue is exhausted does the request terminate
``rejected`` with ``rejected_infra`` incremented — the counter
availability is computed from (``1 - rejected_infra / admitted``):
backpressure and client-deadline expiry are honest answers, infra
failure is the pool failing its job.

The router holds no panels and no queue of its own — worker admission
queues are the buffering layer (each worker owns its backpressure,
Orca-style); the router's state per request is one small record.  All
timing through ``utils.deadline.mono_now_s``.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading

import numpy as np

from csmom_tpu.serve import proto
from csmom_tpu.registry import serve_endpoints
from csmom_tpu.serve.buckets import bucket_spec
from csmom_tpu.serve.slo import default_policy
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["PoolRequest", "Router", "RouterConfig"]

TERMINAL_STATES = ("served", "rejected", "expired")

_IDS = itertools.count(1)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Dispatch policy knobs (defaults tuned for the CPU pool)."""

    profile: str = "serve"
    default_deadline_s: float | None = 0.5
    hedge_fraction: float = 0.35   # of the remaining deadline budget
    hedge_floor_s: float = 0.05    # never hedge sooner than this
    hedge_after_s: float = 0.25    # hedge delay for deadline-less requests
    max_attempts: int = 3          # primary + hedge + one failover
    connect_timeout_s: float = 2.0


@dataclasses.dataclass
class PoolRequest:
    """One pool request's life-cycle record (router-side)."""

    kind: str
    n_assets: int
    priority: str = "interactive"
    deadline_s: float | None = None      # ABSOLUTE monotonic, None = none
    panel_version: int | None = None     # live-panel snapshot version
    req_id: int = dataclasses.field(default_factory=lambda: next(_IDS))
    state: str = "routing"
    result: object = None
    error: str | None = None
    worker_id: str | None = None         # who served it
    hedged: bool = False
    attempts: int = 0
    t_submit_s: float = 0.0
    t_done_s: float | None = None
    # the request's trace context (obs.trace; None = untraced).  The
    # router owns the CLIENT half: route/transport/finalize stages plus
    # whatever worker half the winning attempt brought home.
    trace: object = dataclasses.field(default=None, repr=False,
                                      compare=False)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    @property
    def total_s(self) -> float | None:
        return (None if self.t_done_s is None
                else max(0.0, self.t_done_s - self.t_submit_s))

    def remaining_s(self, now_s: float) -> float | None:
        return (None if self.deadline_s is None
                else self.deadline_s - now_s)


class Router:
    """Admit → dispatch (hedged) → exactly-once terminal accounting."""

    def __init__(self, workers_fn, config: RouterConfig | None = None):
        """``workers_fn() -> list`` of objects with ``.worker_id`` and
        ``.socket_path`` — the supervisor's current READY set (queried
        per attempt, so a worker that died between attempts is already
        gone from the menu)."""
        self.config = config or RouterConfig()
        self.spec = bucket_spec(self.config.profile)
        self.policy = default_policy()
        self._workers_fn = workers_fn
        self._lock = threading.Lock()
        self._rr = itertools.count()
        # per-SLO-class books (closed like the global one); the policy
        # resolves legacy names ("batch" -> "bulk") so the wire protocol
        # and the in-process service count the same classes
        self.by_class = {name: {"admitted": 0, "served": 0, "rejected": 0,
                                "expired": 0}
                         for name in self.policy.names()}
        # accounting counters — the cross-process closed book
        self.admitted = 0
        self.served = 0
        self.rejected = 0
        self.expired = 0
        self.rejected_infra = 0
        self.rejected_unserveable = 0
        self.hedged = 0
        self.hedge_wins = 0
        self.duplicates_suppressed = 0
        self.late_served_suppressed = 0
        self.retries = 0
        self.worker_conn_failures = 0

    # --------------------------------------------------------------- admit

    def submit(self, kind: str, values, mask, priority: str = "interactive",
               deadline_s: float | None = None,
               panel_version: int | None = None) -> PoolRequest:
        """Admit one request; returns its handle (terminal on door
        rejection).  ``deadline_s`` is RELATIVE seconds (None = config
        default)."""
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics
        from csmom_tpu.obs import trace as obs_trace

        values = np.asarray(values)
        mask = np.asarray(mask, dtype=bool)
        n_assets = int(values.shape[0]) if values.ndim == 2 else 0
        rel = (self.config.default_deadline_s if deadline_s is None
               else deadline_s)
        now = mono_now_s()
        try:
            priority = self.policy.resolve_name(priority)
        except ValueError:
            pass  # the worker's own door rejects unknown classes
        budget_ms = None
        try:
            budget_ms = round(1e3 * self.policy.resolve(priority).deadline_s,
                              3)
        except ValueError:
            pass
        req = PoolRequest(
            kind=kind, n_assets=n_assets, priority=priority,
            deadline_s=None if rel is None else now + rel, t_submit_s=now,
            panel_version=panel_version,
            trace=obs_trace.begin(kind, priority,
                                  panel_version=panel_version,
                                  budget_ms=budget_ms))
        with self._lock:
            self.admitted += 1
            if priority in self.by_class:
                self.by_class[priority]["admitted"] += 1
        checkpoint("pool.route", kind=kind, req=req.req_id)
        reason = self._unserveable_reason(kind, values, mask)
        if reason is not None:
            self._terminate(req, "rejected", error=reason, unserveable=True)
            metrics.counter("serve_pool.rejected_unserveable").inc()
            return req
        t = threading.Thread(
            target=self._drive, args=(req, values, mask),
            name=f"csmom-pool-req-{req.req_id}", daemon=True)
        t.start()
        return req

    def _unserveable_reason(self, kind: str, values, mask) -> str | None:
        # same door checks as service.submit: an unserveable request must
        # fail here, not burn dispatch attempts on every worker in turn
        kinds = serve_endpoints()
        if kind not in kinds:
            return f"unknown endpoint {kind!r} (serveable: {kinds})"
        if values.ndim != 2:
            return f"panel must be [assets, months], got ndim={values.ndim}"
        if values.shape[1] != self.spec.months:
            return (f"panel has {values.shape[1]} months; this pool scores "
                    f"{self.spec.months}-month histories")
        if self.spec.asset_bucket_for(values.shape[0]) is None:
            return (f"{values.shape[0]} assets exceeds the largest bucket "
                    f"({self.spec.max_assets})")
        if mask.shape != values.shape:
            return (f"mask shape {mask.shape} does not match the values "
                    f"panel {values.shape}")
        return None

    # ------------------------------------------------------------ dispatch

    def _pick_worker(self, exclude: set):
        workers = [w for w in self._workers_fn()
                   if w.worker_id not in exclude]
        if not workers:
            return None
        return workers[next(self._rr) % len(workers)]

    def _hedge_delay(self, req: PoolRequest, now: float) -> float:
        rem = req.remaining_s(now)
        if rem is None:
            return self.config.hedge_after_s
        return max(self.config.hedge_floor_s,
                   self.config.hedge_fraction * rem)

    def _drive(self, req: PoolRequest, values, mask) -> None:
        """Attempt loop: primary, hedge-on-delay, failover-on-error.

        Event-driven: the loop sleeps on the attempt-conclusion event
        with a timeout set to the next interesting instant (hedge timer,
        deadline), and on every wake acts on exactly one of: a terminal
        state (done), a concluded-but-failed attempt (failover or
        settle), the hedge timer (launch the hedge, at most once), or
        the deadline (expire — after a short grace when a dispatch is
        still in flight, since its work is already spent)."""
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics

        tried: set = set()
        failures: list = []
        state: dict = {"done": threading.Event(), "lock": threading.Lock(),
                       "in_flight": 0, "concluded": 0}

        def launch(is_hedge: bool) -> bool:
            worker = self._pick_worker(tried)
            if worker is None:
                return False
            tried.add(worker.worker_id)
            with self._lock:
                req.attempts += 1
                if is_hedge:
                    req.hedged = True
            with state["lock"]:
                state["in_flight"] += 1
            threading.Thread(
                target=self._attempt, args=(req, worker, values, mask,
                                            is_hedge, state, failures),
                daemon=True).start()
            return True

        if not launch(False):
            self._terminate(req, "rejected", infra=True,
                            error="no ready worker in the pool (all "
                                  "crashed, draining, or never became "
                                  "ready)")
            metrics.counter("serve_pool.rejected_infra").inc()
            return
        hedge_at = mono_now_s() + self._hedge_delay(req, mono_now_s())
        acted = 0
        while True:
            if req.state in TERMINAL_STATES:
                return
            now = mono_now_s()
            rem = req.remaining_s(now)
            with state["lock"]:
                in_flight = state["in_flight"]
                concluded = state["concluded"]
            if concluded > acted:
                acted = concluded
                state["done"].clear()
                if in_flight == 0:
                    # every launched attempt failed: failover while the
                    # budget and the worker menu allow, else settle
                    if ((rem is None or rem > 0)
                            and req.attempts < self.config.max_attempts
                            and launch(False)):
                        with self._lock:
                            self.retries += 1
                        metrics.counter("serve_pool.retries").inc()
                        continue
                    self._settle(req, failures)
                    return
                continue  # a loser concluded; the other attempt lives on
            if rem is not None and rem <= 0:
                if in_flight == 0 or rem <= -_LATE_GRACE_S:
                    self._terminate(req, "expired",
                                    error="deadline expired before any "
                                          "worker answered")
                    metrics.counter("serve_pool.expired").inc()
                    return
            if (hedge_at is not None and now >= hedge_at
                    and req.attempts < self.config.max_attempts):
                hedge_at = None  # hedge at most once per request
                if launch(True):
                    with self._lock:
                        self.hedged += 1
                    checkpoint("pool.hedge", kind=req.kind, req=req.req_id)
                    metrics.counter("serve_pool.hedges").inc()
                continue
            waits = [0.25]  # heartbeat: re-evaluate even with no event
            if hedge_at is not None:
                waits.append(max(0.001, hedge_at - now))
            if rem is not None:
                waits.append(max(0.001, rem + _LATE_GRACE_S))
            state["done"].wait(timeout=min(waits))

    def _settle(self, req: PoolRequest, failures: list) -> None:
        """Close the books on a request no attempt could serve."""
        from csmom_tpu.obs import metrics

        now = mono_now_s()
        if req.deadline_s is not None and now > req.deadline_s:
            self._terminate(req, "expired",
                            error="deadline expired with every dispatch "
                                  "attempt failed")
            metrics.counter("serve_pool.expired").inc()
            return
        reason = "; ".join(failures[-3:]) or "no worker answered"
        # infra iff the pool itself failed (dead sockets, crashed
        # workers); an honest worker-level rejection (backpressure,
        # draining) settling here is the pool's honest answer
        infra = (all("connection failed" in f for f in failures)
                 if failures else True)
        self._terminate(req, "rejected", infra=infra,
                        error=f"all {req.attempts} attempt(s) failed: "
                              f"{reason}"[:300])
        metrics.counter("serve_pool.rejected_infra" if infra
                        else "serve_pool.rejected").inc()

    def _attempt(self, req: PoolRequest, worker, values, mask,
                 is_hedge: bool, state: dict, failures: list) -> None:
        """One dispatch attempt against one worker (its own socket)."""
        from csmom_tpu.obs import metrics, span

        now = mono_now_s()
        rem = req.remaining_s(now)
        # a deadline-less request must outwait the WORKER's own terminal
        # wait (_NO_DEADLINE_WAIT_S in worker.py) — a shorter socket
        # timeout here would misread slow-but-successful work as an
        # infra failure and throw the result away
        wait_budget = rem if rem is not None else _NO_DEADLINE_ATTEMPT_S
        timeout = (self.config.connect_timeout_s + wait_budget
                   + _TERMINAL_GRACE_S)
        header = {"op": "score", "kind": req.kind,
                  "req_id": req.req_id, "priority": req.priority,
                  "deadline_rel_s": rem,
                  "panel_version": req.panel_version}
        wire_trace = (req.trace.to_wire() if req.trace is not None
                      else None)
        if wire_trace is not None:
            # the trace context crosses the process boundary in the
            # frame header (identity only, never timestamps): the worker
            # answers with its half, and the two stitch here
            header["trace"] = wire_trace
        t_attempt0 = mono_now_s()
        try:
            with span("pool.attempt", phase="row", kind=req.kind,
                      worker=worker.worker_id, hedge=is_hedge):
                obj, arrays = proto.request(
                    worker.socket_path, header,
                    arrays={"values": values, "mask": mask},
                    timeout_s=timeout)
        except (OSError, proto.ProtocolError) as e:
            with self._lock:
                self.worker_conn_failures += 1
            metrics.counter("serve_pool.worker_conn_failures").inc()
            reason = (f"connection failed "
                      f"({type(e).__name__}: {e})")[:160]
            if req.trace is not None:
                # a dispatch that will never report back: the worker died
                # (the rehearsed SIGKILL) or reset — its half is an
                # ORPHAN, closed here with the reason instead of leaking
                req.trace.note_orphan(worker.worker_id, reason)
            failures.append(f"{worker.worker_id}: {reason}")
            self._conclude_attempt(state)
            return
        t_attempt1 = mono_now_s()
        resp_state = obj.get("state")
        if resp_state == "served":
            result = (obj.get("result_obj") if "result_obj" in obj
                      else arrays.get("result"))
            if result is not None and not isinstance(result, dict):
                result = np.asarray(result)[:req.n_assets]
            won = self._terminate(req, "served", result=result,
                                  worker_id=obj.get("worker_id"),
                                  hedge_win=is_hedge,
                                  trace_half=obj.get("trace_half"),
                                  attempt_window=(t_attempt0, t_attempt1,
                                                  worker.worker_id))
            if won:
                metrics.counter("serve_pool.served").inc()
            self._conclude_attempt(state)
            return
        # a worker-level rejection/expiry is a failed attempt, not (yet)
        # the request's fate — another worker may still serve it
        failures.append(
            f"{worker.worker_id}: {resp_state}: {obj.get('error')}"[:160])
        self._conclude_attempt(state)

    @staticmethod
    def _conclude_attempt(state: dict) -> None:
        with state["lock"]:
            state["in_flight"] -= 1
            state["concluded"] += 1
        state["done"].set()

    # ------------------------------------------------------------ terminal

    def _terminate(self, req: PoolRequest, state: str, result=None,
                   error: str | None = None, worker_id: str | None = None,
                   infra: bool = False, unserveable: bool = False,
                   hedge_win: bool = False, trace_half: dict | None = None,
                   attempt_window: tuple | None = None) -> bool:
        """Exactly-once terminal transition; returns True iff this call
        won.  A losing ``served`` (the hedge pair both answered) counts
        ``duplicates_suppressed`` — the duplicate is EXPECTED under
        hedging; silently double-counting it would break the books."""
        with self._lock:
            if req.state in TERMINAL_STATES:
                if state == "served":
                    if req.hedged:
                        # the expected loser of a hedge pair
                        self.duplicates_suppressed += 1
                    else:
                        # an UNhedged late answer (e.g. a worker replying
                        # after the router expired the request): also
                        # suppressed, but counted apart — the
                        # duplicates_suppressed <= hedged invariant is
                        # about hedge arithmetic, and a slow worker must
                        # not read as "exactly-once broke"
                        self.late_served_suppressed += 1
                return False
            req.state = state
            req.result = result
            if error is not None:
                req.error = error
            req.worker_id = worker_id
            req.t_done_s = mono_now_s()
            if state == "served":
                self.served += 1
                if hedge_win:
                    self.hedge_wins += 1
            elif state == "expired":
                self.expired += 1
            else:
                self.rejected += 1
                if infra:
                    self.rejected_infra += 1
                if unserveable:
                    self.rejected_unserveable += 1
            if req.priority in self.by_class:
                self.by_class[req.priority][state] += 1
            if req.trace is not None:
                # stitch + close inside the same exactly-once guard as
                # the request: only the WINNING attempt's half and window
                # reach the absorbed chain — a hedge loser's half can
                # never corrupt the telescoping sum
                if trace_half is not None and attempt_window is not None:
                    t0a, t1a, wid = attempt_window
                    req.trace.absorb_remote(trace_half, t0a, t1a,
                                            worker_id=wid)
                req.trace.close_routed(state, req.t_done_s,
                                       reason=error)
            req._done.set()
        return True

    # ---------------------------------------------------------- accounting

    def accounting(self) -> dict:
        with self._lock:
            return {
                "admitted": self.admitted,
                "served": self.served,
                "rejected": self.rejected,
                "expired": self.expired,
                "rejected_infra": self.rejected_infra,
                "rejected_unserveable": self.rejected_unserveable,
                "hedged": self.hedged,
                "hedge_wins": self.hedge_wins,
                "duplicates_suppressed": self.duplicates_suppressed,
                "late_served_suppressed": self.late_served_suppressed,
                "retries": self.retries,
                "worker_conn_failures": self.worker_conn_failures,
            }

    def class_accounting(self) -> dict:
        """Per-SLO-class books (closed like the global one)."""
        with self._lock:
            return {name: dict(book)
                    for name, book in self.by_class.items()}

    def availability(self) -> float:
        """``1 - rejected_infra / admitted``: the fraction of admitted
        requests that got an HONEST answer (served, backpressure-
        rejected, or client-deadline-expired).  Only infra failures —
        the pool failing its own job — count against it."""
        a = self.accounting()
        if not a["admitted"]:
            return 1.0
        return round(1.0 - a["rejected_infra"] / a["admitted"], 6)

    def invariant_violations(self) -> list:
        """Closed books across the process boundary (empty = holds)."""
        a = self.accounting()
        out = []
        total = a["served"] + a["rejected"] + a["expired"]
        if total != a["admitted"]:
            out.append(
                f"pool accounting broken: served {a['served']} + rejected "
                f"{a['rejected']} + expired {a['expired']} = {total} != "
                f"admitted {a['admitted']}")
        if a["hedge_wins"] > a["hedged"]:
            out.append(f"hedge_wins {a['hedge_wins']} > hedged "
                       f"{a['hedged']}")
        if a["duplicates_suppressed"] > a["hedged"]:
            out.append(
                f"duplicates_suppressed {a['duplicates_suppressed']} > "
                f"hedged {a['hedged']} — a duplicate without a hedge "
                "means a terminal state fired twice")
        if a["rejected_infra"] + a["rejected_unserveable"] > a["rejected"]:
            out.append("rejection sub-counters exceed rejected")
        return out


_TERMINAL_GRACE_S = 5.0
# deadline grace while a dispatch is still in flight: the worker's work
# is already spent, so a response landing a beat late still counts
_LATE_GRACE_S = 1.0
# attempt wait for deadline-less requests — matches the worker's
# _NO_DEADLINE_WAIT_S so the two sides give up together
_NO_DEADLINE_ATTEMPT_S = 30.0
