"""Pool router: admission, hedged dispatch, closed cross-process books.

The router is the pool's front door.  It admits every request, fans out
to whichever workers are READY (the supervisor's routable set), and
enforces the serve layer's core invariant ACROSS the process boundary:
every admitted request reaches exactly one terminal state — ``served`` /
``rejected`` / ``expired`` — no matter which worker died, answered late,
or answered twice.

Since the r18 fabric round the router is also its own SUPERVISED
PROCESS: ``python -m csmom_tpu.serve.router --listen ADDR --routes
FILE`` runs a :class:`RouterServer` replica speaking the same framed
protocol as the workers (``serve/proto.py``, unix or tcp), reading its
routable worker set from the shared routes file the fabric publishes
(:mod:`csmom_tpu.serve.fabric`).  Two or more replicas sit behind a
:class:`~csmom_tpu.serve.fabric.FabricClient` — a replica SIGKILLed
mid-burst costs its in-flight requests one client-side failover to a
surviving replica, never a lost request.

**Consistent-hash cache routing** (:class:`HashRing`): a request that
carries a result-cache identity (endpoint + panel content fingerprint +
panel version — the same key :mod:`csmom_tpu.serve.cache` uses) is
routed to the worker its key hashes to, so byte-identical requests land
on the SAME worker and the per-worker result cache compounds into a
pool-level cache.  The ring is rebuilt from whatever workers are
currently ready: a dead worker's arc redistributes, its replacement
reclaims it (stale hits stay structurally impossible — the version
floor lives in the worker's cache, not in the routing).  Hedges and
failovers exclude the tried worker, so affinity degrades to the
next-best worker instead of stalling.

**Weighted fair dispatch** (:class:`WeightedFairGate`): a bounded
number of dispatches run concurrently, and when the gate is contended
the next slot goes to the waiting SLO class with the lowest rank
(interactive before standard before bulk), weighted-fair within a rank
by queue share — so class rank is enforced BEFORE a request ever
reaches a worker's own queue, not only inside it.

**Hedged retries** (Dean & Barroso, *The Tail at Scale*, CACM 2013):
a request is dispatched to one worker; when a fraction of its deadline
budget elapses with no response, a second attempt fires against a
DIFFERENT worker.  First response wins; the loser's answer is counted
``duplicates_suppressed`` and discarded — the terminal transition is
guarded by one lock, so "exactly once" is structural, not statistical.
Hedging converts a straggling or dying worker from a p99 cliff into one
extra dispatch; the ``hedge_rate`` the artifact records keeps the cost
honest.

**Failover** is the same machinery driven by errors instead of time: a
connection refused/reset (worker crashed, socket gone) fails the attempt
immediately and redispatches to the next worker, up to ``max_attempts``.
Only when every avenue is exhausted does the request terminate
``rejected`` with ``rejected_infra`` incremented — the counter
availability is computed from (``1 - rejected_infra / admitted``):
backpressure and client-deadline expiry are honest answers, infra
failure is the pool failing its job.

The router holds no panels and no queue of its own — worker admission
queues are the buffering layer (each worker owns its backpressure,
Orca-style); the router's state per request is one small record.  All
timing through ``utils.deadline.mono_now_s``.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading

import numpy as np

from csmom_tpu.serve import proto
from csmom_tpu.registry import serve_endpoints
from csmom_tpu.serve.buckets import bucket_spec
from csmom_tpu.serve.slo import default_policy
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["HashRing", "PoolRequest", "Router", "RouterConfig",
           "RouterServer", "WeightedFairGate", "main"]

TERMINAL_STATES = ("served", "rejected", "expired")

_IDS = itertools.count(1)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Dispatch policy knobs (defaults tuned for the CPU pool)."""

    profile: str = "serve"
    default_deadline_s: float | None = 0.5
    hedge_fraction: float = 0.35   # of the remaining deadline budget
    hedge_floor_s: float = 0.05    # never hedge sooner than this
    hedge_after_s: float = 0.25    # hedge delay for deadline-less requests
    max_attempts: int = 3          # primary + hedge + one failover
    connect_timeout_s: float = 2.0
    # weighted fair dispatch: how many dispatches may run concurrently
    # through this router before waiters queue at the gate in SLO rank
    # order (0 disables the gate — r11/r17 behavior)
    fair_slots: int = 16
    # consistent-hash routing on the result-cache identity: identical
    # requests land on the same worker, lifting the per-worker result
    # cache to pool-level hit rates (False = pure round robin)
    affinity: bool = True


@dataclasses.dataclass
class PoolRequest:
    """One pool request's life-cycle record (router-side)."""

    kind: str
    n_assets: int
    priority: str = "interactive"
    deadline_s: float | None = None      # ABSOLUTE monotonic, None = none
    panel_version: int | None = None     # live-panel snapshot version
    req_id: int = dataclasses.field(default_factory=lambda: next(_IDS))
    state: str = "routing"
    result: object = None
    error: str | None = None
    worker_id: str | None = None         # who served it
    hedged: bool = False
    attempts: int = 0
    cache_hit: bool = False              # served from the worker's cache
    affinity: str | None = None          # consistent-hash routing key
    retry_after_s: float | None = None   # backoff hint on a parked fleet
    # True iff a rejection was the POOL's failure (dead sockets, parked
    # fleet), not an honest answer — carried on the wire so the client
    # tier's availability counts it instead of substring-matching text
    infra: bool = False
    t_submit_s: float = 0.0
    t_done_s: float | None = None
    # the request's trace context (obs.trace; None = untraced).  The
    # router owns the CLIENT half: route/transport/finalize stages plus
    # whatever worker half the winning attempt brought home.
    trace: object = dataclasses.field(default=None, repr=False,
                                      compare=False)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    @property
    def total_s(self) -> float | None:
        return (None if self.t_done_s is None
                else max(0.0, self.t_done_s - self.t_submit_s))

    def remaining_s(self, now_s: float) -> float | None:
        return (None if self.deadline_s is None
                else self.deadline_s - now_s)


class HashRing:
    """Consistent-hash ring with virtual nodes (blake2b, seed-free).

    Each member id is hashed onto the ring ``vnodes`` times; a key maps
    to the first vnode clockwise of its hash.  Removing one member moves
    only that member's arcs (about ``1/n`` of the keyspace) — the cache
    property the fabric needs: a worker death reshuffles the minimum,
    and its same-id replacement reclaims exactly its old arcs.
    """

    def __init__(self, ids, vnodes: int = 64):
        import bisect
        import hashlib

        self._bisect = bisect
        points = []
        for wid in ids:
            for v in range(vnodes):
                h = hashlib.blake2b(f"{wid}#{v}".encode(),
                                    digest_size=8).digest()
                points.append((int.from_bytes(h, "big"), str(wid)))
        points.sort()
        self._hashes = [p[0] for p in points]
        self._ids = [p[1] for p in points]

    def pick(self, key: str) -> str | None:
        """The member ``key`` hashes to (None on an empty ring)."""
        if not self._hashes:
            return None
        import hashlib

        h = int.from_bytes(
            hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")
        i = self._bisect.bisect_right(self._hashes, h) % len(self._hashes)
        return self._ids[i]


class WeightedFairGate:
    """Bounded concurrent dispatch with SLO-rank priority at the gate.

    ``slots`` dispatches may run concurrently.  When the gate is
    contended, the next free slot goes to the waiting class with the
    LOWEST rank (interactive first — class rank enforced before the
    worker, not just inside it); among classes of equal rank the slot
    rotates weighted-fair by queue share (each class's granted count is
    normalized by its weight, smallest normalized count wins).  Waiters
    time out against their own deadline budget and are rejected as
    honest backpressure, never silently dropped.

    One leaf lock + condition; the wait is ``Condition.wait`` (exempt
    from the blocking-under-lock audit by design — it RELEASES the lock).
    """

    def __init__(self, policy, slots: int):
        self.slots = int(slots)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._in_use = 0
        self._rank = {}
        self._weight = {}
        for c in policy.classes:
            self._rank[c.name] = c.rank
            self._weight[c.name] = max(0.05, min(1.0, c.queue_share))
        self._waiting = {name: [] for name in self._rank}
        self.granted = {name: 0 for name in self._rank}
        self.timeouts = {name: 0 for name in self._rank}

    def _grant_next_locked(self) -> None:
        """Hand free slots to waiters, best class first."""
        granted_any = False
        while self._in_use < self.slots:
            best = None
            for name, q in self._waiting.items():
                if not q:
                    continue
                score = (self._rank[name],
                         self.granted[name] / self._weight[name])
                if best is None or score < best[0]:
                    best = (score, name)
            if best is None:
                break
            ticket = self._waiting[best[1]].pop(0)
            ticket["granted"] = True
            self._in_use += 1
            self.granted[best[1]] += 1
            granted_any = True
        if granted_any:
            self._cond.notify_all()

    def acquire(self, cls_name: str, timeout_s: float) -> bool:
        """One dispatch slot for ``cls_name`` (False = timed out)."""
        name = cls_name if cls_name in self._rank else \
            min(self._rank, key=lambda n: -self._rank[n])
        give_up = mono_now_s() + max(0.0, timeout_s)
        with self._cond:
            ticket = {"granted": False}
            self._waiting[name].append(ticket)
            self._grant_next_locked()
            while not ticket["granted"]:
                remaining = give_up - mono_now_s()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            if ticket["granted"]:
                return True
            # timed out: withdraw the ticket.  No grant can race this —
            # _grant_next_locked only runs under the same lock we hold
            # continuously from the wait's return through the remove.
            self._waiting[name].remove(ticket)
            self.timeouts[name] += 1
            return False

    def release(self) -> None:
        with self._cond:
            self._in_use -= 1
            self._grant_next_locked()

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self.slots,
                "in_use": self._in_use,
                "granted": dict(self.granted),
                "timeouts": dict(self.timeouts),
            }


class Router:
    """Admit → fair gate → dispatch (hedged, cache-affine) →
    exactly-once terminal accounting."""

    def __init__(self, workers_fn, config: RouterConfig | None = None,
                 retry_after_fn=None):
        """``workers_fn() -> list`` of objects with ``.worker_id`` and
        ``.socket_path`` — the supervisor's current READY set (queried
        per attempt, so a worker that died between attempts is already
        gone from the menu).  ``retry_after_fn() -> float | None`` is
        the supervisor's backoff view: when NO worker is ready, door
        rejections carry this as a retry-after hint instead of burning
        the caller's deadline."""
        self.config = config or RouterConfig()
        self.spec = bucket_spec(self.config.profile)
        self.policy = default_policy()
        self._workers_fn = workers_fn
        self._retry_after_fn = retry_after_fn
        self._fair = (WeightedFairGate(self.policy, self.config.fair_slots)
                      if self.config.fair_slots > 0 else None)
        self._ring_cache: tuple = (None, None)   # (ids tuple, HashRing)
        self._lock = threading.Lock()
        self._rr = itertools.count()
        # the persistent multiplexed transport (ISSUE 15): one bounded
        # channel pool to the worker tier — dispatches interleave on
        # long-lived TCP_NODELAY channels instead of paying a fresh
        # connect + full header encode per attempt.  Probes and admin
        # ops stay on request_once (the dial-discipline split).
        self.channels = proto.ChannelPool(
            connect_timeout_s=self.config.connect_timeout_s)
        # shared score-header renderer (proto.ScoreHeaderCache): the
        # same implementation the fabric client uses, so the two
        # tiers' wire headers cannot drift apart
        self._headers = proto.ScoreHeaderCache()
        # per-SLO-class books (closed like the global one); the policy
        # resolves legacy names ("batch" -> "bulk") so the wire protocol
        # and the in-process service count the same classes
        self.by_class = {name: {"admitted": 0, "served": 0, "rejected": 0,
                                "expired": 0}
                         for name in self.policy.names()}
        # accounting counters — the cross-process closed book
        self.admitted = 0
        self.served = 0
        self.rejected = 0
        self.expired = 0
        self.rejected_infra = 0
        self.rejected_unserveable = 0
        self.rejected_saturated = 0   # fair-gate timeouts (backpressure)
        self.rejected_no_worker = 0   # parked fleet, retry-after issued
        self.served_cache_hits = 0    # worker answered from its cache
        self.affinity_routed = 0      # picks the hash ring decided
        self.hedged = 0
        self.hedge_wins = 0
        self.duplicates_suppressed = 0
        self.late_served_suppressed = 0
        self.retries = 0
        self.worker_conn_failures = 0

    # --------------------------------------------------------------- admit

    def retry_after_hint_s(self) -> float | None:
        """The supervisor's backoff view, rounded for the wire (None
        when no hint is available)."""
        if self._retry_after_fn is None:
            return None
        try:
            hint = self._retry_after_fn()
        except Exception:
            return None
        return None if hint is None else round(max(0.05, float(hint)), 3)

    def submit(self, kind: str, values, mask, priority: str = "interactive",
               deadline_s: float | None = None,
               panel_version: int | None = None,
               trace_ctx=None) -> PoolRequest:
        """Admit one request; returns its handle (terminal on door
        rejection).  ``deadline_s`` is RELATIVE seconds (None = config
        default).  ``trace_ctx`` carries a wire-propagated trace context
        (the router-replica path); without one, a context is minted iff
        this process's trace book is armed."""
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import fleet as obs_fleet
        from csmom_tpu.obs import metrics
        from csmom_tpu.obs import trace as obs_trace

        values = np.asarray(values)
        mask = np.asarray(mask, dtype=bool)
        n_assets = int(values.shape[0]) if values.ndim == 2 else 0
        rel = (self.config.default_deadline_s if deadline_s is None
               else deadline_s)
        now = mono_now_s()
        try:
            priority = self.policy.resolve_name(priority)
        except ValueError:
            pass  # the worker's own door rejects unknown classes
        budget_ms = None
        try:
            budget_ms = round(1e3 * self.policy.resolve(priority).deadline_s,
                              3)
        except ValueError:
            pass
        req = PoolRequest(
            kind=kind, n_assets=n_assets, priority=priority,
            deadline_s=None if rel is None else now + rel, t_submit_s=now,
            panel_version=panel_version,
            trace=trace_ctx if trace_ctx is not None else obs_trace.begin(
                kind, priority, panel_version=panel_version,
                budget_ms=budget_ms))
        with self._lock:
            self.admitted += 1
            if priority in self.by_class:
                self.by_class[priority]["admitted"] += 1
        # fleet demand telemetry (no-op disarmed): at this tier every
        # offered request is admitted — the class books reconcile with
        # these counts BY SCHEMA in the FLEET artifact
        obs_fleet.demand("offered", priority)
        obs_fleet.demand("admitted", priority)
        checkpoint("pool.route", kind=kind, req=req.req_id)
        reason = self._unserveable_reason(kind, values, mask)
        if reason is not None:
            self._terminate(req, "rejected", error=reason, unserveable=True)
            metrics.counter("serve_pool.rejected_unserveable").inc()
            return req
        if not self._workers_fn():
            # EVERY worker parked/unreachable: reject AT THE DOOR with a
            # retry-after hint derived from the supervisor's backoff
            # state — burning the caller's full deadline per request on
            # a fleet that cannot answer would amplify the outage
            hint = self.retry_after_hint_s()
            req.retry_after_s = hint
            with self._lock:
                self.rejected_no_worker += 1
            self._terminate(
                req, "rejected", infra=True,
                error="no ready worker in the pool (all crashed, parked, "
                      "or draining)"
                      + (f"; retry after {hint}s" if hint is not None
                         else ""))
            metrics.counter("serve_pool.rejected_infra").inc()
            return req
        if self.config.affinity:
            # the result-cache identity (the serve/cache.py key minus the
            # pool-constant params): byte-identical requests share it, so
            # the hash ring lands them on the same worker's cache
            from csmom_tpu.serve.cache import panel_fingerprint

            req.affinity = (f"{kind}|{n_assets}|"
                            f"{panel_fingerprint(values, mask)}|"
                            f"{panel_version}")
        t = threading.Thread(
            target=self._drive, args=(req, values, mask),
            name=f"csmom-pool-req-{req.req_id}", daemon=True)
        t.start()
        return req

    def _unserveable_reason(self, kind: str, values, mask) -> str | None:
        # same door checks as service.submit: an unserveable request must
        # fail here, not burn dispatch attempts on every worker in turn
        kinds = serve_endpoints()
        if kind not in kinds:
            return f"unknown endpoint {kind!r} (serveable: {kinds})"
        if values.ndim != 2:
            return f"panel must be [assets, months], got ndim={values.ndim}"
        if values.shape[1] != self.spec.months:
            return (f"panel has {values.shape[1]} months; this pool scores "
                    f"{self.spec.months}-month histories")
        if self.spec.asset_bucket_for(values.shape[0]) is None:
            return (f"{values.shape[0]} assets exceeds the largest bucket "
                    f"({self.spec.max_assets})")
        if mask.shape != values.shape:
            return (f"mask shape {mask.shape} does not match the values "
                    f"panel {values.shape}")
        return None

    # ------------------------------------------------------------ dispatch

    def _ring_for(self, ids: tuple) -> HashRing:
        cached_ids, ring = self._ring_cache
        if cached_ids != ids:
            ring = HashRing(ids)
            self._ring_cache = (ids, ring)
        return ring

    def _pick_worker(self, exclude: set, affinity: str | None = None):
        workers = [w for w in self._workers_fn()
                   if w.worker_id not in exclude]
        if not workers:
            return None
        if affinity is not None and len(workers) > 1:
            # the ring is built over the CURRENT candidates, so a dead
            # worker's arcs redistribute and a hedge (its target already
            # in `exclude`) degrades to the next-best worker
            ids = tuple(sorted(w.worker_id for w in workers))
            wid = self._ring_for(ids).pick(affinity)
            for w in workers:
                if w.worker_id == wid:
                    with self._lock:
                        self.affinity_routed += 1
                    return w
        elif affinity is not None:
            with self._lock:
                self.affinity_routed += 1
            return workers[0]
        return workers[next(self._rr) % len(workers)]

    def _hedge_delay(self, req: PoolRequest, now: float) -> float:
        rem = req.remaining_s(now)
        if rem is None:
            return self.config.hedge_after_s
        return max(self.config.hedge_floor_s,
                   self.config.hedge_fraction * rem)

    def _drive(self, req: PoolRequest, values, mask) -> None:
        """Attempt loop: primary, hedge-on-delay, failover-on-error.

        Event-driven: the loop sleeps on the attempt-conclusion event
        with a timeout set to the next interesting instant (hedge timer,
        deadline), and on every wake acts on exactly one of: a terminal
        state (done), a concluded-but-failed attempt (failover or
        settle), the hedge timer (launch the hedge, at most once), or
        the deadline (expire — after a short grace when a dispatch is
        still in flight, since its work is already spent)."""
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics

        if self._fair is not None:
            # the weighted fair gate: class rank is enforced HERE, before
            # any worker sees the request.  The wait burns the request's
            # own budget; a timeout is honest backpressure.
            now0 = mono_now_s()
            rem0 = req.remaining_s(now0)
            gate_wait = rem0 if rem0 is not None else _NO_DEADLINE_ATTEMPT_S
            if not self._fair.acquire(req.priority, gate_wait):
                with self._lock:
                    self.rejected_saturated += 1
                self._terminate(
                    req, "rejected",
                    error="fair-dispatch gate saturated: the request's "
                          "budget elapsed before a dispatch slot freed "
                          f"(class {req.priority}); back off and retry")
                metrics.counter("serve_pool.rejected_saturated").inc()
                return
        try:
            self._drive_attempts(req, values, mask, checkpoint, metrics)
        finally:
            if self._fair is not None:
                self._fair.release()

    def _drive_attempts(self, req: PoolRequest, values, mask,
                        checkpoint, metrics) -> None:
        tried: set = set()
        failures: list = []
        state: dict = {"done": threading.Event(), "lock": threading.Lock(),
                       "in_flight": 0, "concluded": 0}

        def launch(is_hedge: bool) -> bool:
            worker = self._pick_worker(tried, affinity=req.affinity)
            if worker is None:
                return False
            tried.add(worker.worker_id)
            with self._lock:
                req.attempts += 1
                if is_hedge:
                    req.hedged = True
            with state["lock"]:
                state["in_flight"] += 1
            threading.Thread(
                target=self._attempt, args=(req, worker, values, mask,
                                            is_hedge, state, failures),
                daemon=True).start()
            return True

        if not launch(False):
            hint = self.retry_after_hint_s()
            req.retry_after_s = hint
            with self._lock:
                self.rejected_no_worker += 1
            self._terminate(req, "rejected", infra=True,
                            error="no ready worker in the pool (all "
                                  "crashed, draining, or never became "
                                  "ready)"
                                  + (f"; retry after {hint}s"
                                     if hint is not None else ""))
            metrics.counter("serve_pool.rejected_infra").inc()
            return
        hedge_at = mono_now_s() + self._hedge_delay(req, mono_now_s())
        acted = 0
        while True:
            if req.state in TERMINAL_STATES:
                return
            now = mono_now_s()
            rem = req.remaining_s(now)
            with state["lock"]:
                in_flight = state["in_flight"]
                concluded = state["concluded"]
            if concluded > acted:
                acted = concluded
                state["done"].clear()
                if in_flight == 0:
                    # every launched attempt failed: failover while the
                    # budget and the worker menu allow, else settle
                    if ((rem is None or rem > 0)
                            and req.attempts < self.config.max_attempts
                            and launch(False)):
                        with self._lock:
                            self.retries += 1
                        metrics.counter("serve_pool.retries").inc()
                        continue
                    self._settle(req, failures)
                    return
                continue  # a loser concluded; the other attempt lives on
            if rem is not None and rem <= 0:
                if in_flight == 0 or rem <= -_LATE_GRACE_S:
                    self._terminate(req, "expired",
                                    error="deadline expired before any "
                                          "worker answered")
                    metrics.counter("serve_pool.expired").inc()
                    return
            if (hedge_at is not None and now >= hedge_at
                    and req.attempts < self.config.max_attempts):
                hedge_at = None  # hedge at most once per request
                if launch(True):
                    with self._lock:
                        self.hedged += 1
                    checkpoint("pool.hedge", kind=req.kind, req=req.req_id)
                    metrics.counter("serve_pool.hedges").inc()
                continue
            waits = [0.25]  # heartbeat: re-evaluate even with no event
            if hedge_at is not None:
                waits.append(max(0.001, hedge_at - now))
            if rem is not None:
                waits.append(max(0.001, rem + _LATE_GRACE_S))
            state["done"].wait(timeout=min(waits))

    def _settle(self, req: PoolRequest, failures: list) -> None:
        """Close the books on a request no attempt could serve."""
        from csmom_tpu.obs import metrics

        now = mono_now_s()
        if req.deadline_s is not None and now > req.deadline_s:
            self._terminate(req, "expired",
                            error="deadline expired with every dispatch "
                                  "attempt failed")
            metrics.counter("serve_pool.expired").inc()
            return
        reason = "; ".join(failures[-3:]) or "no worker answered"
        # infra iff the pool itself failed (dead sockets, crashed
        # workers); an honest worker-level rejection (backpressure,
        # draining) settling here is the pool's honest answer
        infra = (all("connection failed" in f for f in failures)
                 if failures else True)
        self._terminate(req, "rejected", infra=infra,
                        error=f"all {req.attempts} attempt(s) failed: "
                              f"{reason}"[:300])
        metrics.counter("serve_pool.rejected_infra" if infra
                        else "serve_pool.rejected").inc()

    def _attempt(self, req: PoolRequest, worker, values, mask,
                 is_hedge: bool, state: dict, failures: list) -> None:
        """One dispatch attempt against one worker, over the pooled
        multiplexed channel to it (ISSUE 15) — no per-attempt dial."""
        from csmom_tpu.obs import metrics, span

        now = mono_now_s()
        rem = req.remaining_s(now)
        # a deadline-less request must outwait the WORKER's own terminal
        # wait (_NO_DEADLINE_WAIT_S in worker.py) — a shorter reply
        # timeout here would misread slow-but-successful work as an
        # infra failure and throw the result away
        wait_budget = rem if rem is not None else _NO_DEADLINE_ATTEMPT_S
        timeout = (self.config.connect_timeout_s + wait_budget
                   + _TERMINAL_GRACE_S)
        header = self._headers.render(req.kind, req.priority,
                                      req.panel_version, req.req_id,
                                      rem, trace_ctx=req.trace)
        t_attempt0 = mono_now_s()
        marks: dict = {}
        try:
            with span("pool.attempt", phase="row", kind=req.kind,
                      worker=worker.worker_id, hedge=is_hedge):
                obj, arrays = self.channels.request(
                    worker.socket_path, header,
                    arrays={"values": values, "mask": mask},
                    timeout_s=timeout, marks=marks)
        except (OSError, proto.ProtocolError) as e:
            with self._lock:
                self.worker_conn_failures += 1
            metrics.counter("serve_pool.worker_conn_failures").inc()
            reason = (f"connection failed "
                      f"({type(e).__name__}: {e})")[:160]
            if req.trace is not None:
                # a dispatch that will never report back: the worker died
                # (the rehearsed SIGKILL) or reset — its half is an
                # ORPHAN, closed here with the reason instead of leaking
                req.trace.note_orphan(worker.worker_id, reason)
            failures.append(f"{worker.worker_id}: {reason}")
            self._conclude_attempt(state)
            return
        t_attempt1 = mono_now_s()
        resp_state = obj.get("state")
        if resp_state == "served":
            result = (obj.get("result_obj") if "result_obj" in obj
                      else arrays.get("result"))
            if result is not None and not isinstance(result, dict):
                result = np.asarray(result)[:req.n_assets]
            won = self._terminate(req, "served", result=result,
                                  worker_id=obj.get("worker_id"),
                                  hedge_win=is_hedge,
                                  cache_hit=bool(obj.get("cache_hit")),
                                  trace_half=obj.get("trace_half"),
                                  attempt_window=(t_attempt0, t_attempt1,
                                                  worker.worker_id,
                                                  marks.get("t_acquired_s"),
                                                  marks.get("t_sent_s")))
            if won:
                metrics.counter("serve_pool.served").inc()
            self._conclude_attempt(state)
            return
        # a worker-level rejection/expiry is a failed attempt, not (yet)
        # the request's fate — another worker may still serve it
        failures.append(
            f"{worker.worker_id}: {resp_state}: {obj.get('error')}"[:160])
        self._conclude_attempt(state)

    @staticmethod
    def _conclude_attempt(state: dict) -> None:
        with state["lock"]:
            state["in_flight"] -= 1
            state["concluded"] += 1
        state["done"].set()

    # ------------------------------------------------------------ terminal

    def _terminate(self, req: PoolRequest, state: str, result=None,
                   error: str | None = None, worker_id: str | None = None,
                   infra: bool = False, unserveable: bool = False,
                   hedge_win: bool = False, cache_hit: bool = False,
                   trace_half: dict | None = None,
                   attempt_window: tuple | None = None) -> bool:
        """Exactly-once terminal transition; returns True iff this call
        won.  A losing ``served`` (the hedge pair both answered) counts
        ``duplicates_suppressed`` — the duplicate is EXPECTED under
        hedging; silently double-counting it would break the books."""
        with self._lock:
            if req.state in TERMINAL_STATES:
                if state == "served":
                    if req.hedged:
                        # the expected loser of a hedge pair
                        self.duplicates_suppressed += 1
                    else:
                        # an UNhedged late answer (e.g. a worker replying
                        # after the router expired the request): also
                        # suppressed, but counted apart — the
                        # duplicates_suppressed <= hedged invariant is
                        # about hedge arithmetic, and a slow worker must
                        # not read as "exactly-once broke"
                        self.late_served_suppressed += 1
                return False
            req.state = state
            req.result = result
            if error is not None:
                req.error = error
            req.worker_id = worker_id
            req.t_done_s = mono_now_s()
            if state == "served":
                self.served += 1
                if hedge_win:
                    self.hedge_wins += 1
                if cache_hit:
                    req.cache_hit = True
                    self.served_cache_hits += 1
            elif state == "expired":
                self.expired += 1
            else:
                self.rejected += 1
                req.infra = infra
                if infra:
                    self.rejected_infra += 1
                if unserveable:
                    self.rejected_unserveable += 1
            if req.priority in self.by_class:
                self.by_class[req.priority][state] += 1
            if req.trace is not None:
                # stitch + close inside the same exactly-once guard as
                # the request: only the WINNING attempt's half and window
                # reach the absorbed chain — a hedge loser's half can
                # never corrupt the telescoping sum
                if trace_half is not None and attempt_window is not None:
                    t0a, t1a, wid = attempt_window[:3]
                    acq, sent = (attempt_window[3:5]
                                 if len(attempt_window) >= 5
                                 else (None, None))
                    req.trace.absorb_remote(trace_half, t0a, t1a,
                                            worker_id=wid,
                                            t_acquired_s=acq,
                                            t_sent_s=sent)
                req.trace.close_routed(state, req.t_done_s,
                                       reason=error)
            req._done.set()
        if state == "served":
            from csmom_tpu.obs import fleet as obs_fleet

            obs_fleet.demand("served", req.priority)
        return True

    # ---------------------------------------------------------- accounting

    def accounting(self) -> dict:
        with self._lock:
            return {
                "admitted": self.admitted,
                "served": self.served,
                "rejected": self.rejected,
                "expired": self.expired,
                "rejected_infra": self.rejected_infra,
                "rejected_unserveable": self.rejected_unserveable,
                "rejected_saturated": self.rejected_saturated,
                "rejected_no_worker": self.rejected_no_worker,
                "served_cache_hits": self.served_cache_hits,
                "affinity_routed": self.affinity_routed,
                "hedged": self.hedged,
                "hedge_wins": self.hedge_wins,
                "duplicates_suppressed": self.duplicates_suppressed,
                "late_served_suppressed": self.late_served_suppressed,
                "retries": self.retries,
                "worker_conn_failures": self.worker_conn_failures,
            }

    def class_accounting(self) -> dict:
        """Per-SLO-class books (closed like the global one)."""
        with self._lock:
            return {name: dict(book)
                    for name, book in self.by_class.items()}

    def availability(self) -> float:
        """``1 - rejected_infra / admitted``: the fraction of admitted
        requests that got an HONEST answer (served, backpressure-
        rejected, or client-deadline-expired).  Only infra failures —
        the pool failing its own job — count against it."""
        a = self.accounting()
        if not a["admitted"]:
            return 1.0
        return round(1.0 - a["rejected_infra"] / a["admitted"], 6)

    def invariant_violations(self) -> list:
        """Closed books across the process boundary (empty = holds)."""
        a = self.accounting()
        out = []
        if a["served_cache_hits"] > a["served"]:
            out.append(f"served_cache_hits {a['served_cache_hits']} > "
                       f"served {a['served']}")
        total = a["served"] + a["rejected"] + a["expired"]
        if total != a["admitted"]:
            out.append(
                f"pool accounting broken: served {a['served']} + rejected "
                f"{a['rejected']} + expired {a['expired']} = {total} != "
                f"admitted {a['admitted']}")
        if a["hedge_wins"] > a["hedged"]:
            out.append(f"hedge_wins {a['hedge_wins']} > hedged "
                       f"{a['hedged']}")
        if a["duplicates_suppressed"] > a["hedged"]:
            out.append(
                f"duplicates_suppressed {a['duplicates_suppressed']} > "
                f"hedged {a['hedged']} — a duplicate without a hedge "
                "means a terminal state fired twice")
        if a["rejected_infra"] + a["rejected_unserveable"] > a["rejected"]:
            out.append("rejection sub-counters exceed rejected")
        return out


_TERMINAL_GRACE_S = 5.0
# deadline grace while a dispatch is still in flight: the worker's work
# is already spent, so a response landing a beat late still counts
_LATE_GRACE_S = 1.0
# attempt wait for deadline-less requests — matches the worker's
# _NO_DEADLINE_WAIT_S so the two sides give up together
_NO_DEADLINE_ATTEMPT_S = 30.0


def no_deadline_score_give_up_s(connect_timeout_s: float) -> float:
    """How long :meth:`RouterServer._score` waits for a DEADLINE-LESS
    request to reach terminal: a full fair-gate wait plus one full
    dispatch attempt (connect + worker wait + grace) plus its own
    grace.  The CLIENT tier's per-attempt receive budget is derived
    FROM this function (fabric.py) so the chain keeps giving up
    outermost-last — a hand-rolled copy on either side silently breaks
    it."""
    return (_NO_DEADLINE_ATTEMPT_S          # fair-gate wait
            + connect_timeout_s
            + _NO_DEADLINE_ATTEMPT_S        # worker-side terminal wait
            + 2 * _TERMINAL_GRACE_S)


# ------------------------------------------------------------ the replica ---

class RouterServer:
    """One supervised router-replica process: a :class:`Router` behind
    the pool wire protocol (unix or tcp), its worker set read from the
    fabric's shared routes file.

    The replica is STATELESS beyond its own books: it holds no panels
    and no queue, so a replica SIGKILLed mid-burst loses only the
    requests currently transiting it — which the fabric client fails
    over to a surviving replica.  Lifecycle ops mirror the worker's
    (``ping`` / ``ready`` / ``score`` / ``stats`` / ``drain`` /
    ``stop``), so the SAME supervisor machinery (spawn, probe, backoff,
    crash-loop parking, rolling restart) babysits both tiers.

    Tracing: a ``score`` frame carrying a ``trace`` entry gets its
    context rebuilt here, opened into this process's armed book (the
    replica-tier trace ledger), threaded through the router's hedged
    dispatch (the worker's half stitches in), and the CLOSED context's
    stage chain rides back in the reply's ``trace_half`` — the client
    tier stitches the full three-tier chain from it.
    """

    def __init__(self, listen_addr: str, routes_path: str,
                 router_id: str = "r0",
                 config: RouterConfig | None = None,
                 expect_cache_version: str | None = None):
        from csmom_tpu.serve.fabric import RoutesView

        self.listen_addr = listen_addr
        self.router_id = router_id
        # the WORKER tier's AOT cache version, echoed in stats for fleet
        # bookkeeping (replicas hold no compiled world of their own)
        self.expect_cache_version = expect_cache_version
        self.routes = RoutesView(routes_path)
        self.router = Router(self.routes.workers, config,
                             retry_after_fn=self.routes.retry_after_s)
        self._draining = False
        self._stop = threading.Event()
        self._listener = None

    # ----------------------------------------------------------- lifecycle

    def bind(self) -> None:
        from csmom_tpu.serve import proto

        self._listener = proto.listen(self.listen_addr)
        self._listener.settimeout(0.2)
        t = threading.Thread(target=self._accept_loop,
                             name=f"csmom-router-{self.router_id}-accept",
                             daemon=True)
        t.start()

    def run_until_stopped(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(0.2)
        self._shutdown()

    def _shutdown(self) -> None:
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        proto.unlink_address(self.listen_addr)

    def stop(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        import socket as _socket

        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except _socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: shutting down
            # one PERSISTENT connection per fabric-client channel: the
            # serve loop demuxes interleaved score frames off it (each
            # scored on its own thread through the router's hedged
            # dispatch) while probes keep their one-shot shape
            t = threading.Thread(
                target=proto.serve_connection,
                args=(conn, self._handle),
                kwargs={"on_stop": self.stop},
                daemon=True)
            t.start()

    def _handle(self, obj: dict, arrays: dict) -> tuple:
        op = obj.get("op")
        if op == "ping":
            return {"ok": True, "worker_id": self.router_id,
                    "router_id": self.router_id, "pid": os.getpid()}, None
        if op == "ready":
            ok, reason = self.routes.status()
            if self._draining:
                ok, reason = False, "draining"
            return {"ok": ok, "reason": None if ok else reason,
                    "worker_id": self.router_id,
                    "router_id": self.router_id,
                    "pid": os.getpid(),
                    "tier": "router",
                    "workers": len(self.routes.workers()),
                    "fresh_compiles": 0}, None
        if op == "stats":
            return self._stats(), None
        if op == "score":
            return self._score(obj, arrays)
        if op in ("drain", "stop"):
            self._draining = True
            out = self._stats()
            out["drained"] = True
            return out, None
        return {"ok": False, "error": f"unknown op {op!r}"}, None

    def _stats(self) -> dict:
        from csmom_tpu.obs import trace as obs_trace

        out = {
            "ok": True,
            "worker_id": self.router_id,
            "router_id": self.router_id,
            "tier": "router",
            "pid": os.getpid(),
            "accounting": self.router.accounting(),
            "classes": self.router.class_accounting(),
            "availability": self.router.availability(),
            "invariant_violations": self.router.invariant_violations(),
            "fair_gate": (self.router._fair.stats()
                          if self.router._fair is not None else None),
            # the persistent transport's evidence: dials vs reuses on
            # the worker-tier channels (reuses >> dials is the point)
            "channels": self.router.channels.stats(),
            "retry_after_s": self.router.retry_after_hint_s(),
            "expect_cache_version": self.expect_cache_version,
        }
        book = obs_trace.current_book()
        if book is not None:
            out["trace"] = {
                "snapshot": book.snapshot(),
                "invariant_violations": book.invariant_violations(),
            }
        return out

    def _score(self, obj: dict, arrays: dict) -> tuple:
        from csmom_tpu.obs import trace as obs_trace

        if self._draining:
            return {"state": "rejected", "error": "router draining",
                    "router_id": self.router_id}, None
        if "values" not in arrays or "mask" not in arrays:
            return {"state": "rejected",
                    "error": "score frame missing values/mask arrays",
                    "router_id": self.router_id}, None
        rel = obj.get("deadline_rel_s")
        pv = obj.get("panel_version")
        trace_ctx = None
        wire_trace = obj.get("trace")
        if isinstance(wire_trace, dict):
            from csmom_tpu.obs.trace import TraceContext

            trace_ctx = TraceContext.from_wire(wire_trace)
            book = obs_trace.current_book()
            if book is not None:
                # the replica-tier ledger: this process's books must
                # close over every trace it transited, SIGKILL included
                book.open_trace(trace_ctx)
        req = self.router.submit(
            str(obj.get("kind")), arrays["values"], arrays["mask"],
            priority=str(obj.get("priority", "interactive")),
            deadline_s=float(rel) if rel is not None else None,
            panel_version=int(pv) if pv is not None else None,
            trace_ctx=trace_ctx,
        )
        # a deadline-bounded request terminates within its own budget;
        # a deadline-less one can spend a full fair-gate wait AND a full
        # dispatch attempt before terminal — the give-up must cover the
        # whole pipeline or a healthy slow request is falsely branded a
        # router defect while the worker later serves it (forked books)
        wait_s = (float(rel) + _TERMINAL_GRACE_S if rel is not None
                  else no_deadline_score_give_up_s(
                      self.router.config.connect_timeout_s))
        if not req.wait(wait_s):
            return {"state": "rejected",
                    "error": "request never reached a terminal state "
                             f"within {wait_s:.1f}s (router defect)",
                    "infra": True,
                    "router_id": self.router_id}, None
        reply = {
            "state": req.state,
            "error": req.error,
            "infra": req.infra,
            "router_id": self.router_id,
            "worker_id": req.worker_id,
            "cache_hit": req.cache_hit,
            "hedged": req.hedged,
            "attempts": req.attempts,
            "retry_after_s": req.retry_after_s,
            "panel_version": req.panel_version,
        }
        if trace_ctx is not None:
            # the replica's closed stage chain (its own route/transport
            # plus the worker's stitched half) for the CLIENT to stitch
            reply["trace_half"] = trace_ctx.half_record()
        out_arrays = None
        if req.state == "served":
            if isinstance(req.result, dict):
                reply["result_obj"] = {k: float(v)
                                       for k, v in req.result.items()}
            else:
                out_arrays = {"result": np.asarray(req.result)}
        return reply, out_arrays


def main(argv=None) -> int:
    """``python -m csmom_tpu.serve.router``: one supervised replica."""
    import argparse
    import signal
    import sys

    ap = argparse.ArgumentParser(
        prog="csmom_tpu.serve.router",
        description="router replica: hedged cache-affine dispatch behind "
                    "a unix/tcp socket, workers from a shared routes file")
    ap.add_argument("--listen", required=True,
                    help="address to serve on (unix:/path or tcp:host:port)")
    ap.add_argument("--routes", required=True,
                    help="path to the fabric's routes file (the shared "
                         "admission view: ready workers + backoff hints)")
    ap.add_argument("--router-id", dest="router_id", default="r0")
    ap.add_argument("--profile", default="serve")
    ap.add_argument("--deadline-ms", dest="deadline_ms", type=float,
                    default=500.0)
    ap.add_argument("--hedge-fraction", dest="hedge_fraction", type=float,
                    default=0.35)
    ap.add_argument("--max-attempts", dest="max_attempts", type=int,
                    default=3)
    ap.add_argument("--fair-slots", dest="fair_slots", type=int, default=16)
    ap.add_argument("--no-affinity", dest="affinity", action="store_false",
                    help="disable consistent-hash cache routing "
                         "(round-robin picks)")
    ap.add_argument("--trace", action="store_true",
                    help="arm the replica-tier trace book (obs.trace); "
                         "its snapshot rides the stats/drain reply")
    ap.add_argument("--expect-cache-version", dest="expect_cache_version",
                    help="echoed in stats for fleet bookkeeping (replicas "
                         "hold no compiled world of their own)")
    args = ap.parse_args(argv)

    if args.trace:
        from csmom_tpu.obs import trace as obs_trace

        obs_trace.arm_tracing(seed=0)

    cfg = RouterConfig(
        profile=args.profile,
        default_deadline_s=(None if args.deadline_ms in (None, 0)
                            else args.deadline_ms / 1e3),
        hedge_fraction=args.hedge_fraction,
        max_attempts=args.max_attempts,
        fair_slots=args.fair_slots,
        affinity=args.affinity,
    )
    server = RouterServer(args.listen, args.routes,
                          router_id=args.router_id, config=cfg,
                          expect_cache_version=args.expect_cache_version)

    def _term(signum, frame):  # graceful stop on SIGTERM
        server.stop()

    signal.signal(signal.SIGTERM, _term)

    # join the run's fleet observatory when armed (env inherited from
    # the router supervisor); disarmed env leaves the replica untouched
    from csmom_tpu.obs import fleet as obs_fleet

    obs_fleet.arm_emitter_from_env("router", args.router_id)

    server.bind()
    ok, reason = server.routes.status()
    print(f"[router {args.router_id}] pid {os.getpid()} listening on "
          f"{args.listen}; routes {'ok' if ok else reason} "
          f"({len(server.routes.workers())} workers)",
          file=sys.stderr, flush=True)
    server.run_until_stopped()
    obs_fleet.disarm_emitter("router stopped (drained)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
