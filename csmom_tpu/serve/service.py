"""The signal service: admission -> coalesce -> dispatch, supervised.

One worker thread drives the pipeline: it blocks on the batcher for the
next padded micro-batch, dispatches it through the engine as a single
compiled call, and fans results back out to the batch's requests.  The
design decisions that matter:

- **Warm before ready**: ``start()`` executes every (endpoint, bucket)
  shape once (``engine.warm``) and only then opens the queue, so the
  first real request never pays a compile; everything after the warmup
  snapshot counts toward ``in_window_fresh_compiles``.
- **Deadlines cancel, never dispatch**: expiry-while-queued is handled
  in the queue's collect pass (the request is terminal before a batch
  can include it); ``expired_dispatched`` stays 0 structurally and the
  SERVE artifact validator enforces it stays 0 forever.
- **A worker crash is a terminal outcome, not a leak**: the dispatch is
  wrapped so ANY failure (including the chaos ``fail`` fault at the
  ``serve.dispatch`` checkpoint, the rehearsed worker-kill) terminates
  the batch's in-flight requests as ``rejected`` with the crash as the
  reason — the accounting invariant holds and the loop continues with
  the next batch, so the remaining queue drains.  Requests are never
  silently dropped: every admitted request ends served/rejected/expired.

Chaos checkpoints (``serve.admit`` lives in queue.submit):

=================  ====================================  ===============
name               site                                  typical faults
=================  ====================================  ===============
serve.admit        queue.submit, before admission        sleep
serve.coalesce     batcher, after gathering a batch      sleep
serve.dispatch     worker, before the engine call        fail, sleep
=================  ====================================  ===============

Obs wiring (zero-cost disarmed, like everything else): queue-depth
gauge, batch-size / queue-wait / service-wall histograms, served /
rejected / expired counters, ``serve.dispatch`` spans (phase ``row``) on
the run timeline.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from csmom_tpu.serve.batcher import Batcher, Microbatch
from csmom_tpu.serve.buckets import ENDPOINTS, bucket_spec
from csmom_tpu.serve.engine import make_engine
from csmom_tpu.serve.queue import AdmissionQueue, Request
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["ServeConfig", "SignalService"]


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Service parameters (defaults = the production bucket grid)."""

    profile: str = "serve"            # buckets.PROFILES key
    engine: str = "jax"               # "jax" | "stub"
    capacity: int = 64                # admission-queue bound
    max_wait_s: float = 0.010         # coalescing window
    default_deadline_s: float | None = 0.5   # per-request, None = none
    lookback: int = 12
    skip: int = 1
    n_bins: int = 10
    mode: str = "rank"                # serve uses the fast ordinal rank


class SignalService:
    """In-process micro-batching signal-scoring service."""

    def __init__(self, config: ServeConfig | None = None):
        self.config = config or ServeConfig()
        self.spec = bucket_spec(self.config.profile)
        self.queue = AdmissionQueue(capacity=self.config.capacity)
        self.batcher = Batcher(self.spec, max_wait_s=self.config.max_wait_s)
        self.engine = make_engine(
            self.config.engine, lookback=self.config.lookback,
            skip=self.config.skip, n_bins=self.config.n_bins,
            mode=self.config.mode)
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        self.warm_report: dict | None = None
        self.n_batches = 0
        self.batch_size_hist: dict = {}
        self._pad_lanes = 0
        self._used_lanes = 0
        self._state_lock = threading.Lock()
        # live-panel version gate (streaming mode): None = batch panels,
        # no versioning.  See attach_live_version.
        self._live_version_fn = None
        self._max_version_skew = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "SignalService":
        if self._worker is not None:
            raise RuntimeError("service already started")
        self.warm_report = self.engine.warm(self.spec)
        self._worker = threading.Thread(
            target=self._worker_loop, name="csmom-serve-worker", daemon=True)
        self._worker.start()
        return self

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop the worker; with ``drain`` (default) first wait until the
        queue is empty so every admitted request reaches a terminal
        state — the accounting invariant is checked on a drained queue."""
        give_up = mono_now_s() + timeout_s
        if drain:
            while self.queue.depth() and mono_now_s() < give_up:
                self._stop.wait(0.01)
        self._stop.set()
        self.queue.wake()
        if self._worker is not None:
            self._worker.join(timeout=max(0.1, give_up - mono_now_s()))

    # --------------------------------------------------------------- submit

    def attach_live_version(self, version_fn, max_skew: int = 0) -> None:
        """Arm the live-panel version gate (streaming mode).

        ``version_fn`` returns the ingestor's CURRENT panel version; a
        request stamped with a ``panel_version`` more than ``max_skew``
        versions behind it is refused at the door — the streaming
        analogue of the pool's AOT-cache version-skew gate: a worker
        must never answer from a panel the ingest side has moved past,
        it must refuse loudly and be counted
        (``rejected_version_skew``).
        """
        self._live_version_fn = version_fn
        self._max_version_skew = int(max_skew)

    def submit(self, kind: str, values, mask, priority: str = "interactive",
               deadline_s: float | None = None,
               panel_version: int | None = None) -> Request:
        """Submit one scoring request (panel ``[A, months]``).

        ``deadline_s`` is RELATIVE seconds from now (None = the config
        default).  Returns the request handle; an unserveable request
        (unknown endpoint, too many assets, wrong month count) is
        rejected at the door — terminal immediately, counted, never
        queued behind work it can only fail.
        """
        values = np.asarray(values)
        mask = np.asarray(mask, dtype=bool)
        n_assets = int(values.shape[0]) if values.ndim == 2 else 0
        rel = (self.config.default_deadline_s if deadline_s is None
               else deadline_s)
        req = Request(
            kind=kind, values=values, mask=mask, n_assets=n_assets,
            priority=priority,
            deadline_s=None if rel is None else mono_now_s() + rel,
            panel_version=panel_version,
        )
        if self._live_version_fn is not None and panel_version is not None:
            live = int(self._live_version_fn())
            if live - panel_version > self._max_version_skew:
                self.queue.reject_at_door(
                    req,
                    f"panel-version skew: request snapshotted at v"
                    f"{panel_version} but ingest is at v{live} "
                    f"(allowed skew {self._max_version_skew}); refresh "
                    "the snapshot and resubmit",
                    version_skew=True,
                )
                return req
        reason = self._unserveable_reason(kind, values, mask)
        if reason is not None:
            self.queue.reject_at_door(req, reason)
            return req
        return self.queue.submit(req)

    def _unserveable_reason(self, kind: str, values, mask) -> str | None:
        if kind not in ENDPOINTS:
            return f"unknown endpoint {kind!r} (serveable: {ENDPOINTS})"
        if values.ndim != 2:
            return f"panel must be [assets, months], got ndim={values.ndim}"
        if values.shape[1] != self.spec.months:
            return (f"panel has {values.shape[1]} months; this service "
                    f"scores {self.spec.months}-month histories "
                    f"(bucket profile {self.spec.name!r})")
        if self.spec.asset_bucket_for(values.shape[0]) is None:
            return (f"{values.shape[0]} assets exceeds the largest bucket "
                    f"({self.spec.max_assets}); split the universe or use "
                    "a larger bucket profile")
        if mask.shape != values.shape:
            # a malformed mask must fail AT THE DOOR: past it, the padder
            # would raise inside the worker thread instead
            return (f"mask shape {mask.shape} does not match the values "
                    f"panel {values.shape}")
        return None

    # --------------------------------------------------------------- worker

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            mb = self.batcher.next_batch(self.queue, self._stop)
            if mb is None:
                continue
            self._dispatch(mb)

    def _dispatch(self, mb: Microbatch) -> None:
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics, span

        # last-instant deadline check AT the dispatch boundary: the queue's
        # collect pass sweeps expiry too, but a deadline can land in the
        # gap between collection and here — the "expired is never
        # dispatched" contract is enforced where dispatch actually begins
        now = mono_now_s()
        live = []                    # (batch row, request) actually dispatched
        for b, r in enumerate(mb.requests):
            if r.expired_at(now):
                self.queue.finish_expired(
                    r, error="deadline expired between collection and "
                             "dispatch (never dispatched)")
                metrics.counter("serve.expired").inc()
            else:
                self.queue.mark_dispatched(r, now)
                live.append((b, r))
        if not live:
            return  # the whole gathered batch expired: nothing to dispatch
        fired = checkpoint("serve.dispatch", kind=mb.kind,
                           n=len(live), bucket=f"{mb.batch_bucket}x"
                           f"{mb.asset_bucket}x{self.spec.months}")
        try:
            if fired == "fail":
                raise RuntimeError(
                    "injected worker crash (chaos 'fail' at serve.dispatch)")
            with span("serve.dispatch", phase="row", kind=mb.kind,
                      b=mb.batch_bucket, a=mb.asset_bucket) as sp:
                out = self.engine.score(mb.kind, mb.values, mb.mask)
                sp.set(n=len(live))
            for b, r in live:
                if mb.kind == "backtest":
                    res = {"mean_spread": float(out[b, 0]),
                           "ann_sharpe": float(out[b, 1])}
                else:
                    res = np.array(out[b, :r.n_assets])
                self.queue.finish_served(r, res)
                metrics.counter("serve.served").inc()
                if r.queue_wait_s is not None:
                    metrics.histogram("serve.queue_wait_s").observe(
                        r.queue_wait_s)
                if r.service_s is not None:
                    metrics.histogram("serve.service_s").observe(r.service_s)
        except Exception as e:  # worker crash: terminate, keep draining
            metrics.counter("serve.worker_crashes").inc()
            reason = (f"worker crashed mid-batch "
                      f"({type(e).__name__}: {e})"[:200])
            for _, r in live:
                self.queue.finish_rejected(r, reason, worker_crash=True)
        finally:
            used = sum(r.n_assets for _, r in live)
            with self._state_lock:
                self.n_batches += 1
                k = str(len(live))
                self.batch_size_hist[k] = self.batch_size_hist.get(k, 0) + 1
                self._used_lanes += used
                self._pad_lanes += mb.batch_bucket * mb.asset_bucket - used
            metrics.histogram("serve.batch_size").observe(len(live))

    # ------------------------------------------------------------ reporting

    def batch_stats(self) -> dict:
        with self._state_lock:
            total = self._used_lanes + self._pad_lanes
            sizes = sum(int(k) * v for k, v in self.batch_size_hist.items())
            return {
                "count": self.n_batches,
                "size_hist": dict(sorted(self.batch_size_hist.items(),
                                         key=lambda kv: int(kv[0]))),
                "mean_size": (round(sizes / self.n_batches, 3)
                              if self.n_batches else None),
                "pad_fraction": (round(self._pad_lanes / total, 4)
                                 if total else None),
            }

    def accounting(self) -> dict:
        return self.queue.accounting()

    def invariant_violations(self) -> list:
        return self.queue.invariant_violations()

    def fresh_compiles(self):
        return self.engine.fresh_compiles()
