"""The signal service: admission -> coalesce -> dispatch, supervised.

One worker thread drives the pipeline: it blocks on the adaptive batcher
for the next padded micro-batch, dispatches it through the engine as a
single compiled call, and fans results back out to the batch's requests.
The design decisions that matter:

- **Warm before ready**: ``start()`` executes every (endpoint, bucket)
  shape once (``engine.warm``) and only then opens the queue, so the
  first real request never pays a compile; everything after the warmup
  snapshot counts toward ``in_window_fresh_compiles``.
- **SLO classes at the door** (:mod:`csmom_tpu.serve.slo`): every
  request resolves to a named class whose deadline budget supplies the
  default deadline and whose quota/share bounds are enforced by the
  queue BEFORE capacity — bulk load cannot starve interactive scoring.
- **Cache first, coalesce second, queue third**
  (:mod:`csmom_tpu.serve.cache`): an identical already-scored request
  is served at the door from the version-keyed result cache; an
  identical IN-FLIGHT request attaches to its leader and shares that
  one dispatch; only novel work enters the queue.  Every path is
  counted (``served_cache_hits`` / ``served_coalesced``) and the
  accounting books close over all of them.  A ``panel_version`` bump
  from ``stream/`` ingestion invalidates every older cache entry
  (:meth:`SignalService.notify_panel_version`), and the get path
  refuses stale entries even if one survives — zero stale hits is a
  schema rule of the SERVE artifact, not a hope.
- **Deadlines cancel, never dispatch**: expiry-while-queued is handled
  in the queue's collect pass (the request is terminal before a batch
  can include it); ``expired_dispatched`` stays 0 structurally and the
  SERVE artifact validator enforces it stays 0 forever.
- **A worker crash is a terminal outcome, not a leak**: the dispatch is
  wrapped so ANY failure (including the chaos ``fail`` fault at the
  ``serve.dispatch`` checkpoint, the rehearsed worker-kill) terminates
  the batch's in-flight requests as ``rejected`` with the crash as the
  reason — the accounting invariant holds and the loop continues with
  the next batch, so the remaining queue drains.  Requests are never
  silently dropped: every admitted request ends served/rejected/expired.

Chaos checkpoints (``serve.admit`` lives in queue.submit,
``serve.cache`` in the cache's get path):

=================  ====================================  ===============
name               site                                  typical faults
=================  ====================================  ===============
serve.admit        queue.submit, before admission        sleep
serve.cache        ResultCache.get, per lookup           cache_poison
serve.coalesce     batcher, after gathering a batch      sleep
serve.dispatch     worker, before the engine call        fail, sleep
=================  ====================================  ===============

Obs wiring (zero-cost disarmed, like everything else): queue-depth
gauge, batch-size / queue-wait / service-wall histograms, served /
rejected / expired / cache-hit counters, ``serve.dispatch`` spans
(phase ``row``) on the run timeline.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from csmom_tpu.registry import serve_endpoints
from csmom_tpu.serve.batcher import Batcher, Microbatch
from csmom_tpu.serve.buckets import bucket_spec
from csmom_tpu.serve.cache import (
    CacheKey,
    InflightCoalescer,
    ResultCache,
    panel_fingerprint,
)
from csmom_tpu.serve.engine import make_engine, unpack_result
from csmom_tpu.serve.queue import AdmissionQueue, Request
from csmom_tpu.serve.slo import SLOPolicy, default_policy
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["ServeConfig", "SignalService"]


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Service parameters (defaults = the production bucket grid).

    ``default_deadline_s`` governs requests that name no deadline of
    their own: the default sentinel ``"class"`` gives each request its
    SLO class's budget (interactive 0.5 s, standard 1 s, bulk 3 s); an
    explicit float keeps the r10 semantics (that value, for every
    class); ``None`` disables default deadlines entirely.  The
    three-way split exists so an operator-configured float is never
    silently overridden by class budgets.
    """

    profile: str = "serve"            # buckets.PROFILES key
    engine: str = "jax"               # "jax" | "stub"
    capacity: int = 64                # admission-queue bound
    max_wait_s: float = 0.010         # idle-arrival coalescing window
    # "class" = per-class budget; a float = that value; None = none
    default_deadline_s: float | str | None = "class"
    lookback: int = 12
    skip: int = 1
    n_bins: int = 10
    mode: str = "rank"                # serve uses the fast ordinal rank
    policy: SLOPolicy | None = None   # SLO classes (None = default_policy)
    cache_enabled: bool = True        # the version-keyed result cache
    cache_entries: int = 512
    cache_bytes: int = 32 << 20


class SignalService:
    """In-process micro-batching signal-scoring service."""

    def __init__(self, config: ServeConfig | None = None):
        self.config = config or ServeConfig()
        self.spec = bucket_spec(self.config.profile)
        self.policy = self.config.policy or default_policy()
        self.queue = AdmissionQueue(capacity=self.config.capacity,
                                    policy=self.policy)
        self.batcher = Batcher(self.spec, max_wait_s=self.config.max_wait_s)
        self.engine = make_engine(
            self.config.engine, lookback=self.config.lookback,
            skip=self.config.skip, n_bins=self.config.n_bins,
            mode=self.config.mode)
        self.cache = (ResultCache(self.config.cache_entries,
                                  self.config.cache_bytes)
                      if self.config.cache_enabled else None)
        self._coalescer = InflightCoalescer()
        # the part of the cache key that is engine identity, not panel
        self._params_key = (self.config.engine, self.config.lookback,
                            self.config.skip, self.config.n_bins,
                            self.config.mode)
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        self.warm_report: dict | None = None
        self.n_batches = 0
        self.batch_size_hist: dict = {}
        self._pad_lanes = 0
        self._used_lanes = 0
        self._state_lock = threading.Lock()
        # live-panel version gate (streaming mode): None = batch panels,
        # no versioning.  See attach_live_version.
        self._live_version_fn = None
        self._max_version_skew = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "SignalService":
        if self._worker is not None:
            raise RuntimeError("service already started")
        self.warm_report = self.engine.warm(self.spec)
        self._worker = threading.Thread(
            target=self._worker_loop, name="csmom-serve-worker", daemon=True)
        self._worker.start()
        return self

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop the worker; with ``drain`` (default) first wait until the
        queue is empty so every admitted request reaches a terminal
        state — the accounting invariant is checked on a drained queue."""
        give_up = mono_now_s() + timeout_s
        if drain:
            while self.queue.depth() and mono_now_s() < give_up:
                self._stop.wait(0.01)
        self._stop.set()
        self.queue.wake()
        if self._worker is not None:
            self._worker.join(timeout=max(0.1, give_up - mono_now_s()))

    # --------------------------------------------------------------- submit

    def attach_live_version(self, version_fn, max_skew: int = 0) -> None:
        """Arm the live-panel version gate (streaming mode).

        ``version_fn`` returns the ingestor's CURRENT panel version; a
        request stamped with a ``panel_version`` more than ``max_skew``
        versions behind it is refused at the door — the streaming
        analogue of the pool's AOT-cache version-skew gate: a worker
        must never answer from a panel the ingest side has moved past,
        it must refuse loudly and be counted
        (``rejected_version_skew``).  The same reading drives cache
        invalidation: every submit raises the cache's version floor to
        ``live - max_skew``, so results computed from panels the gate
        would now refuse can never be served from the cache either.
        """
        self._live_version_fn = version_fn
        self._max_version_skew = int(max_skew)

    def notify_panel_version(self, version: int) -> int:
        """Ingestion-side panel_version bump: invalidate every cache
        entry computed from an older panel.  Returns how many entries
        were dropped.  (``stream/`` calls this on bar close; the loadgen
        mid-run bump drives it too.)"""
        if self.cache is None:
            return 0
        return self.cache.set_version_floor(int(version))

    def submit(self, kind: str, values, mask, priority: str = "interactive",
               deadline_s: float | None = None,
               panel_version: int | None = None,
               cacheable: bool = True, trace_ctx=None) -> Request:
        """Submit one scoring request (panel ``[A, months]``).

        ``deadline_s`` is RELATIVE seconds from now (None = the SLO
        class's budget, falling back to the config default).  Returns
        the request handle; an unserveable request (unknown endpoint or
        class, too many assets, wrong month count) is rejected at the
        door — terminal immediately, counted, never queued behind work
        it can only fail.  ``cacheable=False`` opts one request out of
        the result cache and coalescing (its dispatch is forced).
        ``trace_ctx`` carries a wire-propagated trace context (the pool
        worker path); without one, a context is minted here iff this
        process's trace book is armed (obs.trace, zero-cost disarmed).
        """
        from csmom_tpu.obs import metrics
        from csmom_tpu.obs import trace as obs_trace

        values = np.asarray(values)
        mask = np.asarray(mask, dtype=bool)
        n_assets = int(values.shape[0]) if values.ndim == 2 else 0
        try:
            cls = self.policy.resolve(priority)
        except ValueError as e:
            req = Request(kind=kind, values=values, mask=mask,
                          n_assets=n_assets,
                          priority=self.policy.names()[0],
                          trace=trace_ctx if trace_ctx is not None
                          else obs_trace.begin(kind, str(priority)))
            self.queue.reject_at_door(req, str(e))
            return req
        if deadline_s is not None:
            rel = deadline_s
        elif self.config.default_deadline_s == "class":
            rel = cls.deadline_s
        else:
            rel = self.config.default_deadline_s
        req = Request(
            kind=kind, values=values, mask=mask, n_assets=n_assets,
            priority=cls.name,
            deadline_s=None if rel is None else mono_now_s() + rel,
            panel_version=panel_version,
            # minted BEFORE the door checks so a rejection is a reasoned
            # partial trace, never a request that vanished untraced
            trace=trace_ctx if trace_ctx is not None else obs_trace.begin(
                kind, cls.name, panel_version=panel_version,
                budget_ms=round(1e3 * cls.deadline_s, 3)),
        )
        if self._live_version_fn is not None and panel_version is not None:
            live = int(self._live_version_fn())
            if self.cache is not None:
                # the gate's threshold IS the cache floor: anything the
                # door would now refuse must not be servable from cache
                self.cache.set_version_floor(live - self._max_version_skew)
            if live - panel_version > self._max_version_skew:
                self.queue.reject_at_door(
                    req,
                    f"panel-version skew: request snapshotted at v"
                    f"{panel_version} but ingest is at v{live} "
                    f"(allowed skew {self._max_version_skew}); refresh "
                    "the snapshot and resubmit",
                    version_skew=True,
                )
                return req
        reason = self._unserveable_reason(kind, values, mask)
        if reason is not None:
            self.queue.reject_at_door(req, reason)
            return req
        key = None
        if self.cache is not None and cacheable:
            key = CacheKey(kind=kind, params=self._params_key,
                           months=self.spec.months, n_assets=n_assets,
                           fingerprint=panel_fingerprint(values, mask),
                           panel_version=panel_version)
            # cache -> coalesce, re-checking the cache when a leader
            # went terminal mid-attach (its completion filled the cache,
            # so the retry is usually a hit, not a duplicate dispatch).
            # Bounded: a pathological race storm degrades to leading an
            # uncoalesced dispatch — correct, just uncached.
            role = "leader"
            for _ in range(3):
                hit, result = self.cache.get(key)
                if hit:
                    return self.queue.serve_at_door(
                        req, self._share_result(result))
                role = self._coalescer.lead_or_follow(
                    key, req, self.queue.attach_follower)
                if role != "retry":
                    break
            if role == "follower":
                metrics.counter("serve.coalesced").inc()
                return req
            if role == "leader":
                req.cache_key = key
            else:
                key = None  # retry storm: dispatch uncoalesced, uncached
        out = self.queue.submit(req)
        if key is not None and req.state == "rejected":
            # a door-rejected leader (quota/backpressure) must free the
            # in-flight slot; any follower that attached in the gap was
            # resolved inside the rejection's terminal transition
            self._coalescer.unregister(key, req)
        return out

    @staticmethod
    def _share_result(result):
        """A cached result handed to a caller: numpy payloads go out as
        read-only views and dict payloads as copies, so no caller can
        mutate the shared cache entry."""
        if isinstance(result, np.ndarray):
            view = result.view()
            view.setflags(write=False)
            return view
        if isinstance(result, dict):
            return dict(result)
        return result

    def _unserveable_reason(self, kind: str, values, mask) -> str | None:
        kinds = serve_endpoints()
        if kind not in kinds:
            return f"unknown endpoint {kind!r} (serveable: {kinds})"
        if values.ndim != 2:
            return f"panel must be [assets, months], got ndim={values.ndim}"
        if values.shape[1] != self.spec.months:
            return (f"panel has {values.shape[1]} months; this service "
                    f"scores {self.spec.months}-month histories "
                    f"(bucket profile {self.spec.name!r})")
        if self.spec.asset_bucket_for(values.shape[0]) is None:
            return (f"{values.shape[0]} assets exceeds the largest bucket "
                    f"({self.spec.max_assets}); split the universe or use "
                    "a larger bucket profile")
        if mask.shape != values.shape:
            # a malformed mask must fail AT THE DOOR: past it, the padder
            # would raise inside the worker thread instead
            return (f"mask shape {mask.shape} does not match the values "
                    f"panel {values.shape}")
        return None

    # --------------------------------------------------------------- worker

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            mb = self.batcher.next_batch(self.queue, self._stop)
            if mb is None:
                continue
            self._dispatch(mb)

    def _release_key(self, req: Request) -> None:
        key = getattr(req, "cache_key", None)
        if key is not None:
            self._coalescer.unregister(key, req)

    def _dispatch(self, mb: Microbatch) -> None:
        from csmom_tpu.chaos.inject import checkpoint
        from csmom_tpu.obs import metrics, span

        # last-instant deadline check AT the dispatch boundary: the queue's
        # collect pass sweeps expiry too, but a deadline can land in the
        # gap between collection and here — the "expired is never
        # dispatched" contract is enforced where dispatch actually begins
        now = mono_now_s()
        live = []                    # (batch row, request) actually dispatched
        for b, r in enumerate(mb.requests):
            if r.expired_at(now):
                self.queue.finish_expired(
                    r, error="deadline expired between collection and "
                             "dispatch (never dispatched)")
                self._release_key(r)
                metrics.counter("serve.expired").inc()
            else:
                self.queue.mark_dispatched(r, now)
                live.append((b, r))
        if not live:
            return  # the whole gathered batch expired: nothing to dispatch
        fired = checkpoint("serve.dispatch", kind=mb.kind,
                           n=len(live), bucket=f"{mb.batch_bucket}x"
                           f"{mb.asset_bucket}x{self.spec.months}")
        metrics.gauge("serve.in_flight").set(len(live))
        t_engine = mono_now_s()
        try:
            if fired == "fail":
                raise RuntimeError(
                    "injected worker crash (chaos 'fail' at serve.dispatch)")
            with span("serve.dispatch", phase="row", kind=mb.kind,
                      b=mb.batch_bucket, a=mb.asset_bucket) as sp:
                out = self.engine.score(mb.kind, mb.values, mb.mask)
                sp.set(n=len(live))
            # stamp the engine-wall boundary for every request BEFORE the
            # fan-out loop, so one request's unpack/cache time is never
            # attributed to a batchmate's dispatch stage.  Shard lookup
            # and mark/set run only for LIVE contexts (`t.live` is False
            # on the disarmed no-op singleton): a disarmed batch pays no
            # registry resolution and allocates nothing here
            shards = unresolved = object()
            for _, r in live:
                t = r.trace
                if t is None or not t.live:
                    continue
                if shards is unresolved:
                    shards = (self.engine.dispatch_shards(
                        mb.kind, mb.batch_bucket, mb.asset_bucket)
                        if hasattr(self.engine, "dispatch_shards")
                        else None)
                t.mark("dispatch")
                if shards is not None:
                    t.set(mesh_devices=shards[0], mesh_shards=shards[1])
            for b, r in live:
                # per-asset vs summary unpacking is the registered
                # engine's declaration, not a name special-case here
                res = unpack_result(mb.kind, out, b, r.n_assets)
                key = getattr(r, "cache_key", None)
                if key is not None and self.cache is not None:
                    # fill the cache BEFORE resolving the leader, so a
                    # submit racing the terminal transition finds the
                    # result instead of re-leading a dispatch
                    self.cache.put(key, res)
                self.queue.finish_served(r, res)
                self._release_key(r)
                metrics.counter("serve.served").inc()
                if r.queue_wait_s is not None:
                    metrics.histogram("serve.queue_wait_s").observe(
                        r.queue_wait_s)
                if r.service_s is not None:
                    metrics.histogram("serve.service_s").observe(r.service_s)
        except Exception as e:  # worker crash: terminate, keep draining
            metrics.counter("serve.worker_crashes").inc()
            reason = (f"worker crashed mid-batch "
                      f"({type(e).__name__}: {e})"[:200])
            for _, r in live:
                self.queue.finish_rejected(r, reason, worker_crash=True)
                self._release_key(r)
        finally:
            from csmom_tpu.obs import trace as obs_trace

            self.batcher.note_service_wall(mono_now_s() - t_engine)
            used = sum(r.n_assets for _, r in live)
            pad = mb.batch_bucket * mb.asset_bucket - used
            with self._state_lock:
                self.n_batches += 1
                k = str(len(live))
                self.batch_size_hist[k] = self.batch_size_hist.get(k, 0) + 1
                self._used_lanes += used
                self._pad_lanes += pad
            obs_trace.note_batch(mb.kind, mb.batch_bucket, mb.asset_bucket,
                                 used, pad, mb.fire_reason)
            metrics.histogram("serve.batch_size").observe(len(live))
            metrics.gauge("serve.in_flight").set(0)

    # ------------------------------------------------------------ reporting

    def batch_stats(self) -> dict:
        with self._state_lock:
            total = self._used_lanes + self._pad_lanes
            sizes = sum(int(k) * v for k, v in self.batch_size_hist.items())
            stats = {
                "count": self.n_batches,
                "size_hist": dict(sorted(self.batch_size_hist.items(),
                                         key=lambda kv: int(kv[0]))),
                "mean_size": (round(sizes / self.n_batches, 3)
                              if self.n_batches else None),
                "pad_fraction": (round(self._pad_lanes / total, 4)
                                 if total else None),
            }
        stats["fire_reasons"] = self.batcher.fire_reason_counts()
        return stats

    def cache_stats(self) -> dict:
        if self.cache is None:
            return {"enabled": False}
        out = self.cache.stats()
        out["enabled"] = True
        out["inflight_leaders"] = self._coalescer.inflight()
        return out

    def class_stats(self) -> dict:
        """Per-class books + the policy's budgets (the SERVE artifact's
        ``classes`` block is built from this)."""
        books = self.queue.class_accounting()
        policy = self.policy.summary()
        return {name: {**books[name], **policy[name]} for name in books}

    def accounting(self) -> dict:
        return self.queue.accounting()

    def invariant_violations(self) -> list:
        return self.queue.invariant_violations()

    def fresh_compiles(self):
        return self.engine.fresh_compiles()
