"""SLO classes: named service classes with budgets, quotas, and shares.

The r10 queue hard-coded two priorities (``interactive`` > ``batch``);
that expressed dispatch ORDER but nothing else — no per-class latency
promise, and no protection beyond ordering, so a bulk-backtest tenant
could still fill the bounded queue and starve interactive scoring with
backpressure rejections.  This module replaces the pair with a small
policy object of named classes, each carrying:

- a **deadline budget** (``deadline_s``): the class's latency promise.
  It is the default per-request deadline (a request that does not name
  its own deadline inherits the class budget) AND the p99 target the
  SERVE artifact's per-class books are judged against (``within_budget``
  per class; the ledger ingests per-class p99 rows so a class busting
  its budget fails the PR gate, not the postmortem).
- an **admission quota** (token bucket: ``quota_rps`` + ``quota_burst``):
  a sustained-rate cap with bounded burst credit.  A class offered more
  than its quota is rejected at the door (``rejected_quota``, per class)
  BEFORE it can occupy queue capacity.
- a **queue share** (``queue_share``): the fraction of the bounded
  admission queue this class may occupy.  Even inside its rate quota, a
  class can never hold more than its share of the slots — so a bulk
  burst that arrives faster than the engine drains provably cannot
  consume the capacity interactive admissions need.

Starvation-proofness is the composition: dispatch order prefers lower
``rank`` (interactive first, unchanged from r10), the queue share bounds
how much of the buffer bulk can sit in, and the token bucket bounds how
fast bulk can even ask.  ``tests/test_serve_slo.py`` pins the property
end-to-end: bulk saturation with interactive p99 still inside its class
budget.

Back-compat: the r10 priority name ``batch`` resolves to ``bulk`` (the
alias table), so existing callers and the pool wire protocol keep
working unchanged.

Stdlib-only and clock-disciplined: the token bucket never reads a clock
itself — callers pass ``now_s`` from ``utils.deadline.mono_now_s`` (the
time-discipline lint pins this module wall-clock- and inline-monotonic-
free, like the rest of serve/).
"""

from __future__ import annotations

import dataclasses

__all__ = ["ALIASES", "SLOClass", "SLOPolicy", "TokenBucket",
           "default_policy"]

# legacy priority names (r10's two-class queue, the pool wire protocol)
# -> canonical SLO class names
ALIASES = {"batch": "bulk"}


class TokenBucket:
    """Sustained-rate admission quota with bounded burst credit.

    ``rate`` tokens/second refill up to ``burst``; each admission takes
    one token.  Clock-free by design: every call passes ``now_s`` (the
    caller's ``mono_now_s()``), which also makes quota behavior exactly
    testable without sleeping.
    """

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate/burst must be > 0, got {rate}/{burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._last_s: float | None = None

    def try_take(self, now_s: float) -> bool:
        """Take one token if available (refilling first); False = over
        quota right now."""
        if self._last_s is not None and now_s > self._last_s:
            self._tokens = min(self.burst,
                               self._tokens + (now_s - self._last_s)
                               * self.rate)
        self._last_s = now_s
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One named service class: budget, quota, share, dispatch rank."""

    name: str
    rank: int                      # dispatch order: lower collects first
    deadline_s: float              # latency budget = default deadline AND
                                   # the per-class p99 promise
    quota_rps: float | None = None  # token-bucket rate (None = unlimited)
    quota_burst: float | None = None  # bucket depth (default: 1.5x rate)
    queue_share: float = 1.0       # max fraction of queue capacity

    def make_bucket(self) -> TokenBucket | None:
        if self.quota_rps is None:
            return None
        burst = (self.quota_burst if self.quota_burst is not None
                 else 1.5 * self.quota_rps)
        return TokenBucket(self.quota_rps, burst)

    def max_queued(self, capacity: int) -> int:
        """Slots of a ``capacity``-bounded queue this class may occupy."""
        share = min(1.0, max(0.0, self.queue_share))
        return max(1, int(share * capacity))


class SLOPolicy:
    """An ordered set of SLO classes (rank order = dispatch order)."""

    def __init__(self, classes: tuple):
        if not classes:
            raise ValueError("an SLO policy needs at least one class")
        ordered = sorted(classes, key=lambda c: c.rank)
        names = [c.name for c in ordered]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO class names: {names}")
        self.classes = tuple(ordered)
        self._by_name = {c.name: c for c in ordered}

    def names(self) -> tuple:
        """Class names in dispatch (rank) order."""
        return tuple(c.name for c in self.classes)

    def resolve(self, name: str) -> SLOClass:
        """The class for ``name`` (aliases honored); raises on unknown —
        an unknown class must fail at the door, not invent a bucket."""
        canonical = ALIASES.get(name, name)
        try:
            return self._by_name[canonical]
        except KeyError:
            raise ValueError(
                f"unknown SLO class {name!r} (known: "
                f"{list(self.names())}, aliases: {ALIASES})"
            ) from None

    def resolve_name(self, name: str) -> str:
        return self.resolve(name).name

    def summary(self) -> dict:
        """The policy as artifact-ready JSON (budgets in ms)."""
        return {
            c.name: {
                "rank": c.rank,
                "budget_ms": round(1e3 * c.deadline_s, 3),
                "quota_rps": c.quota_rps,
                "queue_share": c.queue_share,
            }
            for c in self.classes
        }


def default_policy() -> SLOPolicy:
    """The production default: three classes.

    - ``interactive``: tight budget, no rate quota, may use the whole
      queue — the class the service exists to protect.
    - ``standard``: middling budget, no rate quota, bounded to 3/4 of
      the queue.
    - ``bulk``: the backtest tenant — generous budget, rate-limited
      (16 req/s sustained, 24 burst), and never more than half the
      queue, so bulk saturation cannot starve interactive admission.
    """
    return SLOPolicy((
        SLOClass("interactive", rank=0, deadline_s=0.5),
        SLOClass("standard", rank=1, deadline_s=1.0, queue_share=0.75),
        SLOClass("bulk", rank=2, deadline_s=3.0,
                 quota_rps=16.0, quota_burst=24.0, queue_share=0.5),
    ))
