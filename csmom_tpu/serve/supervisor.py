"""Pool supervisor: spawn, probe, restart, and roll the worker fleet.

The supervisor owns worker *processes* the way the queue owns requests —
every one it spawns ends in a known state, with the transitions logged
as events the SERVE_POOL artifact carries:

- **Spawn + demonstrated ready**: a worker is routable only after its
  readiness probe (:mod:`csmom_tpu.serve.health`) reports ``ok`` — every
  bucket shape warmed, every endpoint self-probed, zero fresh compiles,
  cache version matching the supervisor's expectation.  A worker that
  exits ``RC_VERSION_SKEW`` is parked immediately as ``failed`` (a
  restart cannot fix skew; redeploying can), with the worker's pointed
  stderr message preserved as the reason.
- **Crash restart with exponential backoff + jitter**: a dead worker is
  respawned after ``backoff_base_s * 2^k``, jittered ±50% (seeded, so
  rehearsals replay), capped at ``backoff_cap_s``.  A worker that keeps
  dying young (within ``min_uptime_s`` of spawn) escalates ``k``; after
  ``max_restarts`` consecutive young deaths the slot is parked
  ``failed`` — a crash-looping binary must not be hot-spun forever.  A
  worker that lived long resets its own counter.
- **Rolling restart, warm-before-ready**: for each slot, a REPLACEMENT
  worker spawns on a fresh socket and must report fully ready — which
  includes ``fresh_compiles == 0``, i.e. it loaded the serialized AOT
  cache instead of compiling — before its predecessor is drained and
  stopped.  If the replacement refuses (skew) or times out, the roll
  aborts and the predecessor KEEPS SERVING: a bad deploy costs an
  aborted roll, never capacity.

The router reads :meth:`ready_workers` per dispatch attempt, so the
routable set and the supervised set are the same object — there is no
cached view to go stale between a crash and the next request.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import subprocess
import sys
import threading

from csmom_tpu.serve import health, proto
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["PoolConfig", "PoolSupervisor", "WorkerHandle"]

# the repo checkout that owns this module: spawned workers run
# ``sys.executable -m csmom_tpu...``, so the package must resolve in the
# child no matter what cwd the caller is parked in (smoke/test runs chdir
# into scratch dirs; for an installed package the prepend is a no-op)
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class PoolConfig:
    """Everything the supervisor needs to run one worker fleet."""

    n_workers: int = 2
    profile: str = "serve"
    engine: str = "jax"
    # wire transport for the fleet's sockets: "unix" (one host, the r11
    # default) or "tcp" (loopback ports today, cross-container by
    # swapping the host — the r18 fabric's spelling)
    transport: str = "unix"
    capacity: int = 64
    max_wait_ms: float = 10.0
    deadline_ms: float = 500.0
    # >0 pins each slot to a fixed contiguous device slice (slot k owns
    # devices [k*N, k*N+N)); a replacement worker spawned into the slot
    # re-pins the SAME slice by construction (csmom_tpu/mesh/pinning)
    devices_per_worker: int = 0
    cache_subdir: str = "bench"
    require_warm_cache: bool = False
    expect_cache_version: str | None = None  # None = compute from health
    ready_timeout_s: float = 120.0
    poll_interval_s: float = 0.2
    backoff_base_s: float = 0.2
    backoff_cap_s: float = 5.0
    max_restarts: int = 5
    min_uptime_s: float = 2.0
    seed: int = 0


@dataclasses.dataclass
class WorkerHandle:
    """One supervised worker slot (the process may change; the slot
    persists across restarts and rolls)."""

    slot: int
    worker_id: str
    socket_path: str
    device_slice: str | None = None
    proc: subprocess.Popen | None = None
    state: str = "starting"   # starting | ready | draining | dead | failed
    # how the CURRENT process came to exist: cold | respawn | roll |
    # spare-promotion — ready-wall samples gate per regime (ISSUE 20)
    spawn_kind: str = "cold"
    generation: int = 0
    restarts: int = 0          # consecutive young deaths (resets on uptime)
    next_restart_at: float | None = None
    t_spawned_s: float = 0.0
    t_ready_s: float | None = None
    reason: str | None = None
    ready_report: dict | None = None
    log_path: str | None = None


class PoolSupervisor:
    """Spawn and babysit N workers; expose the READY set to the router.

    The machinery is tier-agnostic on purpose (the r18 fabric): what a
    slot RUNS comes from :meth:`_slot_argv`, and where it listens from
    :meth:`_slot_address` — the router-replica supervisor
    (:class:`csmom_tpu.serve.fabric.RouterSupervisor`) overrides exactly
    those two hooks and inherits spawn, demonstrated-ready probing,
    exponential-backoff restarts, crash-loop parking, and
    warm-before-ready rolling restarts unchanged.
    """

    # worker ids are "<prefix><slot>" — the router tier overrides to "r"
    slot_prefix = "w"

    def __init__(self, config: PoolConfig, run_dir: str):
        self.config = config
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        mesh_devices = None
        if config.engine == "jax-mesh" and not config.expect_cache_version:
            # the token must match what each worker computes: the pinned
            # slice size, or — unpinned — every visible device (workers
            # inherit this process's environment, so the counts agree).
            # Only the mesh engine pays the jax import here; the stub
            # rehearse tier and plain-jax pools stay jax-free.
            mesh_devices = config.devices_per_worker or None
            if mesh_devices is None:
                import jax

                mesh_devices = len(jax.devices())
        self.expect_cache_version = (
            config.expect_cache_version
            or health.aot_cache_version(
                config.profile, engine=config.engine,
                mesh_devices=mesh_devices))
        self.handles: list = []
        self.events: list = []      # [{t_s, event, worker_id, ...}]
        # merged into every spawned process's environment AFTER the
        # inherited os.environ — the fabric uses this to arm chaos plans
        # in ONE tier (e.g. net_delay in router replicas only) without
        # polluting its own process
        self.extra_env: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        self._rng = random.Random(config.seed)
        self._t0 = mono_now_s()
        self.kills_observed = 0
        self.restarts_total = 0
        self.rolls_completed = 0
        # the elastic tier (serve/fleet.py) attaches here when armed:
        # death hooks run on the monitor thread BEFORE backoff/park —
        # a hook returning True claims the death (spare promotion) and
        # the supervisor schedules no re-warm for that slot
        self.fleet = None
        self.death_hooks: list = []

    # -------------------------------------------------------------- events

    def _event(self, event: str, worker_id: str, **ctx) -> None:
        rec = {"t_s": round(mono_now_s() - self._t0, 4), "event": event,
               "worker_id": worker_id, **ctx}
        with self._lock:
            self.events.append(rec)

    @property
    def t0_mono_s(self) -> float:
        """The absolute monotonic instant this supervisor's event clock
        started — ``event["t_s"] + t0_mono_s`` puts lifecycle events on
        the same system-wide timeline the fleet observatory samples on
        (``obs.fleet.absolute_events``)."""
        return self._t0

    def ready_walls(self) -> list:
        """Every (re)spawn's spawn→ready wall plus the worker-reported
        bind/warm decomposition — the ``worker-ready-wall`` samples the
        capacity account and ROADMAP item 2's autoscaler consume."""
        with self._lock:
            return [{"worker_id": e["worker_id"],
                     "generation": e.get("generation"),
                     "kind": e.get("spawn_kind") or "cold",
                     "wall_s": e.get("wall_s"),
                     "walls": e.get("walls")}
                    for e in self.events if e["event"] == "ready"]

    # --------------------------------------------------------------- spawn

    def _slot_address(self, slot: int, generation: int = 0) -> str:
        """Where the process in ``slot`` (at ``generation``) listens.
        Unix sockets are run-dir files; tcp binds a freshly-probed
        loopback port per (slot, generation) — a rolling replacement
        must not race its predecessor for the same port."""
        if self.config.transport == "tcp":
            from csmom_tpu.serve.proto import free_tcp_port

            return f"tcp:127.0.0.1:{free_tcp_port()}"
        name = (f"{self.slot_prefix}{slot}.sock" if generation == 0
                else f"{self.slot_prefix}{slot}.g{generation}.sock")
        return os.path.join(self.run_dir, name)

    def _slot_argv(self, h: WorkerHandle) -> list:
        """The command a slot runs (the router tier overrides this)."""
        return self._worker_argv(h)

    def _worker_argv(self, h: WorkerHandle) -> list:
        c = self.config
        argv = [sys.executable, "-m", "csmom_tpu.serve.worker",
                "--socket", h.socket_path,
                "--worker-id", h.worker_id,
                "--profile", c.profile,
                "--engine", c.engine,
                "--capacity", str(c.capacity),
                "--max-wait-ms", str(c.max_wait_ms),
                "--deadline-ms", str(c.deadline_ms),
                "--cache-subdir", c.cache_subdir,
                "--expect-cache-version", self.expect_cache_version]
        if h.device_slice:
            argv += ["--device-slice", h.device_slice]
        if c.require_warm_cache:
            argv.append("--require-warm-cache")
        return argv

    def _spawn_env(self) -> dict:
        """The environment every slot process runs under (shared with
        the spare pool in ``serve/fleet.py`` — a promoted spare must be
        indistinguishable from a supervisor-spawned worker)."""
        env = dict(os.environ)  # fault plans and JAX_PLATFORMS inherit
        env["PYTHONPATH"] = (_PKG_ROOT + os.pathsep
                             + env.get("PYTHONPATH", ""))
        env.update(self.extra_env)
        c = self.config
        if (c.devices_per_worker > 0 and c.engine == "jax-mesh"
                and env.get("JAX_PLATFORMS", "").startswith("cpu")
                and "xla_force_host_platform_device_count"
                not in env.get("XLA_FLAGS", "")):
            # the CPU recipe: every worker must SEE the whole simulated
            # topology so its slice indexes the same device list the
            # supervisor derived slices from (real TPU topologies
            # provide their own devices and skip this)
            need = c.n_workers * c.devices_per_worker
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={need}"
            ).strip()
        return env

    def _spawn(self, h: WorkerHandle) -> None:
        from csmom_tpu.chaos.inject import checkpoint

        checkpoint("pool.spawn", worker=h.worker_id, gen=h.generation)
        h.log_path = os.path.join(
            self.run_dir, f"{h.worker_id}.g{h.generation}.log")
        env = self._spawn_env()
        log = open(h.log_path, "ab")
        try:
            h.proc = subprocess.Popen(
                self._slot_argv(h), stdout=log, stderr=log, env=env)
        finally:
            log.close()
        h.state = "starting"
        h.t_spawned_s = mono_now_s()
        h.t_ready_s = None
        h.ready_report = None
        self._event("spawn", h.worker_id, pid=h.proc.pid,
                    generation=h.generation,
                    device_slice=h.device_slice)

    def _stderr_tail(self, h: WorkerHandle, n: int = 400) -> str:
        try:
            with open(h.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 4096))
                return f.read().decode("utf-8", "replace")[-n:].strip()
        except (OSError, TypeError):
            return ""

    def _probe_until_ready(self, h: WorkerHandle,
                           timeout_s: float) -> bool:
        """Poll readiness until ok / worker exit / timeout.  A worker
        that EXITS while starting is classified: version-skew refusal
        and cold-cache refusal park the slot as ``failed`` (restart
        cannot fix either); anything else is a crash (restartable)."""
        from csmom_tpu.serve.worker import RC_COLD_CACHE, RC_VERSION_SKEW

        give_up = mono_now_s() + timeout_s
        while mono_now_s() < give_up and not self._stop.is_set():
            rc = h.proc.poll()
            if rc is not None:
                tail = self._stderr_tail(h)
                if rc in (RC_VERSION_SKEW, RC_COLD_CACHE):
                    # a restart cannot fix skew or a cold cache: park the
                    # slot with the worker's own pointed message — no
                    # backoff loop, no silent compile
                    h.state = "failed"
                    h.reason = (
                        f"worker refused ready (rc={rc}): {tail}")
                    self._event("refused_ready", h.worker_id, rc=rc,
                                reason=tail[:200])
                else:
                    # a startup crash is a crash: same backoff/park
                    # machinery as a death in service
                    self._event("died_starting", h.worker_id, rc=rc)
                    self._on_death(h, mono_now_s())
                return False
            report = health.readiness(h.socket_path, timeout_s=2.0)
            if report.get("ok"):
                h.state = "ready"
                h.t_ready_s = mono_now_s()
                h.ready_report = report
                # the spawn→bind→warm→ready decomposition: wall_s is the
                # supervisor-observed spawn→ready; "walls" carries the
                # worker's own bind/warm stamps from its ready report —
                # one sample per (re)spawn, recorded even with fleet
                # capture disarmed (the re-warm window is measured at
                # the source)
                self._event("ready", h.worker_id,
                            generation=h.generation,
                            spawn_kind=h.spawn_kind,
                            fresh_compiles=report.get("fresh_compiles"),
                            wall_s=round(h.t_ready_s - h.t_spawned_s, 3),
                            walls=report.get("walls"))
                self._gauge_ready()
                return True
            self._stop.wait(self.config.poll_interval_s)
        if h.state == "starting":
            h.state = "failed"
            h.reason = f"never became ready within {timeout_s:.0f}s"
            self._event("ready_timeout", h.worker_id)
        return False

    # ----------------------------------------------------------- lifecycle

    def start(self, require_ready: bool = True) -> "PoolSupervisor":
        """Spawn the fleet and wait until every slot resolved (ready,
        failed, or scheduled for a backoff restart).  With
        ``require_ready`` (default), raises when NO worker became ready
        — an empty pool is a dead service, better to fail loudly at
        start; ``require_ready=False`` lets the monitor keep working a
        crash-looping fleet (the backoff rehearsals drive this)."""
        from csmom_tpu.mesh.pinning import slice_for_slot

        dpw = self.config.devices_per_worker
        for slot in range(self.config.n_workers):
            h = WorkerHandle(
                slot=slot, worker_id=f"{self.slot_prefix}{slot}",
                socket_path=self._slot_address(slot),
                device_slice=slice_for_slot(slot, dpw) if dpw else None)
            self.handles.append(h)
            self._spawn(h)
        for h in self.handles:
            self._probe_until_ready(h, self.config.ready_timeout_s)
        if require_ready and not self.ready_workers():
            reasons = "; ".join(
                f"{h.worker_id}: {h.reason}" for h in self.handles)
            self.stop()
            raise RuntimeError(f"no worker became ready — {reasons}")
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="csmom-pool-monitor",
            daemon=True)
        self._monitor.start()
        return self

    def ready_workers(self) -> list:
        return [h for h in self.handles if h.state == "ready"]

    def retry_after_s(self) -> float | None:
        """The backoff-state retry hint for a fleet with NO ready worker:
        seconds until the NEXT scheduled restart could plausibly serve
        (its backoff delay plus the ready timeout's headroom is the
        caller's problem — the hint is the floor, not a promise).  None
        while any worker is ready (no hint needed) or when every slot is
        parked ``failed`` (retrying cannot help; redeploying can —
        callers should surface the park reason instead)."""
        now = mono_now_s()
        best = None
        for h in self.handles:
            if h.state == "ready":
                return None
            if h.state == "starting":
                # a spawn in flight: readiness is typically one probe
                # interval away
                cand = self.config.poll_interval_s
            elif h.state == "dead" and h.next_restart_at is not None:
                cand = max(self.config.poll_interval_s,
                           h.next_restart_at - now)
            else:
                continue  # parked/failed: no restart is coming
            best = cand if best is None else min(best, cand)
        return None if best is None else round(best, 3)

    def _gauge_ready(self) -> None:
        from csmom_tpu.obs import metrics

        metrics.gauge("serve_pool.ready_workers").set(
            len(self.ready_workers()))

    # -------------------------------------------------------------- monitor

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            now = mono_now_s()
            for h in list(self.handles):
                if h.state == "ready" and h.proc.poll() is not None:
                    self._on_death(h, now)
                elif h.state == "dead" and h.next_restart_at is not None \
                        and now >= h.next_restart_at:
                    h.next_restart_at = None
                    self._restart(h)
            self._stop.wait(self.config.poll_interval_s)

    def _on_death(self, h: WorkerHandle, now: float) -> None:
        rc = h.proc.returncode
        uptime = now - (h.t_ready_s or h.t_spawned_s)
        young = uptime < self.config.min_uptime_s
        h.restarts = h.restarts + 1 if young else 1
        with self._lock:
            self.kills_observed += 1
        h.state = "dead"
        h.reason = f"died rc={rc} after {uptime:.2f}s"
        self._event("death", h.worker_id, rc=rc,
                    uptime_s=round(uptime, 3), young=young,
                    consecutive=h.restarts)
        self._gauge_ready()
        # the elastic tier's seam: a hook that promotes a hot spare into
        # the slot returns True and the re-warm machinery below never
        # runs — the kill cost one routes publish, not a warm window
        for hook in list(self.death_hooks):
            try:
                if hook(h, now):
                    return
            except Exception as e:  # a broken hook must not kill the monitor
                self._event("death_hook_error", h.worker_id,
                            error=f"{type(e).__name__}: {e}"[:200])
        if h.restarts > self.config.max_restarts:
            h.state = "failed"
            h.reason = (f"crash loop: {h.restarts - 1} consecutive young "
                        f"deaths — parked (not hot-spinning a broken "
                        "worker)")
            self._event("crash_loop_parked", h.worker_id,
                        restarts=h.restarts - 1)
            return
        # exponential backoff with seeded ±50% jitter, capped
        base = min(self.config.backoff_cap_s,
                   self.config.backoff_base_s * (2 ** (h.restarts - 1)))
        delay = base * (1.0 + self._rng.uniform(-0.5, 0.5))
        h.next_restart_at = now + delay
        self._event("restart_scheduled", h.worker_id,
                    delay_s=round(delay, 3), backoff_base_s=round(base, 3))

    def _restart(self, h: WorkerHandle) -> None:
        h.generation += 1
        h.spawn_kind = "respawn"
        if self.config.transport == "tcp":
            # the crash may have BEEN a lost port race (or the port got
            # claimed while the slot was down): a replacement probes a
            # fresh port like a rolling replacement does — retrying the
            # dead port every backoff cycle can only crash-loop to
            # parked, even with unlimited free ports available
            h.socket_path = self._slot_address(h.slot, h.generation)
        with self._lock:
            self.restarts_total += 1
        self._spawn(h)
        threading.Thread(
            target=self._probe_until_ready,
            args=(h, self.config.ready_timeout_s), daemon=True).start()

    # ------------------------------------------------------------- rolling

    def rolling_restart(self) -> dict:
        """Replace every worker, one at a time, warm-before-ready.

        Per slot: spawn the replacement on a fresh socket; it must
        report READY — including zero fresh compiles — before the
        predecessor drains.  Returns a summary; ``aborted`` carries the
        first failure (the old worker keeps serving in that case)."""
        rolled, aborted = [], None
        for slot in range(len(self.handles)):
            old = self.handles[slot]
            if old.state != "ready":
                continue
            repl = WorkerHandle(
                slot=slot, worker_id=old.worker_id,
                socket_path=self._slot_address(slot, old.generation + 1),
                # the slot's slice, not a fresh assignment: a rolled
                # worker re-pins exactly its predecessor's devices
                device_slice=old.device_slice,
                spawn_kind="roll",
                generation=old.generation + 1)
            self._event("roll_start", old.worker_id,
                        from_generation=old.generation,
                        to_generation=repl.generation)
            self._spawn(repl)
            if not self._probe_until_ready(repl,
                                           self.config.ready_timeout_s):
                aborted = (f"{repl.worker_id} g{repl.generation}: "
                           f"{repl.reason}")
                self._event("roll_aborted", old.worker_id,
                            reason=repl.reason)
                self._reap(repl)
                break
            # replacement is demonstrably warm: NOW drain the predecessor.
            # Swap before draining so the router's next pick sees the new
            # generation — zero-capacity gap by construction.
            self.handles[slot] = repl
            old.state = "draining"
            self._drain_stop(old)
            rolled.append({"worker_id": repl.worker_id,
                           "generation": repl.generation,
                           "fresh_compiles":
                               (repl.ready_report or {}).get(
                                   "fresh_compiles")})
            with self._lock:
                self.rolls_completed += 1
            self._event("roll_done", repl.worker_id,
                        generation=repl.generation)
        return {"rolled": rolled, "aborted": aborted}

    # ---------------------------------------------------------------- stop

    def _drain_stop(self, h: WorkerHandle, timeout_s: float = 15.0) -> None:
        stop_acked = False
        try:
            proto.request_once(h.socket_path, {"op": "stop"},
                          timeout_s=timeout_s)
            stop_acked = True
        except (OSError, proto.ProtocolError):
            pass  # dead, wedged, or mid-start (socket not bound yet)
        if h.proc is not None:
            try:
                # a worker that never acked the stop op (e.g. still
                # importing before its bind) gets only a short grace
                # before SIGTERM — its own handler drains on TERM
                h.proc.wait(timeout=timeout_s if stop_acked else 0.5)
            except subprocess.TimeoutExpired:
                h.proc.terminate()
                try:
                    h.proc.wait(timeout=3.0)
                except subprocess.TimeoutExpired:
                    h.proc.kill()
        h.state = "dead" if h.state != "failed" else h.state
        self._event("stopped", h.worker_id, generation=h.generation)

    def _reap(self, h: WorkerHandle) -> None:
        if h.proc is not None and h.proc.poll() is None:
            h.proc.terminate()
            try:
                h.proc.wait(timeout=3.0)
            except subprocess.TimeoutExpired:
                h.proc.kill()

    def stop(self) -> None:
        """Drain-stop the fleet and the monitor (idempotent)."""
        fleet = self.fleet
        if fleet is not None:
            # the elastic tier first: no promotion/backfill/scaling may
            # race the drain (controller stop is idempotent)
            fleet.stop()
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        for h in self.handles:
            if h.proc is not None and h.proc.poll() is None:
                self._drain_stop(h)

    # ---------------------------------------------------------------- info

    def kill_worker(self, worker_id: str, sig=signal.SIGKILL) -> bool:
        """Chaos hook: hard-kill one worker's CURRENT process (the
        rehearsal's worker-process death; the monitor sees it like any
        crash)."""
        for h in self.handles:
            if h.worker_id == worker_id and h.proc is not None \
                    and h.proc.poll() is None:
                os.kill(h.proc.pid, sig)
                self._event("chaos_kill", worker_id, sig=int(sig))
                return True
        return False

    def worker_stats(self) -> list:
        """Per-worker stats from every live worker (a corpse contributes
        its handle state and a reason instead — lost books are REPORTED,
        the router's accounting is the closed ledger)."""
        out = []
        for h in self.handles:
            rec = {"worker_id": h.worker_id, "state": h.state,
                   "generation": h.generation, "restarts": h.restarts,
                   "device_slice": h.device_slice}
            if h.t_ready_s is not None and h.t_spawned_s is not None:
                rec["lifecycle"] = {
                    "ready_wall_s": round(h.t_ready_s - h.t_spawned_s, 3),
                    "walls": (h.ready_report or {}).get("walls"),
                }
            if h.state == "ready":
                try:
                    obj, _ = proto.request_once(h.socket_path, {"op": "stats"},
                                           timeout_s=5.0)
                    rec.update({
                        "accounting": obj.get("accounting"),
                        "batches": obj.get("batches"),
                        "cache": obj.get("cache"),
                        "fresh_compiles": obj.get("fresh_compiles"),
                    })
                except (OSError, proto.ProtocolError) as e:
                    rec["stats_error"] = f"{type(e).__name__}: {e}"[:120]
            elif h.reason:
                rec["reason"] = h.reason[:300]
            out.append(rec)
        return out

    def summary(self) -> dict:
        with self._lock:
            return {
                "n_workers": self.config.n_workers,
                "expect_cache_version": self.expect_cache_version,
                "kills": self.kills_observed,
                "restarts": self.restarts_total,
                "rolls_completed": self.rolls_completed,
                "events": list(self.events),
            }
