"""Pool worker: one process owning one ``SignalService``, behind a socket.

``python -m csmom_tpu.serve.worker --socket PATH ...`` runs the existing
in-process micro-batching service (:mod:`csmom_tpu.serve.service`)
wrapped in the pool wire protocol (:mod:`csmom_tpu.serve.proto`): the
router holds a PERSISTENT multiplexed channel here (many in-flight
score frames interleave on it, each handled on its own thread — ISSUE
15), the supervisor dials one-shot for probes and lifecycle ops.  The
process is the isolation unit — a crash, a GIL stall, or a restart
here takes down ONE worker's queue, and the router's hedged retries
route around it.

Startup discipline (the order is the contract):

1. **Version gate first.**  With ``--expect-cache-version``, the worker
   computes its own :func:`csmom_tpu.serve.health.aot_cache_version` and
   on mismatch REFUSES to serve: a pointed message on stderr and exit
   ``RC_VERSION_SKEW`` — before any warm, so version skew between the
   router's deploy and this worker's code can never become a fresh
   compile inside the serving window.
2. **Cold-cache honesty.**  With ``--require-warm-cache`` (the jax
   engine's default in pool mode), :func:`health.cache_readiness` must
   pass before warming begins; otherwise exit ``RC_COLD_CACHE`` pointing
   at ``csmom warmup --profiles serve``.  Warm-before-ready is only
   cheap when the serialized-executable cache is the deploy artifact.
3. **Liveness before readiness.**  The socket binds and answers ``ping``
   immediately; ``ready`` reports ``ok: false, reason: warming`` until
   the service has warmed every bucket shape AND served one self-probe
   request per endpoint end-to-end — readiness is demonstrated, never
   declared.

Chaos: the service's ``serve.admit``/``serve.coalesce``/
``serve.dispatch`` checkpoints all fire inside this process (the fault
plan arrives by env inheritance from the supervisor), so a plan's
``kill`` at ``serve.dispatch`` is a REAL worker-process death mid-batch
— the scenario the rehearsal matrix and ``SERVE_POOL_r11.json`` pin.
``CSMOM_SERVE_WORKER_FAULT=exit:<rc>`` additionally makes the process
exit at startup (the supervisor backoff-cap rehearsals need a
deterministic crash-looper).

All timing through :func:`csmom_tpu.utils.deadline.mono_now_s` (the
time-discipline lint pins this module like the rest of serve/).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading

import numpy as np

from csmom_tpu.serve import health, proto
from csmom_tpu.registry import serve_endpoints
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["RC_COLD_CACHE", "RC_VERSION_SKEW", "WorkerServer", "main"]

RC_COLD_CACHE = 3      # AOT cache missing/stale for the selected profile
RC_VERSION_SKEW = 4    # --expect-cache-version did not match ours

# startup chaos knob (crash-loop rehearsals): "exit:<rc>" exits rc
FAULT_ENV = "CSMOM_SERVE_WORKER_FAULT"

# grace beyond a request's own deadline before the worker gives up
# waiting for a terminal state (the service guarantees terminality; this
# bounds the reply even if that guarantee breaks)
_TERMINAL_GRACE_S = 5.0
_NO_DEADLINE_WAIT_S = 30.0


class WorkerServer:
    """The socket front of one in-process :class:`SignalService`."""

    def __init__(self, socket_path: str, config, worker_id: str = "w0",
                 device_slice: str | None = None):
        from csmom_tpu.serve.service import SignalService

        self.socket_path = socket_path
        self.worker_id = worker_id
        self.service = SignalService(config)
        self.device_slice = device_slice
        self._ready_lock = threading.Lock()
        self._ready_report = {"ok": False, "reason": "warming",
                              "worker_id": worker_id,
                              "device_slice": device_slice}
        self._draining = False
        self._stop = threading.Event()
        self._listener: socket.socket | None = None
        self.cache_version: str | None = None

    # ----------------------------------------------------------- lifecycle

    def bind(self) -> None:
        """Bind + listen and start answering (liveness is up from here;
        readiness stays false until :meth:`warm_and_probe` succeeds).
        The address may be a bare unix path (r11), ``unix:/path``, or
        ``tcp:host:port`` — the r18 fabric's cross-host spelling."""
        self._listener = proto.listen(self.socket_path)
        self._listener.settimeout(0.2)
        t = threading.Thread(target=self._accept_loop,
                             name=f"csmom-worker-{self.worker_id}-accept",
                             daemon=True)
        t.start()

    def warm_and_probe(self, walls: dict | None = None) -> dict:
        """Warm every bucket shape, then demonstrate readiness: one
        self-probe request per endpoint through the full pipeline; ready
        iff all served with zero fresh compiles since the warm snapshot.

        ``walls`` carries the caller's earlier lifecycle stamps (e.g.
        ``main_to_bind_s``); this method adds its own ``warm_s`` so the
        ready report decomposes the spawn→ready wall at the source."""
        t_warm0 = mono_now_s()
        self.service.start()
        spec = self.service.spec
        A = spec.asset_buckets[0]
        rng = np.random.default_rng(0)
        probes = {}
        for kind in serve_endpoints():
            v = 100.0 * np.exp(np.cumsum(
                rng.normal(0, 0.03, (A, spec.months)), axis=1))
            req = self.service.submit(kind, v.astype(np.float32),
                                      np.ones((A, spec.months), bool),
                                      deadline_s=10.0)
            req.wait(15.0)
            probes[kind] = req.state
        fresh = self.service.fresh_compiles()
        ok = (all(s == "served" for s in probes.values())
              and (not isinstance(fresh, int) or fresh == 0))
        if self.service.engine.name == "stub":
            platform = "stub"
        else:
            import jax

            platform = jax.default_backend()
        report = {
            "ok": ok,
            "worker_id": self.worker_id,
            "pid": os.getpid(),
            "platform": platform,
            "engine": self.service.engine.name,
            "profile": spec.name,
            "cache_version": self.cache_version,
            # the pinning contract's evidence: the slice this worker's
            # engine actually built its mesh over (the supervisor's
            # rehearsal checks a replacement re-pinned its predecessor's)
            "device_slice": self.device_slice,
            "warm": self.service.warm_report,
            "probes": probes,
            "fresh_compiles": fresh,
            # spawn→bind→warm→ready decomposed at the source: the
            # supervisor's ready event copies this block, so every
            # (re)spawn's re-warm window is a measured sample even with
            # fleet capture disarmed
            "walls": dict(walls or {},
                          warm_s=round(mono_now_s() - t_warm0, 3)),
            "reason": None if ok else (
                f"self-probe states {probes}, fresh_compiles={fresh!r}"),
        }
        with self._ready_lock:
            self._ready_report = report
        return report

    def run_until_stopped(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(0.2)
        self._shutdown()

    def _shutdown(self) -> None:
        # drain before the lights go out: the SIGTERM path reaches here
        # without a "stop" op, and queued requests must still terminate
        # (idempotent when the stop op already drained)
        try:
            self.service.stop(drain=True, timeout_s=10.0)
        except Exception:
            pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        proto.unlink_address(self.socket_path)

    def stop(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: shutting down
            # one PERSISTENT connection per peer channel (ISSUE 15):
            # the serve loop demuxes many in-flight requests off it,
            # scoring each on its own thread, and a one-shot probe
            # (no _mux, closes after its reply) exits via clean EOF
            t = threading.Thread(
                target=proto.serve_connection,
                args=(conn, self._handle),
                kwargs={"on_stop": self.stop},
                daemon=True)
            t.start()

    def _handle(self, obj: dict, arrays: dict) -> tuple:
        op = obj.get("op")
        if op == "ping":
            return {"ok": True, "worker_id": self.worker_id,
                    "pid": os.getpid()}, None
        if op == "ready":
            with self._ready_lock:
                report = dict(self._ready_report)
            if self._draining:
                report["ok"] = False
                report["reason"] = "draining"
            return report, None
        if op == "stats":
            return self._stats(), None
        if op == "score":
            return self._score(obj, arrays)
        if op == "tune_quota":
            # the fleet autoscaler's quota seam (serve/fleet.py): retune
            # a class's admission bucket within the declared policy shape
            applied = self.service.queue.retune_quota(
                str(obj.get("slo_class", "")),
                float(obj.get("quota_rps") or 0.0),
                (float(obj["quota_burst"])
                 if obj.get("quota_burst") else None))
            return {"state": "ok" if applied else "rejected",
                    "ok": applied, "worker_id": self.worker_id,
                    "applied": applied}, None
        if op in ("drain", "stop"):
            self._draining = True
            self.service.stop(drain=True)
            out = self._stats()
            out["drained"] = True
            return out, None
        return {"ok": False, "error": f"unknown op {op!r}"}, None

    def _stats(self) -> dict:
        return {
            "ok": True,
            "worker_id": self.worker_id,
            "pid": os.getpid(),
            "device_slice": self.device_slice,
            "accounting": self.service.accounting(),
            "classes": self.service.class_stats(),
            "cache": self.service.cache_stats(),
            "batches": self.service.batch_stats(),
            "fresh_compiles": self.service.fresh_compiles(),
            "invariant_violations": self.service.invariant_violations(),
        }

    def _score(self, obj: dict, arrays: dict) -> tuple:
        if self._draining:
            return {"state": "rejected", "error": "worker draining",
                    "worker_id": self.worker_id}, None
        if "values" not in arrays or "mask" not in arrays:
            return {"state": "rejected",
                    "error": "score frame missing values/mask arrays",
                    "worker_id": self.worker_id}, None
        rel = obj.get("deadline_rel_s")
        pv = obj.get("panel_version")
        # a wire-carried trace context means the ROUTER is tracing this
        # request: rebuild the server half here (even with no local book
        # armed — the sampling decision propagates with the request, the
        # Dapper way) so the reply can carry a stitchable stage chain
        trace_ctx = None
        wire_trace = obj.get("trace")
        if isinstance(wire_trace, dict):
            from csmom_tpu.obs.trace import TraceContext

            trace_ctx = TraceContext.from_wire(wire_trace)
        req = self.service.submit(
            str(obj.get("kind")), arrays["values"], arrays["mask"],
            priority=str(obj.get("priority", "interactive")),
            deadline_s=float(rel) if rel is not None else None,
            panel_version=int(pv) if pv is not None else None,
            trace_ctx=trace_ctx,
        )
        wait_s = (float(rel) + _TERMINAL_GRACE_S if rel is not None
                  else _NO_DEADLINE_WAIT_S)
        if not req.wait(wait_s):
            # the service contract says this is unreachable; answering
            # anyway bounds the router's exposure to a broken worker
            return {"state": "rejected",
                    "error": "request never reached a terminal state "
                             f"within {wait_s:.1f}s (worker defect)",
                    "worker_id": self.worker_id}, None
        reply = {
            "state": req.state,
            "error": req.error,
            "worker_id": self.worker_id,
            "queue_wait_s": req.queue_wait_s,
            "service_s": req.service_s,
            # served straight from this worker's result cache: the
            # router counts these so the FABRIC's pool-level hit rate
            # survives a worker corpse (its own cache book dies with it)
            "cache_hit": bool(req.cache_hit),
            # stamped through so the router's books can reconcile which
            # panel version every response was computed from
            "panel_version": req.panel_version,
        }
        if trace_ctx is not None:
            # the server half of the stitched trace: this worker's stage
            # chain (closed by the service's terminal transition), sent
            # back as plain JSON — a SIGKILL before this line is exactly
            # the orphan half the router closes with reason
            reply["trace_half"] = trace_ctx.half_record()
        out_arrays = None
        if req.state == "served":
            if isinstance(req.result, dict):
                reply["result_obj"] = {k: float(v)
                                       for k, v in req.result.items()}
            else:
                out_arrays = {"result": np.asarray(req.result)}
        return reply, out_arrays


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="csmom_tpu.serve.worker",
        description="pool worker: SignalService behind a unix socket")
    ap.add_argument("--socket", required=True,
                    help="serve address: a unix socket path (bare or "
                         "unix:/path) or tcp:host:port")
    ap.add_argument("--worker-id", dest="worker_id", default="w0")
    ap.add_argument("--profile", default="serve")
    ap.add_argument("--engine", default="jax",
                    choices=["jax", "jax-mesh", "stub"])
    ap.add_argument("--device-slice", dest="device_slice",
                    help="pin this worker to a contiguous device slice "
                         "'<start>:<count>' (exported as "
                         "CSMOM_MESH_DEVICE_SLICE before the engine "
                         "builds; the jax-mesh engine meshes only these "
                         "devices — a replacement spawned into the same "
                         "slot re-pins the same slice)")
    ap.add_argument("--capacity", type=int, default=64)
    ap.add_argument("--max-wait-ms", dest="max_wait_ms", type=float,
                    default=10.0)
    ap.add_argument("--deadline-ms", dest="deadline_ms", type=float,
                    default=500.0)
    ap.add_argument("--expect-cache-version", dest="expect_cache_version",
                    help="refuse ready unless our computed AOT cache "
                         "version matches (the rolling-deploy skew gate)")
    ap.add_argument("--require-warm-cache", dest="require_warm_cache",
                    action="store_true",
                    help="exit nonzero when the AOT cache is cold/stale "
                         "for --profile instead of compiling at warm")
    ap.add_argument("--cache-subdir", dest="cache_subdir", default="bench",
                    help="persistent-cache namespace shared with "
                         "`csmom warmup` (default 'bench')")
    args = ap.parse_args(argv)
    t_main0 = mono_now_s()

    fault = os.environ.get(FAULT_ENV, "")
    if fault.startswith("exit:"):
        print(f"[worker {args.worker_id}] chaos {FAULT_ENV}={fault}: "
              "exiting at startup", file=sys.stderr, flush=True)
        return int(fault.split(":", 1)[1] or 1)

    mesh_devices = None
    if args.device_slice:
        from csmom_tpu.mesh.pinning import DEVICE_SLICE_ENV, \
            parse_device_slice

        try:
            _, mesh_devices = parse_device_slice(args.device_slice)
        except ValueError as e:
            print(f"[worker {args.worker_id}] --device-slice: {e}",
                  file=sys.stderr, flush=True)
            return 2
        # exported BEFORE any engine builds: the mesh variants read the
        # pinned slice from the environment (the same channel the fault
        # plans ride), so every entry this process compiles lives on
        # exactly these devices
        os.environ[DEVICE_SLICE_ENV] = args.device_slice

    if args.engine == "jax-mesh" and mesh_devices is None:
        # unpinned mesh worker: its compiled world spans every visible
        # device, and the VERSION token must say so — a restart on a
        # resized topology has to read as skew, not share a token.
        # Counting devices initializes the backend, which the warm path
        # pays moments later anyway.
        import jax

        mesh_devices = len(jax.devices())

    my_version = health.aot_cache_version(
        args.profile, engine=args.engine,
        mesh_devices=mesh_devices if args.engine == "jax-mesh" else None)
    if (args.expect_cache_version
            and args.expect_cache_version != my_version):
        print(
            f"[worker {args.worker_id}] REFUSING READY: AOT cache version "
            f"skew — supervisor expects {args.expect_cache_version}, this "
            f"worker's code computes {my_version} (bucket grid / endpoint "
            "set / engine params / jax release differ).  Serving would "
            "compile fresh shapes inside the window; redeploy matching "
            f"code and {health.WARMUP_POINTER}",
            file=sys.stderr, flush=True,
        )
        return RC_VERSION_SKEW

    if args.engine.startswith("jax") and args.require_warm_cache:
        ready, reason = health.cache_readiness(
            args.profile, args.cache_subdir,
            mesh_devices=mesh_devices if args.engine == "jax-mesh"
            else None)
        if not ready:
            print(f"[worker {args.worker_id}] NOT READY: {reason}",
                  file=sys.stderr, flush=True)
            return RC_COLD_CACHE

    if args.engine.startswith("jax"):
        # point jax at the shared serialized-executable cache BEFORE the
        # first trace, so warm() loads what `csmom warmup` compiled
        from csmom_tpu.utils.jit_cache import enable_persistent_cache

        enable_persistent_cache(args.cache_subdir, min_compile_s=0.0)

    from csmom_tpu.serve.service import ServeConfig

    cfg = ServeConfig(
        profile=args.profile, engine=args.engine, capacity=args.capacity,
        max_wait_s=args.max_wait_ms / 1e3,
        default_deadline_s=(None if args.deadline_ms in (None, 0)
                            else args.deadline_ms / 1e3),
    )
    server = WorkerServer(args.socket, cfg, worker_id=args.worker_id,
                          device_slice=args.device_slice)
    server.cache_version = my_version

    def _term(signum, frame):  # graceful drain on SIGTERM
        server.stop()

    signal.signal(signal.SIGTERM, _term)

    # join the run's fleet observatory when armed (CSMOM_FLEET inherited
    # from the supervisor's env) — sampling off the request path; a
    # disarmed env leaves this process exactly as before
    from csmom_tpu.obs import fleet as obs_fleet

    obs_fleet.arm_emitter_from_env("worker", args.worker_id)

    server.bind()
    t_bind = mono_now_s()
    t0 = mono_now_s()
    report = server.warm_and_probe(
        walls={"main_to_bind_s": round(t_bind - t_main0, 3)})
    print(f"[worker {args.worker_id}] pid {os.getpid()} "
          f"{'READY' if report['ok'] else 'NOT READY'} in "
          f"{mono_now_s() - t0:.2f}s: probes {report['probes']}, "
          f"fresh_compiles {report['fresh_compiles']!r}",
          file=sys.stderr, flush=True)
    server.run_until_stopped()
    obs_fleet.disarm_emitter("worker stopped (drained)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
