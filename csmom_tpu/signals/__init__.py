"""Signal engineering over masked panels: momentum, turnover, intraday."""

from csmom_tpu.signals.momentum import (
    formation_listed_mask,
    monthly_returns,
    momentum,
    momentum_dynamic,
    padded_prices,
    raw_monthly_returns,
)
from csmom_tpu.signals.residual import (
    residual_momentum,
    residual_momentum_sweep,
    residual_sweep_backtest,
)
from csmom_tpu.signals.turnover import (
    turnover_features,
    shares_outstanding_vector,
    volume_tercile_labels,
)

__all__ = [
    "formation_listed_mask",
    "monthly_returns",
    "padded_prices",
    "raw_monthly_returns",
    "momentum",
    "momentum_dynamic",
    "residual_momentum",
    "residual_momentum_sweep",
    "residual_sweep_backtest",
    "turnover_features",
    "shares_outstanding_vector",
    "volume_tercile_labels",
]
