"""Signal engineering over masked panels: momentum, turnover, intraday."""

from csmom_tpu.signals.momentum import monthly_returns, momentum

__all__ = ["monthly_returns", "momentum"]
