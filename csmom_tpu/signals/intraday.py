"""Intraday minute-bar features.

Reference: ``compute_intraday_features_minute``
(``/root/reference/src/features.py:110-143``): per ticker sorted by time —
1-minute return, rolling-5 return sum, tick-rule signed volume, rolling-30
volume sums, and a volume z-score against rolling-60 moments (std NaN -> 1).

Row semantics matter for parity: every reference window is over *observed
rows* of that ticker, not calendar minutes — a ticker missing a minute simply
has a shorter series (the shipped caches range 2,597-2,729 bars per name).
So features are computed on a **compacted layout** ``[A, R]``: row j of
asset a is a's j-th observed bar, padded to the max row count, with
``row_valid[a, j] = j < n_rows[a]``.  Windows become plain contiguous
trailing windows (the masked rolling kernels), exactly matching pandas
``groupby(ticker).rolling``.  A companion ``time_idx[A, R]`` maps each row
back to the global minute axis for the event engine.

The compaction itself is one argsort per asset done host-side at ingest; all
feature math is jit on TPU.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from csmom_tpu.ops.rolling import rolling_sum, rolling_mean, rolling_std


@dataclasses.dataclass(frozen=True)
class CompactMinutePanel:
    """Per-asset compacted minute bars + mapping to the global minute axis."""

    price: np.ndarray     # f[A, R]
    volume: np.ndarray    # f[A, R]
    time_idx: np.ndarray  # i32[A, R] global minute index of each row
    row_valid: np.ndarray # bool[A, R]
    tickers: tuple
    times: np.ndarray     # datetime64[T] global minute axis (union)

    @property
    def n_rows(self):
        return self.row_valid.sum(axis=1)


def compact_minutes(df, tickers=None) -> CompactMinutePanel:
    """Long intraday frame -> compacted per-asset row layout.

    ``df`` columns: datetime, ticker, price, volume (canonical intraday
    schema).  Host-side; runs once per dataset.
    """
    if tickers is None:
        tickers = sorted(df["ticker"].unique())
    times = np.sort(df["datetime"].unique())

    groups = {t: g.sort_values("datetime") for t, g in df.groupby("ticker")}
    R = max((len(g) for g in groups.values()), default=0)
    A = len(tickers)
    price = np.full((A, R), np.nan)
    volume = np.full((A, R), np.nan)
    time_idx = np.zeros((A, R), dtype=np.int32)
    row_valid = np.zeros((A, R), dtype=bool)
    for a, t in enumerate(tickers):
        g = groups.get(t)
        if g is None:
            continue
        n = len(g)
        price[a, :n] = g["price"].values
        volume[a, :n] = g["volume"].values
        time_idx[a, :n] = np.searchsorted(times, g["datetime"].values)
        row_valid[a, :n] = True
    return CompactMinutePanel(
        price=price, volume=volume, time_idx=time_idx, row_valid=row_valid,
        tickers=tuple(tickers), times=times,
    )


FEATURE_NAMES = ("ret_1m", "ret_5m", "vol_roll_sum", "vol_zscore", "signed_vol_roll")


@partial(jax.jit, static_argnames=("window",))
def minute_features(price, volume, row_valid, window: int = 30):
    """All reference minute features over a compacted [A, R] layout.

    Returns:
      features: f[A, R, 5] in FEATURE_NAMES order.
      feat_valid: bool[A, R] rows where every feature is defined (the panel
        equivalent of the driver's ``feats.dropna()`` at ``run_demo.py:127``
        — in practice each asset's first row, where ret_1m is NaN).
    """
    prev_p = jnp.roll(price, 1, axis=1)
    prev_valid = jnp.roll(row_valid, 1, axis=1).at[:, 0].set(False)
    ret_valid = row_valid & prev_valid
    ret_1m = jnp.where(ret_valid, price / jnp.where(ret_valid, prev_p, 1.0) - 1.0, jnp.nan)

    ret_5m, ret5_valid = rolling_sum(ret_1m, ret_valid, 5, 1)

    # tick rule: sign of the price change, 0 on the first row (fillna(0),
    # features.py:128); the zero IS a valid observation for the rolling sum
    tick = jnp.where(ret_valid, jnp.sign(price - prev_p), 0.0)
    signed_vol = tick * volume
    signed_vol = jnp.where(row_valid, jnp.nan_to_num(signed_vol), jnp.nan)

    vol_roll, _ = rolling_sum(volume, row_valid, window, 1)
    signed_roll, _ = rolling_sum(signed_vol, row_valid, window, 1)

    v_mean, _ = rolling_mean(vol_roll, row_valid, 60, 1)
    v_std, v_std_valid = rolling_std(vol_roll, row_valid, 60, 1, ddof=1)
    v_std = jnp.where(v_std_valid, v_std, 1.0)  # std NaN -> 1.0 (features.py:135)
    zscore = (vol_roll - v_mean) / v_std

    features = jnp.stack([ret_1m, ret_5m, vol_roll, zscore, signed_roll], axis=-1)
    feat_valid = row_valid & ret_valid & ret5_valid
    return features, feat_valid


@jax.jit
def next_row_return(price, feat_valid):
    """Training label: next-row return over *surviving* rows.

    The driver computes ``shift(-1)`` per ticker *after* dropping NaN feature
    rows (``run_demo.py:129-131``), i.e. over the compacted surviving-row
    sequence.  Survivors are a contiguous tail per asset (row 0 is the only
    casualty), so the next surviving row is simply row j+1.

    Returns (y f[A, R], y_valid bool[A, R]); the last surviving row of each
    asset is invalid (its next_ret would be NaN and is dropped, run_demo:131).
    """
    nxt_p = jnp.roll(price, -1, axis=1)
    nxt_valid = jnp.roll(feat_valid, -1, axis=1).at[:, -1].set(False)
    y_valid = feat_valid & nxt_valid
    y = jnp.where(y_valid, nxt_p / jnp.where(y_valid, price, 1.0) - 1.0, jnp.nan)
    return y, y_valid
