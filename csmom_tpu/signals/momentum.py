"""Momentum signals on monthly panels.

Reference semantics (``/root/reference/src/features.py:5-57``): from month-end
prices, ``ret_1m = pct_change`` per asset, then ``mom_J`` = shift by
``skip`` months followed by a rolling-J compounded product
``prod(1+r) - 1`` evaluated with a Python lambda per window — the hottest
signal loop in the reference (SURVEY §3.2).

Panel form: the window product telescopes, so the compounded (J, skip)
momentum is a single gather-and-divide::

    mom[a, t] = price[a, t-skip] / price[a, t-skip-J] - 1

valid iff every monthly return inside the window exists.  That validity rule
reproduces the reference's NaN semantics exactly on per-asset contiguous
histories: pandas' ``min_periods=1`` never actually emits an early value
because the leading ``pct_change`` NaN poisons every truncated window
(measured in SURVEY §2.1.2: first valid ``mom_J`` lands at month
J+skip+1), and an interior missing month poisons the windows covering it
just like NaN propagates through ``np.prod``.

No Python per-window work, no scan: O(A*T) elementwise ops + one prefix
sum for the validity count — embarrassingly parallel along assets, which is
what lets the asset axis shard cleanly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def monthly_returns(prices, mask):
    """1-month simple returns per asset (``features.py:44``).

    Args:
      prices: f[A, M] month-end price panel (NaN at masked slots).
      mask:   bool[A, M].

    Returns:
      (ret f[A, M], ret_valid bool[A, M]) — slot t holds
      ``prices[t]/prices[t-1] - 1``; the first month of each asset is invalid.
    """
    prev = jnp.roll(prices, 1, axis=1)
    prev_mask = jnp.roll(mask, 1, axis=1).at[:, 0].set(False)
    valid = mask & prev_mask & (prev != 0.0)
    ret = jnp.where(valid, prices / jnp.where(valid, prev, 1.0) - 1.0, jnp.nan)
    return ret, valid


@partial(jax.jit, static_argnames=("lookback", "skip"))
def momentum(prices, mask, lookback: int = 12, skip: int = 1):
    """Compounded (J, skip) momentum via the telescoped price ratio.

    Args:
      prices: f[A, M] month-end prices.
      mask: bool[A, M].
      lookback: J, number of months compounded.
      skip: months skipped between the window end and formation date
        (the Jegadeesh–Titman reversal-avoidance month).

    Returns:
      (mom f[A, M], mom_valid bool[A, M]) — ``mom[:, t]`` is the signal used
      to form the portfolio held over month t+1.
    """
    return momentum_dynamic(prices, mask, lookback, skip)


def momentum_dynamic(prices, mask, lookback, skip):
    """``momentum`` with *traced* (lookback, skip) scalars.

    The telescoped-ratio formulation only uses J and skip in index
    arithmetic, so the lookback can be a traced value — which is what lets
    the whole J x K parameter grid run as one ``vmap`` over a vector of Js
    instead of one compilation per cell.
    """
    _, ret_valid = monthly_returns(prices, mask)
    A, M = prices.shape
    t = jnp.arange(M)

    # window of monthly returns entering the product: [t-skip-J+1, t-skip]
    hi = t - skip
    lo = t - skip - lookback
    in_range = lo >= 0

    # all J returns in the window must exist (NaN poisoning parity)
    bad = (~ret_valid).astype(jnp.int32)
    badc = jnp.concatenate(
        [jnp.zeros((A, 1), jnp.int32), jnp.cumsum(bad, axis=1)], axis=1
    )
    hi_c = jnp.clip(hi, 0, M - 1)
    lo_c = jnp.clip(lo + 1, 0, M - 1)
    window_bad = badc[:, hi_c + 1] - badc[:, lo_c]

    p_hi = prices[:, hi_c]
    p_lo = prices[:, jnp.clip(lo, 0, M - 1)]
    valid = in_range[None, :] & (window_bad == 0) & (p_lo != 0.0)
    mom = jnp.where(valid, p_hi / jnp.where(valid, p_lo, 1.0) - 1.0, jnp.nan)
    return mom, valid
