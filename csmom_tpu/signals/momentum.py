"""Momentum signals on monthly panels.

Reference semantics (``/root/reference/src/features.py:5-57``): from month-end
prices, ``ret_1m = pct_change`` per asset, then ``mom_J`` = shift by
``skip`` months followed by a rolling-J compounded product
``prod(1+r) - 1`` evaluated with a Python lambda per window — the hottest
signal loop in the reference (SURVEY §3.2).

The reference's ``pct_change()`` runs with pandas' default
``fill_method='pad'``: prices are forward-filled before differencing, so an
interior missing month carries the last observed price (its return is 0.0,
not NaN) and a delisted asset keeps a 0-return tail.  Only the months
*before* an asset's first observation stay NaN.  The panel kernels reproduce
that exactly by forward-filling along the time axis (:func:`padded_prices`)
and keying validity off "has the asset been observed yet", not off the raw
observation mask.

Panel form: the window product telescopes on the filled prices, so the
compounded (J, skip) momentum is a single gather-and-divide::

    mom[a, t] = filled[a, t-skip] / filled[a, t-skip-J] - 1

valid iff every (padded) monthly return inside the window exists — i.e. the
window opens at or after the asset's first observation.  That reproduces the
reference's NaN semantics: pandas' ``min_periods=1`` never actually emits an
early value because the leading ``pct_change`` NaN poisons every truncated
window (measured in SURVEY §2.1.2: first valid ``mom_J`` lands at month
J+skip+1 after the asset's first observation).

The grid/backtest drivers (``run_demo.py``) instead build the signal from
*raw* shifted prices (``prices.shift(skip)/prices.shift(skip+J) - 1``), which
additionally drops an asset from every formation date after its delisting;
:func:`formation_listed_mask` expresses that extra requirement for the
engines without changing this module's rolling-product parity.

No Python per-window work, no scan: O(A*T) elementwise ops + one prefix
sum for the validity count — embarrassingly parallel along assets, which is
what lets the asset axis shard cleanly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def padded_prices(prices, mask):
    """Forward-filled price panel (pandas ``fill_method='pad'`` parity).

    Args:
      prices: f[A, M] month-end price panel (NaN at masked slots).
      mask:   bool[A, M] raw observation mask.

    Returns:
      (filled f[A, M], seen bool[A, M]) — ``filled[a, t]`` is the last
      observed price at or before t (NaN before the asset's first
      observation); ``seen[a, t]`` marks slots with at least one observation
      at or before t.
    """
    M = prices.shape[1]
    idx = jnp.arange(M)
    last = jax.lax.cummax(jnp.where(mask, idx, -1), axis=1)
    seen = last >= 0
    filled = jnp.take_along_axis(
        jnp.where(mask, prices, jnp.nan), jnp.clip(last, 0, M - 1), axis=1
    )
    return jnp.where(seen, filled, jnp.nan), seen


@jax.jit
def monthly_returns(prices, mask):
    """1-month simple returns per asset (``features.py:44``).

    Pandas-pad parity: returns are differences of the forward-filled panel,
    so a gap month yields 0.0 (price carried) and a delisted asset a
    0-return tail; only slots before the asset's first observation (plus the
    first month) are invalid.

    Args:
      prices: f[A, M] month-end price panel (NaN at masked slots).
      mask:   bool[A, M].

    Returns:
      (ret f[A, M], ret_valid bool[A, M]) — slot t holds
      ``filled[t]/filled[t-1] - 1``.
    """
    filled, seen = padded_prices(prices, mask)
    prev = jnp.roll(filled, 1, axis=1)
    prev_seen = jnp.roll(seen, 1, axis=1).at[:, 0].set(False)
    # seen is monotone along time, so prev_seen alone implies seen
    valid = prev_seen & (prev != 0.0)
    ret = jnp.where(valid, filled / jnp.where(valid, prev, 1.0) - 1.0, jnp.nan)
    return ret, valid


@jax.jit
def raw_monthly_returns(prices, mask):
    """Adjacent-months returns on the *raw* (un-padded) panel.

    ``ret[t] = prices[t]/prices[t-1] - 1`` with both month-ends observed,
    NaN otherwise — a missing month drops out of that asset's windows
    instead of carrying the last price forward.  This is the contract the
    residual-momentum OLS windows and the low-volatility rolling std build
    on (full masked windows, pandas ``rolling`` NaN-skipping); portfolio
    next-month returns use :func:`monthly_returns` (pad parity) instead.
    """
    prev = jnp.roll(prices, 1, axis=1)
    prev_mask = jnp.roll(mask, 1, axis=1).at[:, 0].set(False)
    valid = mask & prev_mask & (prev != 0.0)
    ret = jnp.where(valid, prices / jnp.where(valid, prev, 1.0) - 1.0, jnp.nan)
    return ret, valid


@partial(jax.jit, static_argnames=("lookback", "skip"))
def momentum(prices, mask, lookback: int = 12, skip: int = 1):
    """Compounded (J, skip) momentum via the telescoped price ratio.

    Args:
      prices: f[A, M] month-end prices.
      mask: bool[A, M].
      lookback: J, number of months compounded.
      skip: months skipped between the window end and formation date
        (the Jegadeesh–Titman reversal-avoidance month).

    Returns:
      (mom f[A, M], mom_valid bool[A, M]) — ``mom[:, t]`` is the signal used
      to form the portfolio held over month t+1.
    """
    return momentum_dynamic(prices, mask, lookback, skip)


def momentum_dynamic(prices, mask, lookback, skip):
    """``momentum`` with *traced* (lookback, skip) scalars.

    The telescoped-ratio formulation only uses J and skip in index
    arithmetic, so the lookback can be a traced value — which is what lets
    the whole J x K parameter grid run as one ``vmap`` over a vector of Js
    instead of one compilation per cell.
    """
    _, ret_valid = monthly_returns(prices, mask)
    filled, _ = padded_prices(prices, mask)
    A, M = prices.shape
    t = jnp.arange(M)

    # window of monthly returns entering the product: [t-skip-J+1, t-skip]
    hi = t - skip
    lo = t - skip - lookback
    in_range = lo >= 0

    # all J (padded) returns in the window must exist — equivalently the
    # window opens at or after the asset's first observation
    bad = (~ret_valid).astype(jnp.int32)
    badc = jnp.concatenate(
        [jnp.zeros((A, 1), jnp.int32), jnp.cumsum(bad, axis=1)], axis=1
    )
    hi_c = jnp.clip(hi, 0, M - 1)
    lo_c = jnp.clip(lo + 1, 0, M - 1)
    window_bad = badc[:, hi_c + 1] - badc[:, lo_c]

    p_hi = filled[:, hi_c]
    p_lo = filled[:, jnp.clip(lo, 0, M - 1)]
    valid = in_range[None, :] & (window_bad == 0) & (p_lo != 0.0)
    mom = jnp.where(valid, p_hi / jnp.where(valid, p_lo, 1.0) - 1.0, jnp.nan)
    return mom, valid


def formation_listed_mask(mask, skip):
    """bool[A, M]: the asset is still listed at the formation window's end.

    The reference's backtest drivers form the signal as
    ``prices.shift(skip) / prices.shift(skip+J) - 1`` on the *raw* panel
    (``run_demo.py:31-45``): once an asset's history ends (delisting), the
    shifted raw price is NaN and the asset drops out of every later
    formation date — even though the padded rolling-product signal would
    carry a value through.  The engines AND this mask into the padded
    momentum validity to reproduce that: an asset is ranked only while an
    observation exists at or after the window-end month ``t - skip`` (its
    last formation date is the month after its final print).  An *interior*
    gap does not un-list an asset — pad semantics carry it — which is what
    keeps scattered-hole panels identical to the plain padded signal.

    ``skip`` may be traced (the engines run under jit).
    """
    M = mask.shape[1]
    idx = jnp.arange(M)
    last = jnp.max(jnp.where(mask, idx, -1), axis=1)  # [A] final print
    hi = idx - skip  # unclipped: hi < 0 is pre-history, V_pad already bars it
    return last[:, None] >= hi[None, :]
