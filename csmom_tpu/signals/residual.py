"""Residual (idiosyncratic) momentum — Blitz, Huij & Martens (2011).

Plain momentum loads on market beta: a 12-month winner portfolio is long
high-beta names after an up market, so much of its risk is factor risk.
Residual momentum ranks instead on the trailing performance of each
stock's market-model *residuals* — momentum that survives after hedging
the market leg — which the literature finds carries similar premium at
roughly half the volatility.  The reference framework has no model-based
signals at all (its one signal is raw ``mom_J``,
``/root/reference/src/features.py:47-52``); this is the extension a
quant user builds next, and it exercises the Strategy plugin boundary
with real computation.

TPU-first form — closed-form rolling OLS, zero per-window work:

For each asset i and formation month t the score needs a market-model
regression of r_i on the (equal-weight) market return m over the trailing
``est_window`` months, then the mean/std of the residuals over the last
``lookback`` months (both windows ending at t - ``skip``).  Every moment
involved — Σr, Σm, Σrm, Σm², Σr², and the valid-month counts, per asset,
over both window lengths — is a rolling masked sum, i.e. one cumulative
sum and one shifted difference over the month axis.  The OLS
coefficients, residual sums, and residual sum-of-squares then come out of
those moments algebraically::

    beta  = (n·Σrm − Σr·Σm) / (n·Σm² − (Σm)²)
    alpha = (Σr − beta·Σm) / n
    Σe    = Σr − n·alpha − beta·Σm                (formation window)
    Σe²   = Σr² − 2a·Σr − 2b·Σrm + n·a² + 2ab·Σm + b²·Σm²

so the whole panel signal is ~a dozen fused elementwise ops over
``f[A, M]`` arrays — no lax.scan, no gather, nothing data-dependent.

A masked month drops out of *that asset's* regression and formation
window (its market return still exists for other assets); validity
requires every month of both windows present, mirroring the NaN-poisoning
warmup semantics of the price-momentum kernel
(:mod:`csmom_tpu.signals.momentum`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.ops.rolling import _windowed_prefix_diff
from csmom_tpu.signals.momentum import monthly_returns, raw_monthly_returns


def _residual_score(prices, mask, lookback, skip: int, est_window,
                    scale_by_vol: bool):
    """Body of :func:`residual_momentum` with possibly-*traced* window
    scalars.  ``lookback`` / ``est_window`` enter only through prefix-sum
    gather indices and count comparisons, so a whole (J, W) parameter grid
    can run as nested ``vmap``s over one trace — the same trick as
    ``momentum_dynamic``.  A misconfigured traced cell (est_window <
    lookback or < 3) comes back all-invalid rather than raising."""
    dt = prices.dtype
    A, M = prices.shape
    r, r_valid = raw_monthly_returns(prices, mask)
    rf = jnp.where(r_valid, jnp.nan_to_num(r), 0.0)
    v = r_valid.astype(dt)

    # equal-weight market return per month (masked cross-sectional mean)
    n_xs = jnp.sum(v, axis=0)
    m = jnp.sum(rf, axis=0) / jnp.maximum(n_xs, 1.0)
    m_row = jnp.broadcast_to(m[None, :], (A, M))
    mv = m_row * v  # market return where THIS asset has a return

    # rolling masked moments over both window lengths, per asset (trailing
    # prefix-sum differences — the shared kernel from ops.rolling)
    def moments(window):
        return {
            "n": _windowed_prefix_diff(v, window),
            "r": _windowed_prefix_diff(rf, window),
            "m": _windowed_prefix_diff(mv, window),
            "rm": _windowed_prefix_diff(rf * m_row, window),
            "mm": _windowed_prefix_diff(mv * m_row, window),
            "rr": _windowed_prefix_diff(rf * rf, window),
        }

    E = moments(est_window)   # estimation window (OLS)
    F = moments(lookback)     # formation window (residual mean/std)

    # OLS on the estimation window; a traced cell with est_window <
    # max(lookback, 3) is structurally invalid rather than an error
    denom = E["n"] * E["mm"] - E["m"] ** 2
    ok_cfg = jnp.asarray(est_window) >= jnp.maximum(jnp.asarray(lookback), 3)
    ok_reg = (E["n"] >= est_window) & (denom > 0) & ok_cfg
    safe_denom = jnp.where(ok_reg, denom, 1.0)
    beta = (E["n"] * E["rm"] - E["r"] * E["m"]) / safe_denom
    alpha = (E["r"] - beta * E["m"]) / jnp.maximum(E["n"], 1.0)

    # residual moments on the formation window under (alpha, beta)
    sum_e = F["r"] - F["n"] * alpha - beta * F["m"]
    sum_ee = (
        F["rr"]
        - 2.0 * alpha * F["r"]
        - 2.0 * beta * F["rm"]
        + F["n"] * alpha**2
        + 2.0 * alpha * beta * F["m"]
        + beta**2 * F["mm"]
    )
    nf = jnp.maximum(F["n"], 1.0)
    mean_e = sum_e / nf
    var_e = jnp.maximum(sum_ee / nf - mean_e**2, 0.0)

    # shift so the score at t reads windows ending at t - skip
    def lag(x):
        return jnp.pad(x, ((0, 0), (skip, 0)))[:, :M] if skip else x

    mean_e, var_e = lag(mean_e), lag(var_e)
    # lag() pads with False, so columns [:skip] are already invalid
    ok = lag(ok_reg & (F["n"] >= lookback))
    ok = ok & mask  # score only where the asset is currently observed

    if scale_by_vol:
        sd = jnp.sqrt(var_e)
        ok = ok & (sd > 0)
        score = mean_e / jnp.where(ok, sd, 1.0)
    else:
        score = mean_e
    return jnp.where(ok, score, jnp.nan), ok


@partial(jax.jit, static_argnames=("lookback", "skip", "est_window",
                                   "scale_by_vol"))
def residual_momentum(
    prices,
    mask,
    lookback: int = 12,
    skip: int = 1,
    est_window: int = 36,
    scale_by_vol: bool = True,
):
    """Market-model residual momentum score per (asset, month).

    Args:
      prices: f[A, M] month-end price panel (NaN at masked slots).
      mask: bool[A, M].
      lookback: formation months J whose residuals are averaged.
      skip: most-recent months excluded (both windows end at t - skip).
      est_window: trailing months for the per-asset market-model OLS;
        must be >= lookback (the formation window is its tail) and >= 3.
      scale_by_vol: divide the mean residual by the formation-window
        residual std (the paper's volatility-scaled "iMom" variant);
        ``False`` ranks on the raw residual mean.

    Returns:
      ``(score f[A, M], valid bool[A, M])`` — valid requires every month
      of the estimation window observed for that asset and a
      well-conditioned regression (non-degenerate market variance).
    """
    if est_window < max(lookback, 3):
        raise ValueError(
            f"est_window={est_window} must be >= max(lookback, 3)="
            f"{max(lookback, 3)}"
        )
    return _residual_score(prices, mask, lookback, skip, est_window,
                           scale_by_vol)


@partial(jax.jit, static_argnames=("skip", "scale_by_vol"))
def residual_momentum_sweep(
    prices,
    mask,
    lookbacks,
    est_windows,
    skip: int = 1,
    scale_by_vol: bool = True,
):
    """Every (lookback, est_window) residual-momentum score in one call.

    The window lengths enter :func:`_residual_score` only as traced
    scalars, so the whole hyperparameter grid is two nested ``vmap``s over
    one trace — no per-cell compilation, the direct analogue of the J x K
    momentum grid.

    Returns ``(scores f[nJ, nW, A, M], valid bool[nJ, nW, A, M])``; cells
    with ``est_window < max(lookback, 3)`` are all-invalid.
    """
    lookbacks = jnp.asarray(lookbacks)
    est_windows = jnp.asarray(est_windows)

    def cell(J, W):
        return _residual_score(prices, mask, J, skip, W, scale_by_vol)

    return jax.vmap(lambda J: jax.vmap(lambda W: cell(J, W))(est_windows))(
        lookbacks
    )


@partial(jax.jit, static_argnames=("skip", "scale_by_vol", "n_bins", "mode",
                                   "freq"))
def residual_sweep_backtest(
    prices,
    mask,
    lookbacks,
    est_windows,
    skip: int = 1,
    scale_by_vol: bool = True,
    n_bins: int = 10,
    mode: str = "rank",
    freq: int = 12,
):
    """Decile backtest of the full (lookback, est_window) residual grid.

    One compiled call: sweep scores (nested vmap), per-cell decile labels,
    and the shared monthly-engine tail per cell.  Returns a
    :class:`csmom_tpu.backtest.grid.GridResult` with the ``nK`` axis
    reinterpreted as the ``est_window`` axis (1-month holding throughout,
    so ``tstat_nw`` uses the auto bandwidth, not a holding-period lag) —
    every GridResult consumer (tables, batched tearsheets) works on it
    unchanged.
    """
    from csmom_tpu.backtest.grid import GridResult
    from csmom_tpu.backtest.monthly import _assemble_result
    from csmom_tpu.ops.ranking import decile_assign_panel

    scores, valid = residual_momentum_sweep(
        prices, mask, lookbacks, est_windows, skip=skip,
        scale_by_vol=scale_by_vol,
    )
    r, r_valid = monthly_returns(prices, mask)

    def cell(score, ok):
        labels, _ = decile_assign_panel(score, ok, n_bins, mode=mode)
        return _assemble_result(r, r_valid, labels, n_bins, freq)

    res = jax.vmap(jax.vmap(cell))(scores, valid)
    return GridResult(
        spreads=res.spread,
        spread_valid=res.spread_valid,
        mean_spread=res.mean_spread,
        ann_sharpe=res.ann_sharpe,
        tstat=res.tstat,
        tstat_nw=res.tstat_nw,
    )
