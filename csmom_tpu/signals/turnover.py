"""Turnover features — the Lee–Swaminathan volume leg.

Reference: ``compute_monthly_turnover`` (``/root/reference/src/features.py:
60-107``): ``adv_est = monthly_volume / 21``; shares outstanding from a
per-ticker info map with a market-cap/price fallback; ``turnover_monthly =
adv_est / shares_outstanding`` (guarded); ``turn_avg`` = rolling
``lookback``-month mean.  The reference computes these and never uses them
(SURVEY §2 row 6) — they are the hook for the paper's momentum x volume
double sort (LeSw00 Table II: momentum spreads within low/mid/high-turnover
terciles), implemented in ``csmom_tpu.backtest.double_sort``.

Panel form: shares_outstanding becomes an ``f[A]`` vector (or ``f[A, M]``
panel when time-varying data exists), everything else is elementwise +
masked rolling means.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.ops.rolling import rolling_mean
from csmom_tpu.ops.ranking import decile_assign_panel

TRADING_DAYS_PER_MONTH = 21.0  # reference constant (features.py:79)


def shares_outstanding_vector(tickers, shares_info: dict, last_price=None):
    """Resolve per-asset shares outstanding from an info map.

    Mirrors ``features.py:82-99``: prefer ``shares_outstanding``; fall back
    to ``market_cap / price`` (int-truncated, like the reference) when a last
    price is available; NaN otherwise.  Host-side helper (runs once).
    """
    import numpy as np

    out = np.full(len(tickers), np.nan)
    for i, t in enumerate(tickers):
        info = (shares_info or {}).get(t) or {}
        so = info.get("shares_outstanding")
        if so is not None and not (isinstance(so, float) and np.isnan(so)):
            out[i] = float(so)
            continue
        mcap = info.get("market_cap")
        price = None if last_price is None else last_price[i]
        # NaN mcap is truthy; int(NaN/price) raises — swallow like the
        # reference's try/except (features.py:93-96) and leave NaN
        try:
            if mcap and price and price > 0 and np.isfinite(mcap):
                out[i] = float(int(mcap / price))
        except (ValueError, OverflowError, TypeError):
            pass
    return out


@partial(jax.jit, static_argnames=("lookback",))
def turnover_features(monthly_volume, volume_mask, shares_outstanding, lookback: int = 3):
    """adv_est / turnover_monthly / turn_avg panels.

    Args:
      monthly_volume: f[A, M] summed monthly share volume.
      volume_mask: bool[A, M] months with >=1 daily bar.
      shares_outstanding: f[A] (NaN when unknown).
      lookback: rolling window for ``turn_avg`` (reference default 3).

    Returns dict of (value, valid) pairs.
    """
    adv = monthly_volume / TRADING_DAYS_PER_MONTH
    so = shares_outstanding[:, None]
    so_ok = jnp.isfinite(so) & (so > 0)
    turn_valid = volume_mask & so_ok
    turn = jnp.where(turn_valid, adv / jnp.where(so_ok, so, 1.0), jnp.nan)
    turn_avg, turn_avg_valid = rolling_mean(turn, turn_valid, lookback, 1)
    return {
        "adv_est": (adv, volume_mask),
        "turnover_monthly": (turn, turn_valid),
        "turn_avg": (turn_avg, turn_avg_valid),
    }


@partial(jax.jit, static_argnames=("n_vol_bins", "mode"))
def volume_tercile_labels(turn_avg, turn_valid, n_vol_bins: int = 3, mode: str = "qcut"):
    """Per-date volume-tercile labels for the LeSw double sort (V1/V2/V3)."""
    return decile_assign_panel(turn_avg, turn_valid, n_bins=n_vol_bins, mode=mode)
