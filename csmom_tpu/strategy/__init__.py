"""Strategy plugin boundary: pluggable cross-sectional signals over one
shared ranking/portfolio engine (both backends).  See ``base.py``."""

from csmom_tpu.strategy.base import (
    Strategy,
    available_strategies,
    consumed_panels,
    make_strategy,
    register_strategy,
    xs_zscore,
)
from csmom_tpu.strategy.builtin import (
    LowVolatility,
    FiftyTwoWeekHigh,
    IntermediateMomentum,
    Momentum,
    ResidualMomentum,
    Reversal,
    VolumeZMomentum,
    ZScoreCombo,
)
from csmom_tpu.strategy.engine import strategy_backtest, strategy_backtest_pandas

__all__ = [
    "Strategy",
    "available_strategies",
    "consumed_panels",
    "make_strategy",
    "register_strategy",
    "xs_zscore",
    "FiftyTwoWeekHigh",
    "IntermediateMomentum",
    "LowVolatility",
    "Momentum",
    "ResidualMomentum",
    "Reversal",
    "VolumeZMomentum",
    "ZScoreCombo",
    "strategy_backtest",
    "strategy_backtest_pandas",
]
