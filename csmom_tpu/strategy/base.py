"""The ``Strategy`` plugin boundary.

The reference hardwires its one signal into the driver: the decile sort at
``/root/reference/run_demo.py:46`` ranks the ``mom_J`` column produced by
``compute_monthly_momentum_from_daily`` and nothing else can be ranked
without editing the driver.  The north star (BASELINE.json) requires the
accelerated engines to land *behind a Strategy plugin boundary* so the CLI,
results schema, and analytics never change when the signal does.

A :class:`Strategy` is a frozen, hashable dataclass whose :meth:`signal`
is a pure JAX function over the masked month-end panel::

    score, valid = strategy.signal(prices, mask, **panels)

``prices``/``mask`` are the ``f[A, M]`` / ``bool[A, M]`` panel pair; extra
named panels (e.g. ``volumes``) are passed through by the engine.  Because
strategies are hashable they ride as static jit arguments: each strategy
(with its parameters) compiles once, and the engine's ranking/portfolio
tail is shared by every strategy on both backends.

User plugins register with :func:`register_strategy` and become available
to the CLI/config layer by name via :func:`make_strategy`.
"""

from __future__ import annotations

import abc
import dataclasses

import jax.numpy as jnp

__all__ = [
    "Strategy",
    "register_strategy",
    "make_strategy",
    "available_strategies",
    "consumed_panels",
    "xs_zscore",
]


@dataclasses.dataclass(frozen=True)
class Strategy(abc.ABC):
    """Base class for cross-sectional strategies (frozen == jit-static)."""

    @abc.abstractmethod
    def signal(self, prices, mask, **panels):
        """Formation-date scores over the panel.

        Args:
          prices: f[A, M] month-end prices (NaN at masked slots).
          mask: bool[A, M] observation mask.
          **panels: extra named data panels (engine passes them through
            verbatim; a strategy uses what it needs and ignores the rest).

        Returns:
          ``(score f[A, M], valid bool[A, M])`` — higher score = ranked
          into a higher decile (long leg).  Invalid slots are excluded
          from the cross-sectional sort, like the reference's NaN
          ``mom_J`` rows dropped at ``run_demo.py:41``.
        """


def consumed_panels(strategy) -> frozenset:
    """Names of extra panels a strategy's ``signal`` can actually read.

    Union of (a) the explicit keyword parameters of its ``signal`` method
    besides ``prices``/``mask`` (the ``**panels`` catch-all does not count —
    it exists so strategies can ignore panels other strategies need) and
    (b) an optional ``panel_names`` attribute for composites that forward
    panels to components.  The engine uses this to reject forwarded panels
    that match nothing — a misspelled ``volumes_maks=`` must fail loudly,
    not be silently swallowed by the catch-all.
    """
    import inspect

    params = inspect.signature(type(strategy).signal).parameters
    names = {
        n
        for n, p in params.items()
        if n not in ("self", "prices", "mask")
        and p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
    }
    names |= set(getattr(strategy, "panel_names", ()))
    return frozenset(names)


def register_strategy(name: str):
    """Class decorator: expose a Strategy to the CLI/config layer by name.

    The backing table is the unified engine registry (ISSUE 9): a
    strategy registers once as a kind-``strategy`` engine and the
    CLI/config zoo, ``csmom registry list``, and any future surface all
    read the same row — there is no separate plugin dict to drift.
    """

    def deco(cls):
        if not (isinstance(cls, type) and issubclass(cls, Strategy)):
            raise TypeError(f"{cls!r} is not a Strategy subclass")
        from csmom_tpu.registry.core import REGISTRY, EngineSpec

        doc = (cls.__doc__ or "").strip().splitlines()
        REGISTRY.register(EngineSpec(
            name=name, kind="strategy", strategy_cls=cls,
            description=doc[0] if doc else "",
            axes="prices f[A,M], mask bool[A,M] -> (score, valid)",
        ), replace=True)
        return cls

    return deco


def make_strategy(name: str, **params) -> Strategy:
    """Instantiate a registered strategy by name with keyword params."""
    zoo = available_strategies()
    try:
        cls = zoo[name]
    except KeyError:
        raise KeyError(
            f"unknown strategy {name!r}; available: {sorted(zoo)}"
        ) from None
    return cls(**params)


def available_strategies() -> dict[str, type[Strategy]]:
    from csmom_tpu.registry import strategies

    return strategies()


def xs_zscore(score, valid):
    """Cross-sectional z-score per date over the masked asset axis.

    Monotone within each date, so ranking a z-scored signal yields the same
    deciles as the raw signal — its purpose is to make *combinations* of
    signals scale-free (each component contributes in units of
    cross-sectional standard deviations).
    """
    v = valid
    n = jnp.maximum(jnp.sum(v, axis=0), 1)
    x = jnp.where(v, jnp.nan_to_num(score), 0.0)
    mu = jnp.sum(x, axis=0) / n
    var = jnp.sum(jnp.where(v, (x - mu[None, :]) ** 2, 0.0), axis=0) / n
    sd = jnp.sqrt(var)
    z = jnp.where(sd[None, :] > 0, (x - mu[None, :]) / jnp.where(sd == 0, 1.0, sd)[None, :], 0.0)
    return jnp.where(v, z, jnp.nan)
