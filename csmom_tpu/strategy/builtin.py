"""Built-in strategies.

``Momentum`` is the reference implementation — the signal the reference's
driver hardwires (``/root/reference/run_demo.py:32``: J=12, skip=1 momentum
ranked at ``:46``).  The others are standard cross-sectional signals from
the same literature, expressed over the identical panel so they demonstrate
the plugin boundary: none of them required touching an engine.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from csmom_tpu.signals.momentum import momentum
from csmom_tpu.signals.residual import residual_momentum
from csmom_tpu.strategy.base import Strategy, register_strategy, xs_zscore

__all__ = [
    "FiftyTwoWeekHigh",
    "IntermediateMomentum",
    "LowVolatility",
    "Momentum",
    "Reversal",
    "ResidualMomentum",
    "VolumeZMomentum",
    "ZScoreCombo",
]


@register_strategy("momentum")
@dataclasses.dataclass(frozen=True)
class Momentum(Strategy):
    """Compounded (J, skip) price momentum — the reference's signal
    (``features.py:5-57`` semantics; first valid value at month J+skip+1)."""

    lookback: int = 12
    skip: int = 1

    def signal(self, prices, mask, **panels):
        from csmom_tpu.signals.momentum import formation_listed_mask

        mom, valid = momentum(prices, mask, lookback=self.lookback,
                              skip=self.skip)
        # the dedicated monthly engine's delisting rule, so
        # strategy_backtest(Momentum) stays bit-identical to it on panels
        # with delistings, not only late entrants
        valid = valid & formation_listed_mask(mask, self.skip)
        return jnp.where(valid, mom, jnp.nan), valid


@register_strategy("intermediate_momentum")
@dataclasses.dataclass(frozen=True)
class IntermediateMomentum(Momentum):
    """Novy-Marx (2012, JFE 103) intermediate momentum: the return over
    months t-12..t-7 only — NM's finding is that momentum's power lives in
    this *intermediate* horizon, not the recent t-6..t-2 leg.  A pure
    reparametrization of :class:`Momentum` (``lookback=6, skip=7``),
    registered under its own name so the plugin registry — not a CLI or
    example parametrization — owns the zoo entry; first valid score at
    month ``lookback + skip + 1 = 14``, same warmup as the reference's
    J=12 signal."""

    lookback: int = 6
    skip: int = 7


@register_strategy("low_volatility")
@dataclasses.dataclass(frozen=True)
class LowVolatility(Strategy):
    """Blitz–van Vliet (2007, JPM 34) volatility effect: rank on the
    NEGATED trailing standard deviation of monthly returns, so the top
    decile is the lowest-volatility book and the spread is long-low /
    short-high vol.  A risk-sorted signal rather than a return-sorted
    one — the one zoo member whose cross-section is built from second
    moments — expressed through the same masked ``rolling_std`` kernel
    the intraday features use, so it needed no new engine code.

    ``min_obs`` months of valid returns must exist inside the trailing
    ``window`` (the paper uses 36 of 36; the default tolerates listing
    gaps the way the rest of the zoo does)."""

    window: int = 36
    min_obs: int = 12

    def signal(self, prices, mask, **panels):
        from csmom_tpu.ops.rolling import rolling_std
        from csmom_tpu.signals.momentum import raw_monthly_returns

        ret, rvalid = raw_monthly_returns(prices, mask)
        vol, vvalid = rolling_std(
            ret, rvalid, self.window, min_periods=self.min_obs, ddof=1
        )
        return jnp.where(vvalid, -vol, jnp.nan), vvalid


@register_strategy("reversal")
@dataclasses.dataclass(frozen=True)
class Reversal(Strategy):
    """Short-term reversal: negative of the trailing ``lookback``-month
    return (Jegadeesh 1990's 1-month contrarian signal by default)."""

    lookback: int = 1
    skip: int = 0

    def signal(self, prices, mask, **panels):
        from csmom_tpu.signals.momentum import formation_listed_mask

        mom, valid = momentum(prices, mask, lookback=self.lookback, skip=self.skip)
        valid = valid & formation_listed_mask(mask, self.skip)
        return jnp.where(valid, -mom, jnp.nan), valid


@register_strategy("residual_momentum")
@dataclasses.dataclass(frozen=True)
class ResidualMomentum(Strategy):
    """Blitz–Huij–Martens (2011) idiosyncratic momentum: rank on the
    volatility-scaled mean of trailing market-model residuals instead of
    raw returns (see :mod:`csmom_tpu.signals.residual` for the closed-form
    rolling-OLS kernel).  Hedges the market-beta loading that raw momentum
    carries; the first valid score lands at month ``est_window + skip + 1``.
    """

    lookback: int = 12
    skip: int = 1
    est_window: int = 36
    scale_by_vol: bool = True

    def signal(self, prices, mask, **panels):
        return residual_momentum(
            prices, mask,
            lookback=self.lookback, skip=self.skip,
            est_window=self.est_window, scale_by_vol=self.scale_by_vol,
        )


@register_strategy("volume_z_momentum")
@dataclasses.dataclass(frozen=True)
class VolumeZMomentum(Strategy):
    """Momentum tilted by trailing volume — a one-score rendering of
    Lee–Swaminathan's finding that high-volume winners outperform
    (``LeSw00.pdf`` §III.B; the reference computes the turnover leg at
    ``features.py:60-107`` but never ranks on it).

    ``score = z(momentum) + gamma * z(mean trailing volume)`` with both
    legs z-scored per date; requires the engine to be given a ``volumes``
    panel (month-summed volume, as :func:`csmom_tpu.api.monthly_price_panel`
    produces).
    """

    lookback: int = 12
    skip: int = 1
    vol_lookback: int = 3
    gamma: float = 0.5

    def signal(self, prices, mask, *, volumes=None, volumes_mask=None, **panels):
        if volumes is None:
            raise ValueError("VolumeZMomentum needs a volumes= panel")
        from csmom_tpu.signals.momentum import formation_listed_mask

        mom, mom_valid = momentum(prices, mask, lookback=self.lookback, skip=self.skip)
        mom_valid = mom_valid & formation_listed_mask(mask, self.skip)
        mom = jnp.where(mom_valid, mom, jnp.nan)
        # fallback mask excludes zeros: segment-summed volume panels store
        # 0.0 (not NaN) at never-observed slots (see monthly_price_panel's
        # phantom-zero note), and a pre-listing zero must not enter the
        # trailing mean — pass volumes_mask to count true zero-volume months
        vm = (
            volumes_mask
            if volumes_mask is not None
            else jnp.isfinite(volumes) & (volumes > 0)
        )

        # trailing mean volume over vol_lookback months (all present)
        v = jnp.where(vm, jnp.nan_to_num(volumes), 0.0)
        csum = jnp.cumsum(v, axis=1)
        ccnt = jnp.cumsum(vm.astype(v.dtype), axis=1)
        L = self.vol_lookback
        prev = jnp.pad(csum, ((0, 0), (L, 0)))[:, : csum.shape[1]]
        prevc = jnp.pad(ccnt, ((0, 0), (L, 0)))[:, : ccnt.shape[1]]
        win_cnt = ccnt - prevc
        vol_avg = (csum - prev) / jnp.maximum(win_cnt, 1)
        vol_valid = win_cnt >= L

        valid = mom_valid & vol_valid
        score = xs_zscore(mom, valid) + self.gamma * xs_zscore(
            jnp.log1p(jnp.maximum(vol_avg, 0.0)), valid
        )
        return jnp.where(valid, score, jnp.nan), valid


@register_strategy("high_52w")
@dataclasses.dataclass(frozen=True)
class FiftyTwoWeekHigh(Strategy):
    """George–Hwang (2004) 52-week-high momentum: rank on nearness of the
    current price to its trailing high, ``P[t-skip] / max(P over the
    lookback window ending t-skip)`` — a score in (0, 1] that GH showed
    subsumes much of plain momentum's power.  On the monthly panel the
    12-month window is the 52-week high; validity requires the full
    window of PRICE observations, so the first valid score lands at
    month ``lookback + skip`` — one month earlier than momentum's
    ``lookback + skip + 1`` (momentum needs J *returns*, i.e. J+1
    prices; this ratio needs only J prices).

    Ranking-mode note: the score has an atom at exactly 1.0 (every name
    sitting at its high), so ``qcut``'s duplicate-edge dropping can
    empty the top decile on strong-market months and invalidate the
    spread there — GH rank on ordinals, and ``mode='rank'`` (ties by
    position) is the natural pairing for this signal."""

    lookback: int = 12
    skip: int = 1

    def signal(self, prices, mask, **panels):
        from csmom_tpu.ops.rolling import rolling_count

        _, M = prices.shape
        neg_inf = jnp.asarray(-jnp.inf, prices.dtype)
        p = jnp.where(mask, prices, neg_inf)

        def shift(x, s, fill):
            return jnp.pad(x, ((0, 0), (s, 0)), constant_values=fill)[:, :M]

        # rolling max has no prefix-sum form, so the window is a static
        # unroll of maxima; window VALIDITY reuses the shared prefix-sum
        # counter (one place owns the min_periods semantics)
        high = jnp.full_like(p, -jnp.inf)
        for s in range(self.skip, self.skip + self.lookback):
            high = jnp.maximum(high, shift(p, s, neg_inf))
        cnt = rolling_count(mask, self.lookback)
        allv = shift(cnt == self.lookback, self.skip, False)
        ps = shift(jnp.where(mask, prices, jnp.nan), self.skip, jnp.nan)
        valid = allv & (high > 0)
        score = ps / jnp.where(valid, high, 1.0)
        return jnp.where(valid, score, jnp.nan), valid


def parse_combo_spec(spec: str) -> tuple:
    """``"momentum:0.6,reversal:0.4"`` -> ((Momentum(), 0.6), (Reversal(), 0.4)).

    The CLI-facing constructor for :class:`ZScoreCombo` components: each
    comma-separated term is ``name[:weight]`` (weight defaults to 1.0),
    where ``name`` is any registered strategy instantiated with its
    defaults.  For parametrized components use the Python API.
    """
    from csmom_tpu.strategy.base import make_strategy

    out = []
    for term in spec.split(","):
        term = term.strip()
        if not term:
            continue
        name, _, w = term.partition(":")
        try:
            weight = float(w) if w else 1.0
        except ValueError:
            raise ValueError(
                f"combo term {term!r}: weight {w!r} is not a number"
            ) from None
        out.append((make_strategy(name.strip()), weight))
    if not out:
        raise ValueError(f"empty combo spec {spec!r}")
    return tuple(out)


@register_strategy("zscore_combo")
@dataclasses.dataclass(frozen=True)
class ZScoreCombo(Strategy):
    """Weighted sum of cross-sectionally z-scored component strategies.

    ``components`` is a tuple of ``(Strategy, weight)`` pairs (tuple so the
    combo stays hashable/jit-static), or a CLI-friendly string spec like
    ``"momentum:0.6,reversal:0.4"`` (parsed by :func:`parse_combo_spec` at
    construction).  A slot is valid only where every component is valid —
    matching how the reference's dropna would treat a multi-column signal
    frame.
    """

    components: tuple = ()

    def __post_init__(self):
        if isinstance(self.components, str):
            object.__setattr__(
                self, "components", parse_combo_spec(self.components)
            )

    @property
    def panel_names(self):
        """Panels any component consumes (the combo forwards ``**panels``)."""
        from csmom_tpu.strategy.base import consumed_panels

        names = set()
        for s, _w in self.components:
            names |= consumed_panels(s)
        return tuple(sorted(names))

    def signal(self, prices, mask, **panels):
        if not self.components:
            raise ValueError("ZScoreCombo needs at least one component")
        total = None
        valid = None
        outs = [
            (s.signal(prices, mask, **panels), w) for s, w in self.components
        ]
        for (score, v), _w in outs:
            valid = v if valid is None else (valid & v)
        for (score, v), w in outs:
            z = xs_zscore(jnp.where(valid, score, jnp.nan), valid)
            contrib = w * jnp.where(valid, z, 0.0)
            total = contrib if total is None else total + contrib
        return jnp.where(valid, total, jnp.nan), valid
