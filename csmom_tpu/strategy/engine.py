"""Strategy-generic monthly decile engine (both backends).

The engine tail — ranking, decile pooling, spread stats — is exactly the
one :func:`csmom_tpu.backtest.monthly_spread_backtest` uses; only the
signal production is delegated to the plugged-in :class:`Strategy`.  With
``strategy=Momentum(lookback=J, skip=s)`` the result is bit-identical to
the momentum engine (pinned by ``tests/test_strategy.py``), which is what
"lands behind the Strategy boundary, engines untouched" means.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np

from csmom_tpu.backtest.monthly import MonthlyResult, _assemble_result
from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.signals.momentum import monthly_returns
from csmom_tpu.strategy.base import Strategy

__all__ = ["strategy_backtest", "strategy_backtest_pandas"]


@partial(jax.jit, static_argnames=("strategy", "n_bins", "mode", "freq",
                                   "impl", "n_sectors"))
def strategy_backtest(
    prices,
    mask,
    strategy: Strategy,
    n_bins: int = 10,
    mode: str = "qcut",
    freq: int = 12,
    impl: str = "xla",
    sector_ids=None,
    n_sectors: int | None = None,
    **panels,
) -> MonthlyResult:
    """Monthly decile backtest of an arbitrary plugged-in strategy.

    Args:
      prices: f[A, M] month-end prices; mask: bool[A, M].
      strategy: hashable :class:`Strategy`; compiled once per instance.
      sector_ids / n_sectors: when given, the strategy's scores rank
        WITHIN each sector (``sector_decile_assign_panel``) and the
        pooled extreme bins form the legs — sector-neutral ranking for
        ANY plugged-in signal, the same labeler the built-in momentum
        sector engine uses.  ``sector_ids`` is i32[A]; negative ids are
        excluded from ranking.
      **panels: extra named panels forwarded to ``strategy.signal`` (e.g.
        ``volumes=``, ``volumes_mask=``).
    """
    ret, ret_valid = monthly_returns(prices, mask)
    score, valid = strategy.signal(prices, mask, **panels)
    if sector_ids is not None:
        from csmom_tpu.ops.ranking import sector_decile_assign_panel

        labels, _ = sector_decile_assign_panel(
            score, valid, sector_ids, n_sectors, n_bins=n_bins, mode=mode
        )
    else:
        labels, _ = decile_assign_panel(score, valid, n_bins=n_bins, mode=mode)
    return _assemble_result(ret, ret_valid, labels, n_bins, freq, impl=impl)


def strategy_backtest_pandas(
    prices_df,
    strategy: Strategy,
    n_bins: int = 10,
    freq: int = 12,
    **panels,
):
    """Pandas-engine run of the same plugged-in strategy.

    The strategy is defined once (as a JAX function); here its scores are
    evaluated eagerly and handed to the pandas ranking/portfolio tail
    (:func:`csmom_tpu.backends.pandas_engine.spread_from_scores_pandas`),
    so a single strategy definition serves both backends.

    Note: on panels with *interior* gaps, ``Momentum`` through this path
    uses calendar windows (NaN-poisoned, like the TPU engine), while the
    legacy no-strategy pandas path compounds over surviving rows
    (``_momentum_frame``) — identical on gap-free histories, and the
    strategy path is the documented semantics everywhere else.
    """
    import pandas as pd

    import jax.numpy as jnp

    from csmom_tpu.backends.pandas_engine import spread_from_scores_pandas

    values = prices_df.to_numpy(dtype=np.float64)
    mask = np.isfinite(values)
    score, valid = strategy.signal(
        jnp.asarray(values), jnp.asarray(mask), **{
            k: (jnp.asarray(v) if v is not None else None) for k, v in panels.items()
        }
    )
    score = np.where(np.asarray(valid), np.asarray(score), np.nan)
    score_df = pd.DataFrame(score, index=prices_df.index, columns=prices_df.columns)
    return spread_from_scores_pandas(prices_df, score_df, n_bins=n_bins, freq=freq)
