"""Live-panel streaming: ring buffer, watermark ingest, incremental
signals, and the event-time replay harness (ISSUE 7).

Import discipline mirrors ``serve/``: the data-plane modules (``ring``,
``ingest``, ``incremental``) are numpy/stdlib-only so the fast rehearse
tier and the plumbing tests never touch jax; the jitted reconcile
entries live behind the ``signals`` engines and are reached only by a
jax-engine replay.
"""

from csmom_tpu.stream.incremental import (
    IncrementalMomentum,
    IncrementalTurnover,
    full_momentum_np,
    full_turnover_np,
)
from csmom_tpu.stream.ingest import StreamIngestor, Tick, WatermarkPolicy
from csmom_tpu.stream.ring import LiveRing, RingSnapshot

__all__ = [
    "IncrementalMomentum",
    "IncrementalTurnover",
    "LiveRing",
    "RingSnapshot",
    "StreamIngestor",
    "Tick",
    "WatermarkPolicy",
    "full_momentum_np",
    "full_turnover_np",
]
