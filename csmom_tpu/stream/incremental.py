"""Incremental momentum/turnover: O(assets) per bar, exact by construction.

The batch engines (:mod:`csmom_tpu.signals.momentum`,
:mod:`csmom_tpu.signals.turnover`) recompute a full ``[A, T]`` panel per
call.  A live stream closes one bar at a time; recomputing T columns to
refresh the last one is O(A*T) of wasted work per tick.  These updaters
carry exactly the running state the last-column signal needs —
forward-filled prices, validity counts, cumulative turnover sums — and
advance it in O(A) per closed bar.

**Exactness is the contract, not a tolerance.**  Every arithmetic step
reproduces the reference recompute operation-for-operation (same
divides, same selects, same accumulation order), so the incremental
output after ANY interleaving of in-order ticks equals the full-panel
recompute bit-for-bit (``numpy`` mirrors below; pinned per-dtype by the
property tests in ``tests/test_stream.py``).  Late merges rewrite
history, which running sums cannot absorb exactly — the updater goes
``dirty`` and REBUILDS from the next snapshot instead of patching
(a patched float cumsum would drift bitwise; a rebuild replays the
exact mirror recurrence).  Integer counts (validity windows) use
add/subtract running sums — exact in integers; float accumulations (the
turnover cumsum) append-only in the same order as ``np.cumsum`` — a
bitwise-identical sequence of additions.

**Reconciliation** is the safety net the replay harness runs
periodically: recompute the full panel through the mirror, compare
bit-for-bit, and on ANY drift rebuild from scratch and count the event
— an incremental serving tier must prove it equals the batch tier, not
hope.  (The jax engines themselves are checked against the mirrors in
the test tier: the momentum mirror matches :func:`signals.momentum.
momentum` exactly — same elementwise IEEE ops; the turnover mirror
matches :func:`signals.turnover.turnover_features` to float-association
tolerance, because XLA's cumsum may associate differently than a
sequential sum.)

Time discipline: event time only — this module reads no clock of any
kind; bar identity comes from the caller's tick log.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "IncrementalMomentum",
    "IncrementalTurnover",
    "full_momentum_np",
    "full_turnover_np",
    "nan_equal",
]

TRADING_DAYS_PER_MONTH = 21.0  # signals.turnover's constant (features.py:79)


# ----------------------------------------------------------- full mirrors --
#
# numpy transcriptions of the jax engines, operation-for-operation.  These
# are the reconciliation references: sequential, deterministic, and (for
# momentum) bitwise-identical to the jitted engines on CPU because every
# step is an elementwise IEEE op with no reassociation freedom.

def _nan(dtype):
    return np.asarray(np.nan, dtype=dtype)


def padded_prices_np(prices: np.ndarray, mask: np.ndarray) -> tuple:
    """numpy mirror of :func:`signals.momentum.padded_prices`."""
    M = prices.shape[1]
    idx = np.arange(M)
    last = np.maximum.accumulate(np.where(mask, idx, -1), axis=1)
    seen = last >= 0
    filled = np.take_along_axis(
        np.where(mask, prices, _nan(prices.dtype)),
        np.clip(last, 0, M - 1), axis=1)
    return np.where(seen, filled, _nan(prices.dtype)), seen


def _ret_valid_np(prices: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Validity plane of :func:`signals.momentum.monthly_returns`."""
    filled, seen = padded_prices_np(prices, mask)
    prev = np.roll(filled, 1, axis=1)
    prev_seen = np.roll(seen, 1, axis=1)
    prev_seen[:, 0] = False
    with np.errstate(invalid="ignore"):
        return prev_seen & (prev != 0.0)


def full_momentum_np(prices: np.ndarray, mask: np.ndarray,
                     lookback: int = 12, skip: int = 1) -> tuple:
    """numpy mirror of :func:`signals.momentum.momentum` (full panel)."""
    prices = np.asarray(prices)
    mask = np.asarray(mask, bool)
    A, M = prices.shape
    ret_valid = _ret_valid_np(prices, mask)
    filled, _ = padded_prices_np(prices, mask)
    t = np.arange(M)
    hi = t - skip
    lo = t - skip - lookback
    in_range = lo >= 0
    bad = (~ret_valid).astype(np.int32)
    badc = np.concatenate(
        [np.zeros((A, 1), np.int32), np.cumsum(bad, axis=1)], axis=1)
    hi_c = np.clip(hi, 0, M - 1)
    lo_c = np.clip(lo + 1, 0, M - 1)
    window_bad = badc[:, hi_c + 1] - badc[:, lo_c]
    p_hi = filled[:, hi_c]
    p_lo = filled[:, np.clip(lo, 0, M - 1)]
    with np.errstate(invalid="ignore"):
        valid = in_range[None, :] & (window_bad == 0) & (p_lo != 0.0)
        one = np.asarray(1.0, dtype=prices.dtype)
        mom = np.where(
            valid, p_hi / np.where(valid, p_lo, one) - one,
            _nan(prices.dtype))
    return mom, valid


def full_turnover_np(volume: np.ndarray, vmask: np.ndarray,
                     shares: np.ndarray, lookback: int = 3) -> tuple:
    """numpy mirror of ``signals.turnover.turnover_features``'s
    ``turn_avg`` leg (adv -> turnover -> trailing NaN-skipping mean).

    The rolling mean uses SEQUENTIAL prefix sums (``np.cumsum``), which
    is the accumulation order the incremental updater reproduces exactly
    — the jitted engine's XLA cumsum may associate differently, so
    engine parity is a tolerance check, mirror parity is bitwise.
    """
    volume = np.asarray(volume)
    vmask = np.asarray(vmask, bool)
    dtype = volume.dtype
    so = np.asarray(shares, dtype=dtype)[:, None]
    with np.errstate(invalid="ignore"):
        adv = volume / np.asarray(TRADING_DAYS_PER_MONTH, dtype=dtype)
        so_ok = np.isfinite(so) & (so > 0)
        turn_valid = vmask & so_ok
        one = np.asarray(1.0, dtype=dtype)
        turn = np.where(turn_valid,
                        adv / np.where(so_ok, so, one), _nan(dtype))
        filled = np.where(turn_valid, np.nan_to_num(turn), 0.0).astype(dtype)
    A, M = filled.shape
    cs = np.concatenate(
        [np.zeros((A, 1), dtype), np.cumsum(filled, axis=1)], axis=1)
    cn = np.concatenate(
        [np.zeros((A, 1), dtype),
         np.cumsum(turn_valid.astype(dtype), axis=1)], axis=1)
    lo = np.maximum(np.arange(M) + 1 - lookback, 0)
    s = cs[:, 1:] - cs[:, lo]
    n = cn[:, 1:] - cn[:, lo]
    out_valid = n >= 1
    with np.errstate(invalid="ignore"):
        mean = s / np.maximum(n, one)
        out = np.where(out_valid, mean, _nan(dtype))
    return out, out_valid


def nan_equal(a: np.ndarray, b: np.ndarray) -> bool:
    """Bitwise-for-values equality with NaN == NaN (the reconciliation
    comparison: same dtype, same values, same NaN pattern)."""
    a = np.asarray(a)
    b = np.asarray(b)
    return (a.dtype == b.dtype and a.shape == b.shape
            and bool(np.array_equal(a, b, equal_nan=True)))


# ------------------------------------------------------ incremental state --

class _UpdaterBase:
    """Shared consume/rebuild/reconcile plumbing.

    ``anchor`` is the global bar index the running prefix state is
    anchored at: 0 for a fresh updater, the snapshot's window start
    after any rebuild.  Reconciliation is only bitwise-comparable when
    the reference recompute shares that anchor — once the ring's window
    slides past it (bar count exceeds ring capacity), a window-anchored
    recompute legitimately differs from the live state: its forward
    fills start blind at the window edge and its float prefix sums
    carry no common prefix to cancel.  The pre-fix reconcile compared
    them anyway and reported spurious drift (ROADMAP item 4 defect (a),
    masked by ``run_replay`` pinning capacity == bars); the fix
    re-anchors first (counted in ``reanchors``) and cross-checks the
    live state against the re-anchored one within a documented
    float-cancellation tolerance, so real corruption still surfaces as
    drift while anchor mismatch alone never does."""

    def __init__(self, n_assets: int, dtype):
        self.n_assets = int(n_assets)
        self.dtype = np.dtype(dtype)
        self.consumed = 0          # bars consumed (global index of next)
        self.anchor = 0            # global bar the prefix state starts at
        self.dirty = False         # a late merge rewrote consumed history
        self.rebuilds = 0
        self.reanchors = 0         # window slid past anchor at reconcile
        self.reconciliations = 0
        self.drift_events = 0

    def mark_dirty(self) -> None:
        """A consumed bar changed under us (late merge): running state no
        longer describes the panel — rebuild at the next sync point."""
        self.dirty = True

    # subclasses: _reset(), _consume(values_col, mask_col), _reference(snapshot)

    def update(self, values_col: np.ndarray, mask_col: np.ndarray) -> None:
        """Consume one closed bar column (O(A)).  Skipped while dirty —
        the pending rebuild replays everything exactly."""
        if self.dirty:
            self.consumed += 1  # the bar exists; rebuild will cover it
            return
        self._consume(np.asarray(values_col, self.dtype),
                      np.asarray(mask_col, bool))
        self.consumed += 1

    def sync(self, snapshot) -> None:
        """Bring state level with ``snapshot``: rebuild if dirty OR if
        the ring window has moved past the consumed frontier (bars were
        evicted unseen — the forward-fill carry would silently skip
        them), else consume any not-yet-consumed closed bars."""
        if self.dirty or snapshot.first_bar_index > self.consumed:
            self.rebuild(snapshot)
            return
        end = snapshot.first_bar_index + snapshot.n_bars
        v, m = self._snapshot_field(snapshot)
        for g in range(max(self.consumed, snapshot.first_bar_index), end):
            j = g - snapshot.first_bar_index
            self._consume(np.asarray(v[:, j], self.dtype), m[:, j])
            self.consumed = g + 1

    def rebuild(self, snapshot) -> None:
        """Replay the exact mirror recurrence over the snapshot window —
        the rebuild-from-scratch path late merges and detected drift
        both take.  Re-anchors the prefix state at the window start."""
        self._reset()
        self.anchor = snapshot.first_bar_index
        self.consumed = snapshot.first_bar_index
        self.dirty = False
        self.rebuilds += 1
        self.sync(snapshot)

    def _cross_atol(self) -> np.ndarray | float:
        """Per-asset absolute tolerance for the live-vs-re-anchored
        cross-check.  0.0 where the last-bar value depends only on
        in-window data (momentum: identical forward fills wherever the
        window recompute is valid, so bitwise); overridden where the
        state carries globally-anchored float prefix sums whose common
        prefix cancels only in exact arithmetic (turnover)."""
        return 0.0

    def reconcile(self, snapshot) -> dict:
        """Full-panel recompute vs the running state.  On drift: count
        it and rebuild from scratch.  Returns the verdict.

        Anchored case (window still starts at our anchor): bitwise, as
        ever.  Slid-window case: capture the live last-bar state, then
        REBUILD from the snapshot (a re-anchor, counted — not drift:
        the anchors differing is the ring doing its job) and compare
        (a) the re-anchored incremental recurrence against the
        vectorized mirror bitwise, and (b) the live state against the
        re-anchored one on lanes both call valid, within
        :meth:`_cross_atol` — (a) proves the recurrence, (b) catches
        real corruption of the long-running state."""
        self.sync(snapshot)
        self.reconciliations += 1
        reanchored = snapshot.first_bar_index != self.anchor
        live_val = live_ok = None
        atol = 0.0
        if reanchored:
            live_val, live_ok = self.current()
            atol = self._cross_atol()
            self.reanchors += 1
            self.rebuild(snapshot)
        ref_val, ref_ok = self._reference(snapshot)
        cur_val, cur_ok = self.current()
        drift = not (nan_equal(cur_val, ref_val[:, -1])
                     and bool(np.array_equal(cur_ok, ref_ok[:, -1])))
        if reanchored and not drift:
            both = live_ok & cur_ok
            if both.any():
                diff = np.abs(live_val[both] - cur_val[both])
                tol = np.broadcast_to(np.asarray(atol), live_ok.shape)[both]
                if not bool(np.all(diff <= tol)):
                    drift = True
        if drift:
            self.drift_events += 1
            if not reanchored:
                self.rebuild(snapshot)  # re-anchored path already rebuilt
        return {"drift": drift, "bars": snapshot.n_bars,
                "version": snapshot.version, "reanchored": reanchored}

    def stats(self) -> dict:
        return {
            "consumed_bars": self.consumed,
            "rebuilds": self.rebuilds,
            "reanchors": self.reanchors,
            "reconciliations": self.reconciliations,
            "drift_events": self.drift_events,
        }


class IncrementalMomentum(_UpdaterBase):
    """Running (J, skip) compounded momentum at the latest closed bar.

    State per asset: the forward-filled price carry, the seen flag, a
    ``(lookback + skip + 1)``-deep ring of filled prices, a matching
    ring of per-return badness bits, and an integer running sum of
    badness over the formation window — add the entering return,
    subtract the leaving one, exact in integers.
    """

    def __init__(self, n_assets: int, lookback: int = 12, skip: int = 1,
                 dtype=np.float64, field: str = "price"):
        super().__init__(n_assets, dtype)
        if lookback < 1 or skip < 0:
            raise ValueError("need lookback >= 1, skip >= 0")
        self.lookback = int(lookback)
        self.skip = int(skip)
        self.field = field
        self._W = self.lookback + self.skip + 1   # filled-price ring depth
        self._reset()

    def _reset(self) -> None:
        A, W = self.n_assets, self._W
        self._filled = np.full(A, np.nan, self.dtype)   # carry
        self._seen = np.zeros(A, bool)
        self._filled_ring = np.full((A, W), np.nan, self.dtype)
        self._bad_ring = np.ones((A, W), np.int32)      # return-badness bits
        self._bad_sum = np.full(A, self.lookback, np.int32)
        self._t = 0                                     # bars consumed here
        self._mom = np.full(A, np.nan, self.dtype)
        self._ok = np.zeros(A, bool)

    def _snapshot_field(self, snapshot):
        return snapshot.values[self.field], snapshot.mask[self.field]

    def _consume(self, values_col: np.ndarray, mask_col: np.ndarray) -> None:
        t = self._t
        W = self._W
        # return at index t (vs t-1): valid iff seen-before and carry != 0
        with np.errstate(invalid="ignore"):
            ret_ok = self._seen & (self._filled != 0.0)
        bad = (~ret_ok).astype(np.int32)  # t == 0 is all-bad, like the mirror
        new_filled = np.where(mask_col, values_col, self._filled)
        self._seen = self._seen | mask_col
        self._filled = new_filled

        # running badness over returns (t-skip-lookback, t-skip]: the
        # entering return is index t-skip, the leaving one t-skip-lookback
        col = t % W
        self._filled_ring[:, col] = new_filled
        self._bad_ring[:, col] = bad
        ent = t - self.skip
        lev = t - self.skip - self.lookback
        self._bad_sum += self._ring_bad(ent) - self._ring_bad(lev)

        hi = t - self.skip
        lo = t - self.skip - self.lookback
        if lo < 0:
            self._mom = np.full(self.n_assets, np.nan, self.dtype)
            self._ok = np.zeros(self.n_assets, bool)
        else:
            p_hi = self._ring_filled(hi)
            p_lo = self._ring_filled(lo)
            with np.errstate(invalid="ignore"):
                valid = (self._bad_sum == 0) & (p_lo != 0.0)
                one = np.asarray(1.0, dtype=self.dtype)
                self._mom = np.where(
                    valid, p_hi / np.where(valid, p_lo, one) - one,
                    _nan(self.dtype))
            self._ok = valid
        self._t = t + 1

    def _ring_bad(self, idx: int) -> np.ndarray:
        if idx < 0:
            # pre-history returns are bad by definition (the mirror's
            # leading pct_change NaN); they only enter the running sum
            # while the window is still partly before bar 0, where the
            # signal is invalid anyway — the constant keeps the sum
            # aligned so it is exact the instant the window materializes
            return np.ones(self.n_assets, np.int32)
        return self._bad_ring[:, idx % self._W]

    def _ring_filled(self, idx: int) -> np.ndarray:
        return self._filled_ring[:, idx % self._W]

    def _reference(self, snapshot) -> tuple:
        v, m = self._snapshot_field(snapshot)
        return full_momentum_np(np.asarray(v, self.dtype), m,
                                self.lookback, self.skip)

    def current(self) -> tuple:
        """(mom[A], valid[A]) at the latest consumed bar."""
        return self._mom.copy(), self._ok.copy()


class IncrementalTurnover(_UpdaterBase):
    """Running trailing-``lookback`` turnover mean at the latest bar.

    State per asset: the cumulative sum of filled turnover values and
    the cumulative valid count, appended in the SAME order as
    ``np.cumsum`` (bitwise-identical float sequence), plus a
    ``lookback``-deep ring of past cumulative values for the window's
    left edge — the trailing sum is two reads and a subtract, exactly
    the prefix-difference the mirror computes.
    """

    def __init__(self, n_assets: int, shares, lookback: int = 3,
                 dtype=np.float64, field: str = "volume"):
        super().__init__(n_assets, dtype)
        if lookback < 1:
            raise ValueError("need lookback >= 1")
        self.lookback = int(lookback)
        self.field = field
        self._shares = np.asarray(shares, dtype=self.dtype)
        if self._shares.shape != (self.n_assets,):
            raise ValueError(
                f"shares must be [A]={self.n_assets}, got "
                f"{self._shares.shape}")
        self._reset()

    def _reset(self) -> None:
        A, L = self.n_assets, self.lookback
        self._cs = np.zeros(A, self.dtype)       # cumulative filled sum
        self._cn = np.zeros(A, self.dtype)       # cumulative valid count
        self._cs_ring = np.zeros((A, L + 1), self.dtype)
        self._cn_ring = np.zeros((A, L + 1), self.dtype)
        self._t = 0
        self._avg = np.full(A, np.nan, self.dtype)
        self._ok = np.zeros(A, bool)

    def _snapshot_field(self, snapshot):
        return snapshot.values[self.field], snapshot.mask[self.field]

    def _cross_atol(self):
        """The trailing mean is a difference of globally-anchored float
        prefix sums; re-anchoring drops the common prefix, which cancels
        exactly only in exact arithmetic.  Bound the float residue by a
        few ulps of the prefix magnitude per asset — generous enough to
        never flag the anchor change, tight enough that genuine state
        corruption (which is O(signal), not O(ulp)) still reads as
        drift."""
        eps = np.finfo(self.dtype).eps
        return 32.0 * eps * (np.abs(self._cs) + 1.0)

    def _consume(self, values_col: np.ndarray, mask_col: np.ndarray) -> None:
        t = self._t
        L = self.lookback
        so = self._shares
        with np.errstate(invalid="ignore"):
            adv = values_col / np.asarray(TRADING_DAYS_PER_MONTH,
                                          dtype=self.dtype)
            so_ok = np.isfinite(so) & (so > 0)
            valid = mask_col & so_ok
            one = np.asarray(1.0, dtype=self.dtype)
            turn = np.where(valid, adv / np.where(so_ok, so, one),
                            _nan(self.dtype))
            filled = np.where(valid, np.nan_to_num(turn),
                              0.0).astype(self.dtype)
        # cumulative state at prefix index t (BEFORE adding this column)
        # parks in the ring so the window's left edge c[t+1-L] stays
        # readable; the additions below are the np.cumsum order exactly
        self._cs_ring[:, t % (L + 1)] = self._cs
        self._cn_ring[:, t % (L + 1)] = self._cn
        self._cs = self._cs + filled
        self._cn = self._cn + valid.astype(self.dtype)
        lo = max(t + 1 - L, 0)
        cs_lo = self._cs_ring[:, lo % (L + 1)] if t + 1 - L > 0 \
            else np.zeros(self.n_assets, self.dtype)
        cn_lo = self._cn_ring[:, lo % (L + 1)] if t + 1 - L > 0 \
            else np.zeros(self.n_assets, self.dtype)
        s = self._cs - cs_lo
        n = self._cn - cn_lo
        out_valid = n >= 1
        with np.errstate(invalid="ignore"):
            one = np.asarray(1.0, dtype=self.dtype)
            mean = s / np.maximum(n, one)
            self._avg = np.where(out_valid, mean, _nan(self.dtype))
        self._ok = out_valid
        self._t = t + 1

    def _reference(self, snapshot) -> tuple:
        v, m = self._snapshot_field(snapshot)
        return full_turnover_np(np.asarray(v, self.dtype), m,
                                self._shares, self.lookback)

    def current(self) -> tuple:
        """(turn_avg[A], valid[A]) at the latest consumed bar."""
        return self._avg.copy(), self._ok.copy()
