"""Streaming ingestion: event-time watermarks over the live ring.

Real tick streams arrive late, out of order, duplicated, and gapped.
This front-end is where each of those degradations becomes a COUNTED,
bounded behavior instead of silent data corruption:

- **Watermark**: event time only (the tick log's bar stamps — this
  module reads no clock).  The watermark trails the newest bar time
  seen by ``allowed_lateness_bars`` bar periods.  A tick at or above
  the watermark is live data; one below it is too old to change
  anything we may already have served — it is QUARANTINED (kept, with
  its reason, up to a bound) and counted, never merged.
- **Late merge**: a tick for a past bar that is still above the
  watermark merges in place — the cell is written and the ring version
  bumps, so every consumer can see the panel changed under them (the
  incremental updaters rebuild their window state off exactly this
  signal).
- **Dedupe**: ticks are idempotent by ``(asset, bar_time)`` — the first
  write wins, repeats count as ``deduped`` and change nothing.  Dedupe
  state is pruned as the watermark passes (a bar below the watermark
  can never be written again, so its keys are dead weight; a duplicate
  arriving that late quarantines first anyway).
- **Gaps**: a tick that jumps the bar grid materializes the skipped
  bars as masked, NaN, ``stale``-flagged columns — the panel records
  "missing", it never carries the last price into a hole.

Closed accounting is the contract the replay artifact schema enforces::

    applied + merged_late + quarantined + deduped == offered

Every offered tick lands in exactly one bucket; nothing the stream ever
handed us can vanish from the ledger (the serve queue's closed-books
rule, one layer down the pipeline).
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

from csmom_tpu.chaos.inject import checkpoint
from csmom_tpu.stream.ring import LiveRing

__all__ = ["StreamIngestor", "Tick", "WatermarkPolicy"]


@dataclasses.dataclass(frozen=True)
class Tick:
    """One bar tick: event-time stamped, per-asset, per-bar values.

    ``bar_time`` is int64 epoch-ns aligned to the bar grid; ``seq`` is
    the feed's arrival sequence number (provenance only — ordering
    decisions use event time, never arrival order).
    """

    asset: str
    bar_time: int
    price: float
    volume: float = float("nan")
    seq: int = -1


@dataclasses.dataclass(frozen=True)
class WatermarkPolicy:
    """Event-time lateness policy, in whole bars.

    ``allowed_lateness_bars = L`` means: once bar ``t`` has been seen,
    ticks for bars older than ``t - L`` periods are quarantined.  L = 0
    quarantines everything out of order; the replay default keeps a few
    bars of grace, which is what real consolidated feeds need.
    """

    bar_period_ns: int
    allowed_lateness_bars: int = 2

    def __post_init__(self):
        if self.bar_period_ns <= 0:
            raise ValueError("bar_period_ns must be positive")
        if self.allowed_lateness_bars < 0:
            raise ValueError("allowed_lateness_bars must be >= 0")

    def watermark(self, max_bar_time: int) -> int:
        """Oldest bar time still writable given the newest seen."""
        return max_bar_time - self.allowed_lateness_bars * self.bar_period_ns


class StreamIngestor:
    """Applies the watermark policy between a tick feed and a LiveRing."""

    # outcome names double as accounting keys (closed-world)
    OUTCOMES = ("applied", "merged_late", "quarantined", "deduped")

    def __init__(self, ring: LiveRing, policy: WatermarkPolicy,
                 quarantine_keep: int = 256):
        self.ring = ring
        self.policy = policy
        self.offered = 0
        self.applied = 0
        self.merged_late = 0
        self.quarantined = 0
        self.deduped = 0
        self.gap_bars = 0             # columns materialized as stale holes
        self.merge_version_bumps = 0  # ring versions spent on late merges
        self._max_bar_time: int | None = None
        # (bar_time -> set of assets written) — pruned below the watermark
        self._seen: dict = {}
        self._bar_index_of: dict = {}  # bar_time -> global bar index
        self.quarantine = deque(maxlen=max(1, quarantine_keep))

    # ------------------------------------------------------------ ingest --

    def offer(self, tick: Tick) -> str:
        """Ingest one tick; returns its outcome (one of ``OUTCOMES``)."""
        self.offered += 1
        checkpoint("stream.ingest", asset=tick.asset, seq=tick.seq)
        bar_time = int(tick.bar_time)

        if not np.isfinite(tick.price):
            # a non-finite price is rejected data, not data (ROADMAP
            # item 4 defect (b)): it must NOT advance the bar grid, and
            # above all must NOT mark the (asset, bar) cell seen — the
            # ring's mask would stay False (write() masks on finiteness)
            # while the dedupe state claimed the cell was filled, so the
            # later REAL tick would be counted `deduped` and the cell
            # would stay unfilled forever with the books still
            # balancing.  Quarantine keeps the ledger closed and the
            # reason auditable; dedupe state is untouched.
            self.quarantined += 1
            self.quarantine.append({
                "asset": tick.asset, "bar_time": bar_time,
                "seq": tick.seq,
                "reason": f"non-finite price {tick.price!r}",
            })
            return "quarantined"

        if self._max_bar_time is not None:
            wm = self.policy.watermark(self._max_bar_time)
            if bar_time < wm:
                self.quarantined += 1
                self.quarantine.append({
                    "asset": tick.asset, "bar_time": bar_time,
                    "seq": tick.seq,
                    "reason": f"below watermark by "
                              f"{(wm - bar_time) // self.policy.bar_period_ns}"
                              " bar(s)",
                })
                return "quarantined"

        key_assets = self._seen.get(bar_time)
        if key_assets is not None and tick.asset in key_assets:
            self.deduped += 1
            return "deduped"

        if self._max_bar_time is None or bar_time > self._max_bar_time:
            self._advance_to(bar_time)
            outcome = "applied"
        elif bar_time == self._max_bar_time:
            outcome = "applied"
        else:
            outcome = "merged_late"

        idx = self._bar_index_of.get(bar_time)
        if idx is None or not self.ring.in_window(idx):
            # the bar left the window (capacity wrap) between watermark
            # check and here — an edge only tiny rings can reach; the
            # honest outcome is quarantine, not a write into a reused column
            self.quarantined += 1
            self.quarantine.append({
                "asset": tick.asset, "bar_time": bar_time, "seq": tick.seq,
                "reason": "bar evicted from the ring window",
            })
            return "quarantined"

        v0 = self.ring.version
        self.ring.write("price", tick.asset, idx, float(tick.price))
        if "volume" in self.ring.fields and np.isfinite(tick.volume):
            self.ring.write("volume", tick.asset, idx, float(tick.volume))
        self._seen.setdefault(bar_time, set()).add(tick.asset)

        if outcome == "merged_late":
            self.merged_late += 1
            self.merge_version_bumps += self.ring.version - v0
        else:
            self.applied += 1
        return outcome

    def _advance_to(self, bar_time: int) -> None:
        """Materialize the bar grid up to ``bar_time``; skipped bars are
        stale holes, and dedupe state below the new watermark is pruned."""
        period = self.policy.bar_period_ns
        if self._max_bar_time is None:
            idx = self.ring.append_bar(bar_time)
            self._bar_index_of[bar_time] = idx
        else:
            t = self._max_bar_time + period
            while t < bar_time:
                idx = self.ring.append_bar(t, stale=True)
                self._bar_index_of[t] = idx
                self.gap_bars += 1
                t += period
            idx = self.ring.append_bar(bar_time)
            self._bar_index_of[bar_time] = idx
        self._max_bar_time = bar_time
        wm = self.policy.watermark(bar_time)
        for bt in [bt for bt in self._seen if bt < wm]:
            del self._seen[bt]
        for bt in [bt for bt in self._bar_index_of if bt < wm]:
            del self._bar_index_of[bt]

    # -------------------------------------------------------- accounting --

    @property
    def version(self) -> int:
        return self.ring.version

    @property
    def watermark_ns(self) -> int | None:
        if self._max_bar_time is None:
            return None
        return self.policy.watermark(self._max_bar_time)

    def accounting(self) -> dict:
        return {
            "offered": self.offered,
            "applied": self.applied,
            "merged_late": self.merged_late,
            "quarantined": self.quarantined,
            "deduped": self.deduped,
            "gap_bars": self.gap_bars,
            "merge_version_bumps": self.merge_version_bumps,
        }

    def invariant_violations(self) -> list:
        """The closed tick book (empty = holds)."""
        a = self.accounting()
        total = (a["applied"] + a["merged_late"] + a["quarantined"]
                 + a["deduped"])
        if total != a["offered"]:
            return [
                f"tick accounting broken: applied {a['applied']} + "
                f"merged_late {a['merged_late']} + quarantined "
                f"{a['quarantined']} + deduped {a['deduped']} = {total} "
                f"!= offered {a['offered']}"
            ]
        return []
